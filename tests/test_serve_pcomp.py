"""Serve-plane P-compositional splitting (ISSUE 9): long decomposable
request histories fan out as per-key sub-lanes riding the PROJECTED
spec's micro-batches, verdicts recombine bit-identically to the direct
decomposed path, per-sub-history cache rows make a one-key change
re-check exactly one key, and the split rides the worker pool."""

import dataclasses

import pytest

from qsm_tpu.models import AtomicKvSUT, KvSpec, StaleCacheKvSUT
from qsm_tpu.ops.pcomp import PComp
from qsm_tpu.serve import CheckClient, CheckServer
from qsm_tpu.serve.protocol import VERDICT_NAMES
from qsm_tpu.utils.corpus import build_corpus

KW = {"n_keys": 8, "n_values": 4}


def _spec():
    return KvSpec(**KW)


def _corpus(n=6, ops=96, seed=5):
    spec = _spec()
    return spec, build_corpus(
        spec, (AtomicKvSUT, StaleCacheKvSUT), n=n, n_pids=16,
        max_ops=ops, seed_base=seed, seed_prefix="serve_pc")


def _expected(spec, hists):
    ref = PComp(spec).check_histories(spec, hists)
    return [VERDICT_NAMES[int(v)] for v in ref]


@pytest.fixture()
def server(tmp_path):
    srv = CheckServer(flush_s=0.005, max_lanes=64,
                      cache_path=str(tmp_path / "bank.jsonl")).start()
    yield srv
    srv.stop()


def test_served_split_matches_direct_decomposed(server):
    spec, hists = _corpus()
    want = _expected(spec, hists)
    with CheckClient(server.address, timeout_s=120) as c:
        res = c.check("kv", hists, spec_kwargs=KW, deadline_s=90)
        assert res["ok"], res
        assert res["verdicts"] == want
        st = c.stats()["stats"]
    assert st["pcomp"]["enabled"]
    assert st["pcomp"]["split"] == len(hists)
    assert st["pcomp"]["sub_lanes"] > len(hists)
    # the batch stamps say these lanes came from decomposition
    assert any(b.get("pcomp_lanes") for b in res["batches"])
    # and they rode the PROJECTED spec's group
    assert any(b.get("model") == "register" for b in res["batches"])


def test_whole_history_key_banks_and_serves_duplicates(server):
    spec, hists = _corpus(n=4)
    want = _expected(spec, hists)
    with CheckClient(server.address, timeout_s=120) as c:
        r1 = c.check("kv", hists, spec_kwargs=KW, deadline_s=90)
        assert r1["verdicts"] == want
        r2 = c.check("kv", hists, spec_kwargs=KW, deadline_s=90)
    assert r2["verdicts"] == want
    assert all(r2["cached"]), r2["cached"]


def test_one_key_change_rechecks_one_key(server):
    spec, hists = _corpus(n=2)
    h = hists[0]
    with CheckClient(server.address, timeout_s=120) as c:
        c.check("kv", [h], spec_kwargs=KW, deadline_s=90)
        st1 = c.stats()["stats"]["pcomp"]
        # flip one PUT's value (same key): every other key's sub-history
        # fingerprint is unchanged
        ops = list(h.ops)
        for j, op in enumerate(ops):
            if op.cmd == 1:
                ops[j] = dataclasses.replace(
                    op, arg=(op.arg - op.arg % 4) + ((op.arg % 4) + 1) % 4)
                break
        from qsm_tpu.core.history import History

        res = c.check("kv", [History(ops)], spec_kwargs=KW, deadline_s=90)
        assert res["ok"]
        st2 = c.stats()["stats"]["pcomp"]
    subs = st2["sub_lanes"] - st1["sub_lanes"]
    hits = st2["sub_cache_hits"] - st1["sub_cache_hits"]
    assert subs > 1
    assert subs - hits == 1, (subs, hits)  # exactly the touched key


def test_short_histories_check_whole(server):
    """No gain, no split: sub and whole land in the same bucket."""
    spec = _spec()
    hists = build_corpus(spec, (AtomicKvSUT,), n=4, n_pids=2, max_ops=8,
                         seed_base=9, seed_prefix="short")
    with CheckClient(server.address, timeout_s=60) as c:
        res = c.check("kv", hists, spec_kwargs=KW, deadline_s=45)
        assert res["ok"]
        st = c.stats()["stats"]["pcomp"]
    assert st["split"] == 0


def test_no_pcomp_flag_serves_whole(tmp_path):
    """pcomp=False: decomposable 64-op histories (native-checkable
    whole) must NOT split."""
    spec, hists = _corpus(n=4, ops=64)
    want = _expected(spec, hists)
    srv = CheckServer(flush_s=0.005, max_lanes=16, pcomp=False).start()
    try:
        with CheckClient(srv.address, timeout_s=120) as c:
            res = c.check("kv", hists, spec_kwargs=KW, deadline_s=90)
            assert res["ok"]
            assert res["verdicts"] == want
            st = c.stats()["stats"]["pcomp"]
        assert not st["enabled"]
        assert st["split"] == 0 and st["sub_lanes"] == 0
    finally:
        srv.stop()


def test_served_witness_is_stitched_and_verifies(server):
    from qsm_tpu.ops.backend import Verdict, verify_witness

    spec, hists = _corpus(n=3)
    with CheckClient(server.address, timeout_s=180) as c:
        res = c.check("kv", hists, spec_kwargs=KW, witness=True,
                      deadline_s=150)
        st = c.stats()["stats"]["pcomp"]
    assert res["ok"], res
    # the witness path decomposes too (per-key searches + stitch)
    assert st["split"] >= 1
    n_ok = 0
    for h, name, w in zip(hists, res["verdicts"], res["witnesses"]):
        if name == VERDICT_NAMES[int(Verdict.LINEARIZABLE)]:
            assert w is not None
            assert verify_witness(spec, h, [tuple(p) for p in w])
            n_ok += 1
    assert n_ok, "witness sample vacuous"


def test_split_lanes_ride_the_worker_pool(tmp_path):
    spec, hists = _corpus(n=4)
    want = _expected(spec, hists)
    srv = CheckServer(flush_s=0.005, max_lanes=32, workers=2,
                      cache_path=str(tmp_path / "bank.jsonl")).start()
    try:
        with CheckClient(srv.address, timeout_s=180) as c:
            res = c.check("kv", hists, spec_kwargs=KW, deadline_s=150)
            assert res["ok"], res
            assert res["verdicts"] == want
            st = c.stats()["stats"]
        assert st["pcomp"]["split"] == len(hists)
        pool = st["pool"]
        assert sum(w.get("dispatches", 0)
                   for w in pool.get("workers", [])) > 0, pool
    finally:
        srv.stop()
