"""PallasTPU prototype (ops/pallas_kernel.py) — parity with the oracle
and the XLA kernel on the scalar-table fast path.

Interpret mode (the CPU platform has no Mosaic compiler) is slow, so
corpora are tiny and budgets capped; the kernel's real A/B against the
XLA while-loop runs in tools/bench_scale.py's ``pallas`` variant cell
when a real-TPU window opens (VERDICT.md round 4, "Next round" #4)."""

from __future__ import annotations

import numpy as np
import pytest

from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.ops.backend import Verdict, verify_witness
from qsm_tpu.ops.jax_kernel import JaxTPU
from qsm_tpu.ops.pallas_kernel import PallasTPU
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.utils.corpus import build_corpus


def _tight(spec, **kw):
    """Interpret-mode-sized backend: small chunked budget, no rescue."""
    return PallasTPU(spec, budget=4_000, mid_budget=0, rescue_budget=0,
                     **kw)


@pytest.fixture(scope="module")
def cas_corpus():
    spec = CasSpec()
    return spec, build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=16,
                              n_pids=4, max_ops=12, seed_base=77,
                              seed_prefix="pal")


def test_pallas_parity_vs_oracle(cas_corpus):
    spec, corpus = cas_corpus
    memo = WingGongCPU(memo=True)
    mv = np.asarray(memo.check_histories(spec, corpus))
    pv = np.asarray(_tight(spec).check_histories(spec, corpus))
    both = (mv != 2) & (pv != 2)
    assert int(((mv != pv) & both).sum()) == 0
    assert int((pv == 2).sum()) == 0  # this corpus decides within budget


def test_pallas_matches_jax_kernel_verdicts(cas_corpus):
    spec, corpus = cas_corpus
    jx = JaxTPU(spec, budget=4_000, mid_budget=0, rescue_budget=0)
    jv = np.asarray(jx.check_histories(spec, corpus))
    pv = np.asarray(_tight(spec).check_histories(spec, corpus))
    assert jv.tolist() == pv.tolist()


def test_pallas_budget_is_honest(cas_corpus):
    """A tiny budget must yield BUDGET_EXCEEDED, never a guess."""
    spec, corpus = cas_corpus
    p = PallasTPU(spec, budget=3, mid_budget=0, rescue_budget=0)
    p.PALLAS_CHUNK = 4
    pv = np.asarray(p.check_histories(spec, corpus))
    memo = WingGongCPU(memo=True)
    mv = np.asarray(memo.check_histories(spec, corpus))
    both = (mv != 2) & (pv != 2)
    assert int(((mv != pv) & both).sum()) == 0
    assert int((pv == 2).sum()) > 0  # some lanes must hit the budget


def test_pallas_witness_replays(cas_corpus):
    spec, corpus = cas_corpus
    p = _tight(spec)
    lin = next(h for h in corpus
               if Verdict(int(p.check_histories(spec, [h])[0]))
               == Verdict.LINEARIZABLE)
    v, wit = p.check_witness(spec, lin)
    assert v == Verdict.LINEARIZABLE and wit is not None
    assert verify_witness(spec, lin, wit)


def test_pallas_cache_prunes_without_changing_verdicts(cas_corpus):
    """The per-lane VMEM memo cache is pruning-only: identical verdicts
    with fewer chunk calls (the violating history's exhaustive search is
    where it bites — same contract as the XLA kernel's cache)."""
    spec, corpus = cas_corpus
    out = {}
    for slots in (0, 64):
        p = PallasTPU(spec, budget=50_000, mid_budget=0, rescue_budget=0)
        p.PALLAS_CACHE_SLOTS = slots
        p.PALLAS_CHUNK = 256
        v = np.asarray(p.check_histories(spec, corpus))
        out[slots] = (v.tolist(), p.pallas_calls)
    assert out[0][0] == out[64][0]
    assert out[64][1] < out[0][1]  # measured: 4 -> 1 chunk calls here


def test_pallas_mosaic_lowering():
    """Cross-platform lowering to the REAL Mosaic TPU backend (no chip
    needed: jax lowers for an explicit target platform).  This is what
    stands between the prototype and a wasted healed-tunnel window — the
    first version failed exactly here ('Reductions over unsigned
    integers not implemented'), which interpret-mode tests can never
    catch."""
    import jax
    import jax.numpy as jnp

    from qsm_tpu.ops.pallas_kernel import build_pallas_chunk

    spec = CasSpec()
    N, S, L, B = 32, 5, 256, 256
    for cs in (64, 0):
        CS = max(cs, 1)
        fn = build_pallas_chunk(spec, N, S, L, chunk=64, budget=2000,
                                interpret=False, cache_slots=cs)
        args = (jnp.zeros((S, N, B), jnp.int32),
                jnp.zeros((S, N, B), jnp.int32),
                jnp.zeros((N, B), jnp.int32),
                jnp.zeros((N, B), jnp.int32),
                jnp.zeros((1, B), jnp.int32),
                jnp.zeros((N, B), jnp.int32),
                jnp.full((N + 1, B), -1, jnp.int32),
                jnp.zeros((N + 1, B), jnp.int32),
                jnp.zeros((3, B), jnp.int32),
                jnp.zeros((CS, B), jnp.int32),
                jnp.zeros((CS, B), jnp.int32),
                jnp.zeros((CS, B), jnp.int32))
        lowered = jax.jit(fn).trace(*args).lower(
            lowering_platforms=("tpu",))
        assert len(lowered.as_text()) > 0


def test_pallas_rejects_unsupported_specs():
    from qsm_tpu.models import QueueSpec

    with pytest.raises(ValueError, match="scalar-table"):
        PallasTPU(QueueSpec())


def test_pallas_pending_ops_route_through_expansion(cas_corpus):
    """Pending-op histories go through the inherited host-side
    complete/prune expansion — verdicts must match the oracle's."""
    spec, corpus = cas_corpus
    import dataclasses

    from qsm_tpu.core.history import History

    # cut the last response off a linearizable history: now pending
    base = max(corpus, key=lambda h: len(h.ops))
    ops = list(base.ops)
    last = max(range(len(ops)), key=lambda i: ops[i].response_time)
    ops[last] = dataclasses.replace(ops[last], resp=-1,
                                    response_time=1 << 30)
    h = History(ops, seed=base.seed, program_id=base.program_id)
    assert h.n_pending == 1
    memo = WingGongCPU(memo=True)
    mv = int(memo.check_histories(spec, [h])[0])
    pv = int(_tight(spec).check_histories(spec, [h])[0])
    if mv != 2 and pv != 2:
        assert mv == pv
