"""PallasTPU prototype (ops/pallas_kernel.py) — parity with the oracle
and the XLA kernel on the scalar-table fast path.

Interpret mode (the CPU platform has no Mosaic compiler) is slow, so
corpora are tiny and budgets capped; the kernel's real A/B against the
XLA while-loop runs in tools/bench_scale.py's ``pallas`` variant cell
when a real-TPU window opens (VERDICT.md round 4, "Next round" #4)."""

from __future__ import annotations

import numpy as np
import pytest

from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.ops.backend import Verdict, verify_witness
from qsm_tpu.ops.jax_kernel import JaxTPU
from qsm_tpu.ops.pallas_kernel import PallasTPU
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.utils.corpus import build_corpus


def _tight(spec, **kw):
    """Interpret-mode-sized backend: small chunked budget, no rescue."""
    return PallasTPU(spec, budget=4_000, mid_budget=0, rescue_budget=0,
                     **kw)


@pytest.fixture(scope="module")
def cas_corpus():
    spec = CasSpec()
    return spec, build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=16,
                              n_pids=4, max_ops=12, seed_base=77,
                              seed_prefix="pal")


@pytest.mark.slow
def test_pallas_parity_vs_oracle(cas_corpus):
    spec, corpus = cas_corpus
    memo = WingGongCPU(memo=True)
    mv = np.asarray(memo.check_histories(spec, corpus))
    pv = np.asarray(_tight(spec).check_histories(spec, corpus))
    both = (mv != 2) & (pv != 2)
    assert int(((mv != pv) & both).sum()) == 0
    assert int((pv == 2).sum()) == 0  # this corpus decides within budget


@pytest.mark.slow
def test_pallas_matches_jax_kernel_verdicts(cas_corpus):
    spec, corpus = cas_corpus
    jx = JaxTPU(spec, budget=4_000, mid_budget=0, rescue_budget=0)
    jv = np.asarray(jx.check_histories(spec, corpus))
    pv = np.asarray(_tight(spec).check_histories(spec, corpus))
    assert jv.tolist() == pv.tolist()


def test_pallas_budget_is_honest(cas_corpus):
    """A tiny budget must yield BUDGET_EXCEEDED, never a guess."""
    spec, corpus = cas_corpus
    p = PallasTPU(spec, budget=3, mid_budget=0, rescue_budget=0)
    p.PALLAS_CHUNK = 4
    pv = np.asarray(p.check_histories(spec, corpus))
    memo = WingGongCPU(memo=True)
    mv = np.asarray(memo.check_histories(spec, corpus))
    both = (mv != 2) & (pv != 2)
    assert int(((mv != pv) & both).sum()) == 0
    assert int((pv == 2).sum()) > 0  # some lanes must hit the budget


@pytest.mark.slow
def test_pallas_witness_replays(cas_corpus):
    spec, corpus = cas_corpus
    p = _tight(spec)
    lin = next(h for h in corpus
               if Verdict(int(p.check_histories(spec, [h])[0]))
               == Verdict.LINEARIZABLE)
    v, wit = p.check_witness(spec, lin)
    assert v == Verdict.LINEARIZABLE and wit is not None
    assert verify_witness(spec, lin, wit)


@pytest.mark.slow
def test_pallas_cache_prunes_without_changing_verdicts(cas_corpus):
    """The per-lane VMEM memo cache is pruning-only: identical verdicts
    with fewer chunk calls (the violating history's exhaustive search is
    where it bites — same contract as the XLA kernel's cache)."""
    spec, corpus = cas_corpus
    out = {}
    for slots in (0, 64):
        p = PallasTPU(spec, budget=50_000, mid_budget=0, rescue_budget=0)
        p.PALLAS_CACHE_SLOTS = slots
        p.PALLAS_CHUNK = 256
        v = np.asarray(p.check_histories(spec, corpus))
        out[slots] = (v.tolist(), p.pallas_calls)
    assert out[0][0] == out[64][0]
    assert out[64][1] < out[0][1]  # measured: 4 -> 1 chunk calls here


def _lower_for_tpu(N, S, B, cache_slots):
    """Trace + lower one build_pallas_chunk config for the real Mosaic
    TPU target (no chip needed).  ONE definition of the kernel's
    table/carry argument layout for every lowering test — it must
    mirror build_pallas_chunk's in_specs exactly, and a carry-plane
    change edited in only one duplicated literal would leave the other
    test lowering a stale layout."""
    import jax
    import jax.numpy as jnp

    from qsm_tpu.ops.pallas_kernel import build_pallas_chunk

    CS = max(cache_slots, 1)
    fn = build_pallas_chunk(CasSpec(), N, S, lanes=256, chunk=64,
                            budget=2000, interpret=False,
                            cache_slots=cache_slots)
    args = (jnp.zeros((S, N, B), jnp.int32),
            jnp.zeros((S, N, B), jnp.int32),
            jnp.zeros((N, B), jnp.int32),
            jnp.zeros((N, B), jnp.int32),
            jnp.zeros((1, B), jnp.int32),
            jnp.zeros((N, B), jnp.int32),
            jnp.full((N + 1, B), -1, jnp.int32),
            jnp.zeros((N + 1, B), jnp.int32),
            jnp.zeros((3, B), jnp.int32),
            jnp.zeros((CS, B), jnp.int32),
            jnp.zeros((CS, B), jnp.int32),
            jnp.zeros((CS, B), jnp.int32))
    return jax.jit(fn).trace(*args).lower(lowering_platforms=("tpu",))


def test_pallas_mosaic_lowering():
    """Cross-platform lowering to the REAL Mosaic TPU backend (no chip
    needed: jax lowers for an explicit target platform).  This is what
    stands between the prototype and a wasted healed-tunnel window —
    two prior versions failed exactly here (unsigned reductions, then
    ALL integer reductions, unimplemented in Mosaic), which
    interpret-mode tests can never catch."""
    for cs in (64, 0):
        lowered = _lower_for_tpu(N=32, S=5, B=256, cache_slots=cs)
        assert len(lowered.as_text()) > 0


def test_pallas_mosaic_lowering_at_vmem_envelope():
    """Mosaic lowering at S = MAX_PALLAS_STATES — the LARGEST table the
    prototype admits (ADVICE.md round 5, finding 2: the lowering test
    only exercised S=5, so a big-S table spec could fail VMEM
    allocation/compile on the real chip and waste a healed window) —
    cross-checked against the static VMEM estimator: the envelope gate
    and the lowering must agree in both directions."""
    from qsm_tpu.analysis.kernel_passes import (VMEM_BUDGET_BYTES,
                                                pallas_vmem_bytes)
    from qsm_tpu.ops.pallas_kernel import (MAX_PALLAS_OPS,
                                           MAX_PALLAS_STATES)

    N, S, L, CS = MAX_PALLAS_OPS, MAX_PALLAS_STATES, 256, 64
    # the static estimator must admit this config ...
    assert pallas_vmem_bytes(N, S, L, CS) <= VMEM_BUDGET_BYTES
    # ... and reject what MAX_PALLAS_STATES exists to exclude (the
    # S=1280 scalarized queue/stack shadows)
    assert pallas_vmem_bytes(N, 1280, L, CS) > VMEM_BUDGET_BYTES
    lowered = _lower_for_tpu(N=N, S=S, B=256, cache_slots=CS)
    assert len(lowered.as_text()) > 0


def test_pallas_rejects_unsupported_specs():
    from qsm_tpu.models import QueueSpec

    with pytest.raises(ValueError, match="scalar-table"):
        PallasTPU(QueueSpec())


@pytest.mark.slow
def test_pallas_pending_ops_route_through_expansion(cas_corpus):
    """Pending-op histories go through the inherited host-side
    complete/prune expansion — verdicts must match the oracle's."""
    spec, corpus = cas_corpus
    import dataclasses

    from qsm_tpu.core.history import History

    # cut the last response off a linearizable history: now pending
    base = max(corpus, key=lambda h: len(h.ops))
    ops = list(base.ops)
    last = max(range(len(ops)), key=lambda i: ops[i].response_time)
    ops[last] = dataclasses.replace(ops[last], resp=-1,
                                    response_time=1 << 30)
    h = History(ops, seed=base.seed, program_id=base.program_id)
    assert h.n_pending == 1
    memo = WingGongCPU(memo=True)
    mv = int(memo.check_histories(spec, [h])[0])
    pv = int(_tight(spec).check_histories(spec, [h])[0])
    if mv != 2 and pv != 2:
        assert mv == pv
