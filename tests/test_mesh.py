"""The mesh-substrate gate (ISSUE 19): ONE NamedSharding lane axis
under every check plane, with bit-identical answers at every mesh
shape.

Two lanes:

* the SUBPROCESS parity lane — tests/_mesh_worker.py spawned with
  forced host device counts 8 and 1 (``forced_host_device_env``, the
  no-hardware recipe docs/MESH.md documents): verdicts, witnesses and
  minimized shrink rows must compare bit-for-bit across shapes, kv
  riding its pcomp per-key sub-lanes;
* in-process pins on the substrate's own contracts — mesh-divisible
  planner buckets and ``@meshN`` plan identity, plan-driven default
  sharding in ``build_backend``, the batcher's mesh-ceil flush target,
  the server's fan-out exclusivity, topology identity helpers, and the
  monitor frontier re-checking through a sharded oracle
  (tests/conftest.py pins this process to an 8-device virtual CPU
  platform, so in-process meshes up to 8 wide are real here).
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "_mesh_worker.py")


def _load_worker_module():
    spec = importlib.util.spec_from_file_location("_mesh_worker", WORKER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# the subprocess parity lane
# ---------------------------------------------------------------------------

def test_forced_device_count_parity_8_vs_1(tmp_path):
    """The acceptance gate: the identical corpus through the identical
    substrate at mesh shapes 8 and 1 answers identically — verdicts
    AND witnesses AND shrink rows — with kv pcomp-split and every
    linearizable witness replayed in-worker (witness_failures 0)."""
    from qsm_tpu.utils.device import forced_host_device_env

    outs = {n: str(tmp_path / f"mesh{n}.json") for n in (8, 1)}
    procs = {
        n: subprocess.Popen(
            [sys.executable, WORKER, str(n), outs[n]],
            env=forced_host_device_env(n), cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for n in (8, 1)
    }
    logs = {}
    try:
        for n, p in procs.items():
            logs[n], _ = p.communicate(timeout=600)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
    assert all(p.returncode == 0 for p in procs.values()), \
        "\n---\n".join(f"d{n}:\n{log}" for n, log in logs.items())

    reports = {n: json.load(open(outs[n])) for n in (8, 1)}
    assert reports[8]["devices"] == 8 and reports[1]["devices"] == 1
    mod = _load_worker_module()
    fams = [f[0] for f in mod.FAMILY_SHAPES]
    for fam in fams:
        r8, r1 = (reports[8]["families"][fam],
                  reports[1]["families"][fam])
        # bit-identical answers, per family
        assert r8["verdicts"] == r1["verdicts"], fam
        assert r8["witnesses"] == r1["witnesses"], fam
        # the corpus must exercise both verdicts or parity is vacuous
        assert len(set(r8["verdicts"])) >= 2, (fam, r8["verdicts"])
        # compile-bucket identity carries the shape: @mesh8 vs plain
        assert r8["plan"].endswith("@mesh8"), r8["plan"]
        assert "@mesh" not in r1["plan"], r1["plan"]
        assert r8["mesh_shape_key"] == [8, "batch"]
        assert r1["mesh_shape_key"] == [1]
    # the pcomp plane rode the mesh: kv decomposed, plain cas did not
    assert reports[8]["families"]["kv"]["pcomp"] is True
    assert reports[8]["families"]["cas"]["pcomp"] is False
    # shrink plane: same 1-minimal rows at both shapes
    assert reports[8]["shrink_ok"] and reports[1]["shrink_ok"]
    assert reports[8]["shrink_rows"] == reports[1]["shrink_rows"]
    # every linearizable witness replayed search-free, both shapes
    assert reports[8]["witness_failures"] == 0
    assert reports[1]["witness_failures"] == 0


# ---------------------------------------------------------------------------
# in-process pins: planner compile buckets
# ---------------------------------------------------------------------------

def test_plan_buckets_are_mesh_divisible_and_identity_is_suffixed():
    from qsm_tpu.models import CasSpec
    from qsm_tpu.search.planner import plan_search

    plain = plan_search(CasSpec())
    plan = plan_search(CasSpec(), mesh_devices=8)
    assert plan.mesh_devices == 8
    assert plan.name == f"{plain.name}@mesh8"
    assert all(b % 8 == 0 for b in plan.batch_buckets)
    assert set(plan.slots_for_batch) == set(plan.batch_buckets)
    assert any("mesh_devices=8" in w for w in plan.why)
    # mesh_devices=1 is the identity: same name, same ladder
    one = plan_search(CasSpec(), mesh_devices=1)
    assert one.name == plain.name
    assert one.batch_buckets == plain.batch_buckets


def test_mesh_bucket_ladder_filters_and_falls_back():
    from qsm_tpu.mesh.dispatch import mesh_bucket_ladder

    assert mesh_bucket_ladder((1, 2, 4, 8, 64), 1) == (1, 2, 4, 8, 64)
    assert mesh_bucket_ladder((1, 2, 4, 8, 64), 8) == (8, 64)
    # nothing divisible: one bucket of exactly one lane per device
    assert mesh_bucket_ladder((3, 5, 7), 8) == (8,)


def test_build_backend_applies_plan_mesh_sharding():
    """A ``@mesh8`` plan materializes its own lane sharding when the
    caller passes none — compile-bucket identity and placement can
    never drift apart."""
    from qsm_tpu.mesh import backend_sharding, mesh_shape_key
    from qsm_tpu.models import CasSpec
    from qsm_tpu.search.planner import build_backend, plan_search

    plan = plan_search(CasSpec(), mesh_devices=8)
    backend = build_backend(CasSpec(), plan)
    assert mesh_shape_key(backend_sharding(backend)) == (8, "batch")


def test_kernel_mesh_key_buckets_and_lane_sharding():
    """The driver's compile cache is keyed by mesh shape, its ladders
    are filtered to mesh-divisible widths, and the carry sharding is
    the lane-axis derivation of the batch sharding."""
    from qsm_tpu.mesh import batch_sharding, make_mesh
    from qsm_tpu.models import CasSpec
    from qsm_tpu.ops.jax_kernel import JaxTPU

    mesh = make_mesh(8)
    drv = JaxTPU(CasSpec(), sharding=batch_sharding(mesh))
    assert drv._mesh_key == (8, "batch")
    assert all(b % 8 == 0 for b in drv.BATCH_BUCKETS)
    assert set(drv.MAX_SLOTS_FOR_BATCH) == set(drv.BATCH_BUCKETS)
    assert drv._lane_sharding.spec[0] == "batch"
    plain = JaxTPU(CasSpec())
    assert plain._mesh_key == (1,)
    assert plain._lane_sharding is None


# ---------------------------------------------------------------------------
# in-process pins: serve plane fan-out
# ---------------------------------------------------------------------------

def test_batcher_mesh_ceil_flush_target():
    from qsm_tpu.serve.batcher import MicroBatcher

    sink = lambda *a: None  # noqa: E731 — never flushed here
    b = MicroBatcher(sink, flush_s=0.01, max_lanes=10, mesh_devices=8)
    # every lanes target is rounded UP to a multiple of the mesh width
    # (never down: admission capacity must not silently shrink)
    assert b.max_lanes == 16
    assert b._mesh_ceil(1) == 8 and b._mesh_ceil(17) == 24
    assert b.snapshot()["mesh_devices"] == 8
    plain = MicroBatcher(sink, flush_s=0.01, max_lanes=10)
    assert plain.max_lanes == 10 and plain._mesh_ceil(7) == 7


def test_server_mesh_devices_and_worker_pool_are_exclusive():
    from qsm_tpu.serve.server import CheckServer

    with pytest.raises(ValueError):
        CheckServer(workers=2, mesh_devices=8)


def test_server_stats_report_mesh_devices():
    from qsm_tpu.serve.server import CheckServer

    server = CheckServer(flush_s=0.005, max_lanes=8,
                         mesh_devices=8).start()
    try:
        assert server.stats()["mesh_devices"] == 8
        assert server.batcher.max_lanes % 8 == 0
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# in-process pins: topology identity helpers
# ---------------------------------------------------------------------------

def test_topology_identity_helpers():
    from jax.sharding import PartitionSpec as P

    from qsm_tpu.mesh import (batch_sharding, lane_sharding_of,
                              make_mesh, make_mesh_2d,
                              mesh_device_count, mesh_shape_key)

    mesh = make_mesh(8)
    sharding = batch_sharding(mesh)
    assert mesh_device_count(mesh) == 8
    assert mesh_device_count(sharding) == 8
    assert mesh_shape_key(sharding) == (8, "batch")
    assert mesh_shape_key(None) == (1,)
    assert lane_sharding_of(sharding).spec == P("batch")
    # hierarchical mesh: the lane derivation keeps dim 0 over BOTH
    # axes and drops the rest — carries shard like their batch dim
    mesh2 = make_mesh_2d(2, 4)
    s2 = batch_sharding(mesh2)
    assert mesh_shape_key(s2) == (8, "host", "batch")
    assert lane_sharding_of(s2).spec[0] == ("host", "batch")


# ---------------------------------------------------------------------------
# in-process pins: monitor plane on a sharded oracle
# ---------------------------------------------------------------------------

def test_monitor_frontier_recheck_through_sharded_oracle():
    """The frontier's window re-check (oracle.check_from) answers
    identically through a mesh-sharded kernel and the unsharded one —
    the monitor plane rides the substrate without a verdict drift."""
    from qsm_tpu import generate_program, run_concurrent
    from qsm_tpu.mesh import batch_sharding, make_mesh
    from qsm_tpu.models import AtomicCasSUT, CasSpec
    from qsm_tpu.monitor.frontier import IncrementalFrontier
    from qsm_tpu.ops.jax_kernel import JaxTPU

    spec = CasSpec()
    prog = generate_program(spec, seed=5, n_pids=4, max_ops=12)
    hist = run_concurrent(AtomicCasSUT(spec), prog, seed="mesh-mon")
    ops = sorted(hist.completed().ops, key=lambda o: o.invoke_time)

    def drive(oracle):
        frontier = IncrementalFrontier(spec, oracle=oracle)
        seq = []
        for op in ops:
            frontier.append_completed(op)
            seq.append(int(frontier.advance()))
        seq.append(int(frontier.check_window()))
        return seq

    sharded = drive(JaxTPU(spec, budget=200_000,
                           sharding=batch_sharding(make_mesh(8))))
    plain = drive(JaxTPU(spec, budget=200_000))
    assert sharded == plain
    assert sharded[-1] is not None


# ---------------------------------------------------------------------------
# in-process pins: the window's mesh comes from its probed device SET
# ---------------------------------------------------------------------------

def test_mesh_from_devices_uses_the_explicit_list():
    """The ISSUE 20 bugfix, pinned: a drain mesh is built from the
    devices the window's probe ACTUALLY answered with — order
    preserved, size = len(list), never a forced count over
    ``jax.devices()`` (a 2-chip window must not lay out 8 shards)."""
    import jax

    from qsm_tpu.mesh import mesh_device_count, mesh_from_devices

    window = jax.devices()[1:4]          # a window that offered 3 chips
    mesh = mesh_from_devices(window)
    assert mesh_device_count(mesh) == 3
    assert list(mesh.devices.flat) == list(window)
    assert mesh.axis_names == ("batch",)


def test_mesh_from_devices_refuses_empty_and_duplicates():
    import jax
    import pytest

    from qsm_tpu.mesh import mesh_from_devices

    with pytest.raises(ValueError, match="empty device set"):
        mesh_from_devices([])
    d0 = jax.devices()[0]
    with pytest.raises(ValueError, match="duplicate devices"):
        mesh_from_devices([d0, d0])


def test_drain_scheduler_builds_mesh_from_window_devices():
    """The drain scheduler threads the probed set through
    ``mesh_from_devices``: hand it 3 of the process's 8 devices and
    its mesh is exactly 3 wide."""
    import jax

    from qsm_tpu.devq.drain import DrainScheduler
    from qsm_tpu.devq.queue import DeviceWorkQueue

    sched = DrainScheduler(DeviceWorkQueue(),
                           devices=jax.devices()[:3], window_s=1.0,
                           cache=None)
    assert sched.n_devices == 3
