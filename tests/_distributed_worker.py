"""Worker for the REAL ``jax.distributed`` multi-process test.

Spawned (twice) by tests/test_distributed.py with a localhost coordinator:
each process owns 4 virtual CPU devices, ``init_distributed`` joins them
into one 8-device global runtime, and the Wing–Gong kernel runs sharded
over the global (host, batch) mesh — the identical program shape a real
2-host TPU deployment executes, with DCN replaced by localhost TCP
(SURVEY.md §5 comm backend row; VERDICT.md round 2, "Next round" #5).

Importable by the parent test for the shared corpus/encoding helpers; the
``__main__`` path is the subprocess body.
"""

from __future__ import annotations

import json
import sys

import numpy as np

N_PIDS = 4
N_OPS = 16
N_HIST = 32
BUDGET = 500_000


def build_inputs():
    """Deterministic CAS corpus + kernel-ready encoding, identical in every
    process (generation is seed-derived, no wall clock anywhere)."""
    from qsm_tpu.core.history import bucket_for, encode_batch
    from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
    from qsm_tpu.utils.corpus import build_corpus

    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=64,
                          n_pids=N_PIDS, max_ops=N_OPS, seed_base=42,
                          seed_prefix="dist")
    # the raw kernel decides complete histories only (pending-op expansion
    # is the JaxTPU driver's host-side job, not under test here)
    corpus = [h for h in corpus if h.n_pending == 0][:N_HIST]
    assert len(corpus) == N_HIST, len(corpus)
    n_ops = bucket_for(max(len(h) for h in corpus))
    enc = encode_batch(corpus, spec.initial_state(), max_ops=n_ops)
    args = (enc.ops[:, :, 1].astype(np.int32),
            enc.ops[:, :, 2].astype(np.int32),
            enc.ops[:, :, 3].astype(np.int32),
            enc.valid.astype(bool),
            enc.precedes().astype(bool),
            np.tile(np.asarray(enc.init_state, np.int32), (N_HIST, 1)))
    return spec, n_ops, args


def main(argv) -> int:
    pid, nproc, port, out_path = (int(argv[0]), int(argv[1]), argv[2],
                                  argv[3])
    sys.path.insert(0, "/root/repo")
    # a plain JAX_PLATFORMS=cpu from the parent is IGNORED once the image's
    # sitecustomize registered the axon TPU plugin — the config update after
    # import is what actually wins (tests/conftest.py has the same dance);
    # without it the first device query would try to initialize the chip
    # tunnel and hang the worker forever
    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform(4)
    import jax

    from qsm_tpu.mesh import (batch_sharding, init_distributed,
                              lane_sharding_of, make_mesh_2d,
                              mesh_device_count, mesh_shape_key)
    from qsm_tpu.ops.jax_kernel import build_kernel

    ok = init_distributed(f"127.0.0.1:{port}", num_processes=nproc,
                          process_id=pid)
    assert ok, "init_distributed returned False with explicit coordinator"
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())
    assert len(jax.local_devices()) == 4, len(jax.local_devices())

    spec, n_ops, args = build_inputs()
    mesh = make_mesh_2d(2, 4)
    # the mesh must really span both OS processes, not 8 local devices
    assert len({d.process_index for d in mesh.devices.flat}) == 2
    sharding = batch_sharding(mesh)
    # the promoted substrate's identity helpers hold on the MULTI-HOST
    # mesh shape too: 8 global devices under ("host", "batch"), and the
    # lane derivation reduces the hierarchical spec to its leading axis
    assert mesh_device_count(mesh) == 8, mesh_device_count(mesh)
    assert mesh_shape_key(sharding) == (8, "host", "batch")
    assert lane_sharding_of(sharding).spec[0] == ("host", "batch")
    garrs = [
        jax.make_array_from_callback(a.shape, sharding,
                                     lambda idx, a=a: a[idx])
        for a in args
    ]
    fn = jax.jit(jax.vmap(build_kernel(spec, n_ops, BUDGET)))
    status, _iters = fn(*garrs)
    status.block_until_ready()

    # every process reports its ADDRESSABLE rows; the parent unions them
    rows = {}
    for shard in status.addressable_shards:
        sl = shard.index[0]
        for off, v in enumerate(np.asarray(shard.data).ravel()):
            rows[str(sl.start + off)] = int(v)
    with open(out_path, "w") as f:
        json.dump({"process_index": pid,
                   "process_count": jax.process_count(),
                   "global_devices": len(jax.devices()),
                   "mesh_shape_key": list(mesh_shape_key(sharding)),
                   "rows": rows}, f)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
