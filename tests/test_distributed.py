"""REAL ``jax.distributed`` execution — 2 OS processes, localhost
coordinator, sharded kernel over the global (host, batch) mesh, verdict
parity with a single-process run (VERDICT.md round 2, "Next round" #5: the
multi-host program shape actually executes; ``init_distributed`` no longer
has only its no-op branch covered)."""

from __future__ import annotations

import importlib.util
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# heaviest parametrized suite: full lane only (README "Tests", pyproject `slow` marker)
pytestmark = pytest.mark.slow

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "_distributed_worker.py")


def _load_worker_module():
    spec = importlib.util.spec_from_file_location("_distributed_worker",
                                                  WORKER)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_jax_distributed_sharded_kernel_parity(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        env.pop(k, None)

    outs = [str(tmp_path / f"worker{i}.json") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", str(port), outs[i]],
            env=env, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    logs = []
    try:
        for p in procs:
            # generous: the workers each cold-start a JAX runtime; under
            # heavy machine load 300s has been observed too tight
            out, _ = p.communicate(timeout=600)
            logs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any("Multiprocess computations aren't implemented on the CPU "
           "backend" in log for log in logs):
        # environment capability gate, same contract as the tpu marker's
        # clean skip: some jaxlib builds cannot run cross-process
        # collectives on the CPU backend at all — nothing this test
        # guards (the sharded-kernel program shape) can be exercised
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives "
                    "in this environment")
    assert all(p.returncode == 0 for p in procs), "\n---\n".join(logs)

    reports = [json.load(open(o)) for o in outs]
    assert {r["process_index"] for r in reports} == {0, 1}
    assert all(r["process_count"] == 2 for r in reports)
    assert all(r["global_devices"] == 8 for r in reports)
    # both processes built the SAME multi-host mesh shape and the
    # substrate's compile-bucket identity agrees on it (qsm_tpu/mesh)
    assert all(r["mesh_shape_key"] == [8, "host", "batch"]
               for r in reports)

    # union of per-process addressable rows covers the whole batch
    mod = _load_worker_module()
    rows: dict[int, int] = {}
    for r in reports:
        for k, v in r["rows"].items():
            rows[int(k)] = v
    assert sorted(rows) == list(range(mod.N_HIST))

    # single-process reference: same kernel, same budget, this process's
    # devices (tests/conftest.py pins an 8-device virtual CPU platform)
    import jax

    from qsm_tpu.ops.jax_kernel import build_kernel

    spec, n_ops, args = mod.build_inputs()
    fn = jax.jit(jax.vmap(build_kernel(spec, n_ops, mod.BUDGET)))
    status, _ = fn(*args)
    want = np.asarray(status)
    got = np.asarray([rows[i] for i in range(mod.N_HIST)])
    np.testing.assert_array_equal(got, want)
    # the corpus must exercise both verdicts, or parity proves nothing
    assert (want == 1).any() and (want == 2).any()
