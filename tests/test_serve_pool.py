"""Worker-pool serving plane (qsm_tpu/serve/pool.py) — the tier-1 gate
for ISSUE 6.

What is pinned, in order of importance:

* pooled verdicts and witnesses are BIT-IDENTICAL to the direct host
  path across register/cas/queue/kv (workers run the exact engine the
  in-process server keeps warm — the pool changes where checking
  happens, never what it answers);
* a worker SIGKILLed MID-BATCH (the `worker` fault site's kill action)
  never produces a wrong verdict or a hung client: the undecided lanes
  re-dispatch to a healthy worker — or, last resort, the supervisor's
  own in-process host ladder — inside the `worker-dispatch` watchdog
  bound;
* a spec that crash-loops workers is quarantined to the in-process
  ladder (bounded respawns, never a spawn storm);
* the persistent verdict bank is SUPERVISOR-owned: kill the pooled
  server, restart it, and the bank serves (workers are bank-free, so
  no SIGKILL can tear it);
* `CheckServer.stop()` tears the pool down deterministically — tier-1
  runs never leak a worker process;
* the 2-worker × 2-client smoke rides the default (`not slow`) lane.
"""

from __future__ import annotations

import io
import json
import threading
import time

import pytest

from qsm_tpu.models.registry import MODELS
from qsm_tpu.ops.backend import verify_witness
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.resilience.policy import preset
from qsm_tpu.serve import (CheckClient, CheckServer, VERDICT_NAMES,
                           WorkerPool)
from qsm_tpu.serve.frames import encode_frame, read_frame
from qsm_tpu.utils.corpus import build_corpus

FAMILIES = ("register", "cas", "queue", "kv")


def _corpus(family, n=8, pids=3, ops=8, prefix="pool"):
    entry = MODELS[family]
    spec = entry.make_spec()
    hists = build_corpus(
        spec, (entry.impls["atomic"], entry.impls["racy"]), n=n,
        n_pids=pids, max_ops=ops, seed_prefix=f"{prefix}_{family}")
    return spec, hists


def _names(verdicts):
    return [VERDICT_NAMES[int(v)] for v in verdicts]


def _pooled(tmp_path, workers=2, **kw):
    kw.setdefault("flush_s", 0.005)
    kw.setdefault("max_lanes", 16)
    kw.setdefault("cache_path", str(tmp_path / "bank.jsonl"))
    return CheckServer(workers=workers, **kw).start()


def _worker_procs(srv):
    return [s.handle.proc for s in srv.pool._slots if s.handle is not None]


# --- parity: the pool changes where, never what ---------------------------

def test_pooled_verdicts_bit_identical_across_families(tmp_path):
    """The acceptance pin: across register/cas/queue/kv the pooled path
    answers exactly what the direct host checker answers, and every
    batch stamp names the worker that decided it."""
    srv = _pooled(tmp_path)
    try:
        with CheckClient(srv.address) as client:
            for family in FAMILIES:
                spec, hists = _corpus(family)
                direct = WingGongCPU(memo=True).check_histories(spec, hists)
                res = client.check(family, hists)
                assert res["ok"], res
                assert res["verdicts"] == _names(direct), family
                assert "LINEARIZABLE" in res["verdicts"], family
                for b in res["batches"]:
                    assert b.get("worker") in (0, 1), b
        assert srv.pool.snapshot()["dispatches"] >= len(FAMILIES)
        assert srv.stats()["worker_faults"] == 0
    finally:
        srv.stop()


def test_pooled_witnesses_bit_identical(tmp_path):
    """Witness requests keep the one-search supervisor-oracle rule on a
    pooled server; witnesses equal the direct oracle's and replay
    search-free."""
    spec, hists = _corpus("cas", n=6)
    oracle = WingGongCPU(memo=True)
    srv = _pooled(tmp_path)
    try:
        with CheckClient(srv.address) as client:
            res = client.check("cas", hists, witness=True)
        assert res["ok"]
        for h, v, w in zip(hists, res["verdicts"], res["witnesses"]):
            dv, dw = oracle.check_witness(spec, h)
            assert v == VERDICT_NAMES[int(dv)]
            if v == "LINEARIZABLE":
                w = [tuple(p) for p in w]
                assert w == dw
                assert verify_witness(spec, h, w)
            else:
                assert w is None
    finally:
        srv.stop()


# --- worker loss: shed, re-dispatch, never wrong, never hung --------------

def test_sigkill_mid_batch_redispatches_to_healthy_worker(
        tmp_path, monkeypatch):
    """kill:worker@2 SIGKILLs a worker on its SECOND dispatch — mid
    batch, mid pipe protocol.  The supervisor sees the crash, sheds the
    worker, and the undecided lanes re-dispatch to the OTHER (healthy)
    worker: verdicts unchanged, one worker fault counted."""
    monkeypatch.setenv("QSM_TPU_FAULTS", "kill:worker@2")
    spec, hists = _corpus("cas", n=6)
    direct = WingGongCPU(memo=True).check_histories(spec, hists)
    spec2, hists2 = _corpus("cas", n=6, prefix="pool2")
    direct2 = WingGongCPU(memo=True).check_histories(spec2, hists2)
    srv = _pooled(tmp_path, workers=2, quarantine_after=3)
    try:
        with CheckClient(srv.address, timeout_s=60.0) as client:
            first = client.check("cas", hists)
            assert first["ok"] and first["verdicts"] == _names(direct)
            second = client.check("cas", hists2, deadline_s=30.0)
            assert second["ok"], second
            assert second["verdicts"] == _names(direct2)
        snap = srv.pool.snapshot()
        assert snap["worker_faults"] >= 1
        # the re-dispatched batch says it survived a worker loss
        wf = [b for b in second["batches"] if b.get("worker_faults")]
        assert wf and wf[0]["search"]["wf"] >= 1
        assert "cas" not in "".join(snap["quarantined_specs"])
    finally:
        srv.stop()


def test_hung_worker_is_shed_inside_watchdog_bound(tmp_path, monkeypatch):
    """hang:worker wedges the dispatch inside the worker; the
    `worker-dispatch` watchdog bound fires, the worker is SIGKILLed
    like a wedged chip, and the lanes resolve on the in-process ladder
    — bounded wall-clock, exact verdicts, no hung client."""
    monkeypatch.setenv("QSM_TPU_FAULTS", "hang:worker")
    monkeypatch.setenv("QSM_TPU_FAULT_HANG_S", "30")
    spec, hists = _corpus("register", n=4)
    direct = WingGongCPU(memo=True).check_histories(spec, hists)
    srv = _pooled(
        tmp_path, workers=1,
        worker_policy=preset("worker-dispatch").with_(timeout_s=0.5,
                                                      deadline_s=5.0))
    try:
        t0 = time.monotonic()
        with CheckClient(srv.address, timeout_s=60.0) as client:
            res = client.check("register", hists, deadline_s=20.0)
        assert res["ok"]
        assert res["verdicts"] == _names(direct)
        assert time.monotonic() - t0 < 10.0  # watchdogged, not slept out
        assert srv.pool.worker_faults >= 1
    finally:
        srv.stop()


def test_crash_loop_spec_is_quarantined_no_respawn_storm(
        tmp_path, monkeypatch):
    """kill:worker (every dispatch) grinds through quarantine_after
    workers exactly once, then the spec is quarantined to the
    in-process ladder: later requests never touch the pool, respawns
    stay bounded, verdicts stay exact."""
    monkeypatch.setenv("QSM_TPU_FAULTS", "kill:worker")
    spec, hists = _corpus("queue", n=5)
    direct = WingGongCPU(memo=True).check_histories(spec, hists)
    spec2, hists2 = _corpus("queue", n=5, prefix="pool2")
    direct2 = WingGongCPU(memo=True).check_histories(spec2, hists2)
    srv = _pooled(tmp_path, workers=2, quarantine_after=2)
    try:
        with CheckClient(srv.address, timeout_s=60.0) as client:
            res = client.check("queue", hists, deadline_s=30.0)
            assert res["ok"] and res["verdicts"] == _names(direct)
            snap = srv.pool.snapshot()
            assert snap["quarantines"] == 1
            assert snap["quarantined_specs"], snap
            # a fresh corpus for the same spec goes straight in-process
            res2 = client.check("queue", hists2, deadline_s=30.0)
            assert res2["ok"] and res2["verdicts"] == _names(direct2)
            assert any(b.get("pool") == "in-process"
                       for b in res2["batches"]), res2["batches"]
        snap = srv.pool.snapshot()
        assert snap["worker_faults"] == 2  # exactly the quarantine budget
        # bounded respawns, not a storm (backoff makes more impossible
        # inside this test's lifetime anyway — this pins the counter)
        assert snap["respawns"] <= 2
    finally:
        srv.stop()


# --- the bank stays supervisor-owned --------------------------------------

def test_pooled_restart_after_kill_serves_persistent_bank(tmp_path):
    """Kill a pooled server (no graceful flush beyond per-batch puts),
    tear a trailing line, restart WITH workers: every banked verdict
    serves cached and bit-identical — workers never touched the bank."""
    bank = str(tmp_path / "bank.jsonl")
    spec, hists = _corpus("cas", n=8)
    direct = WingGongCPU(memo=True).check_histories(spec, hists)

    srv = _pooled(tmp_path, workers=2, cache_path=bank)
    try:
        with CheckClient(srv.address) as client:
            res = client.check("cas", hists)
            assert res["ok"] and not any(res["cached"])
    finally:
        srv.stop()
    with open(bank, "a") as f:
        f.write('{"key": "torn-mid-wr')  # simulated torn tail

    srv2 = _pooled(tmp_path, workers=2, cache_path=bank)
    try:
        with CheckClient(srv2.address) as client:
            res2 = client.check("cas", hists)
        assert res2["ok"]
        assert all(res2["cached"]), res2["cached"]
        assert res2["verdicts"] == _names(direct)
    finally:
        srv2.stop()


# --- lifecycle: deterministic teardown, shed carries pool state -----------

def test_stop_reaps_every_worker_process(tmp_path):
    """The ISSUE 6 small fix: stop() must terminate → bounded-join →
    kill-escalate so tier-1 runs never leak a worker process."""
    srv = _pooled(tmp_path, workers=2)
    procs = _worker_procs(srv)
    assert len(procs) == 2
    with CheckClient(srv.address) as client:
        spec, hists = _corpus("register", n=4)
        assert client.check("register", hists)["ok"]
    srv.stop()
    for proc in procs:
        assert proc.poll() is not None, "leaked worker process"


def test_shed_response_carries_pool_state(tmp_path):
    srv = _pooled(tmp_path, workers=2, queue_depth=2)
    try:
        with CheckClient(srv.address) as client:
            spec, hists = _corpus("register", n=5)
            res = client.check("register", hists)
        assert res["ok"] is False and res["shed"] is True
        assert res["reason"] == "queue full"
        assert res["pool"]["workers"] == 2
        assert res["pool"]["live"] in (0, 1, 2)
        assert "quarantined" in res["pool"]
    finally:
        srv.stop()


def test_workers_require_auto_engine():
    with pytest.raises(ValueError):
        CheckServer(workers=2, engine="planned")


# --- the CI pool smoke: 2 workers × 2 concurrent clients ------------------

def test_pool_smoke_two_workers_two_clients(tmp_path):
    """The default-lane smoke (ISSUE 6 satellite): two concurrent
    clients on distinct families against a 2-worker pool — both exact,
    and the stats op exposes per-worker rows."""
    srv = _pooled(tmp_path, workers=2)
    results = {}

    def drive(family):
        spec, hists = _corpus(family, n=6)
        direct = WingGongCPU(memo=True).check_histories(spec, hists)
        with CheckClient(srv.address) as client:
            res = client.check(family, hists)
        results[family] = (res, _names(direct))

    try:
        threads = [threading.Thread(target=drive, args=(f,))
                   for f in ("register", "cas")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert set(results) == {"register", "cas"}
        for family, (res, direct_names) in results.items():
            assert res["ok"], family
            assert res["verdicts"] == direct_names, family
        st = srv.stats()
        assert st["workers"] == 2
        rows = st["pool"]["workers"]
        assert len(rows) == 2
        for row in rows:
            assert {"wid", "alive", "dispatches", "faults", "deaths",
                    "respawns"} <= set(row)
        assert sum(r["dispatches"] for r in rows) >= 1
        assert st["batcher"]["concurrency"] == 2
    finally:
        srv.stop()


# --- units: frames, preset, counters --------------------------------------

def test_frame_roundtrip_and_torn_frame():
    doc = {"op": "check", "seq": 7, "rows": [[0, 1, 2, 3, 4, 5]]}
    buf = io.BytesIO(encode_frame(doc))
    assert read_frame(buf) == doc
    # a torn frame (killed writer) reads as EOF, never as half a doc
    torn = encode_frame(doc)[:-3]
    assert read_frame(io.BytesIO(torn)) is None
    assert read_frame(io.BytesIO(b"")) is None


def test_worker_dispatch_preset_exists():
    p = preset("worker-dispatch")
    assert p.attempts >= 2          # at least one re-dispatch
    assert p.timeout_s and p.timeout_s > 0
    assert p.deadline_s and p.deadline_s >= p.timeout_s


def test_search_stats_worker_faults_counter():
    from qsm_tpu.search.stats import SearchStats, stats_delta

    a = SearchStats(histories=4, worker_faults=3)
    b = SearchStats(histories=1, worker_faults=1)
    assert a.to_compact()["wf"] == 3
    assert stats_delta(a, b).worker_faults == 2
    merged = SearchStats().absorb(a)
    assert merged.worker_faults == 3
    assert a.to_timings()["resilience_worker_faults"] == 3.0


def test_pool_rejects_zero_workers():
    with pytest.raises(ValueError):
        WorkerPool(0)


def test_quarantine_is_keyed_per_spec(tmp_path, monkeypatch):
    """Quarantining the killer spec must not take healthy specs with
    it: after a cas crash-loop, register still rides the pool."""
    monkeypatch.setenv("QSM_TPU_FAULTS", "kill:worker")
    srv = _pooled(tmp_path, workers=2, quarantine_after=1)
    try:
        with CheckClient(srv.address, timeout_s=60.0) as client:
            spec, hists = _corpus("cas", n=4)
            direct = WingGongCPU(memo=True).check_histories(spec, hists)
            res = client.check("cas", hists, deadline_s=30.0)
            assert res["ok"] and res["verdicts"] == _names(direct)
            quarantined = srv.pool.snapshot()["quarantined_specs"]
            assert any("cas" in q for q in quarantined)
            assert not any("register" in q for q in quarantined)
    finally:
        srv.stop()
