"""Tier-1 gate for the batched shrink plane (qsm_tpu/shrink, ISSUE 10).

Pins, per docs/SHRINK.md:

* 1-MINIMALITY — the minimized history is still a VIOLATION and every
  further single-op drop decides LINEARIZABLE (checked directly against
  the oracle, independent of the shrinker's own bookkeeping);
* DETERMINISM — the whole pipeline is seed/RNG-free: two runs over the
  same input produce bit-identical minimized histories;
* DECOMPOSED == UNDECOMPOSED — shrinking through the PComp split and
  through the whole-history host ladder steps to the SAME minimized
  history on multireg/multicas (verdict parity ⇒ selection parity);
* CERTIFICATES — the per-neighbor witnesses replay through
  ``verify_witness`` across register/cas/queue/kv (stitched on the
  decomposable family, plain elsewhere);
* SERVE — the ``shrink`` verb returns the identical minimized history
  as the in-process API, banks duplicates, and a deadline firing
  MID-shrink returns best-so-far with an honest ``why`` (never a wrong
  or fabricated result);
* the planner's DECOMPOSED-corpus segdc re-gate (ROADMAP item 3
  leftover) with its pinned threshold.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from qsm_tpu.core.generator import generate_program
from qsm_tpu.models.registry import MODELS, make
from qsm_tpu.ops.backend import Verdict, verify_witness
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.resilience.failover import FailoverBackend, host_fallback
from qsm_tpu.sched.runner import run_concurrent
from qsm_tpu.shrink import (collect_shrink_stats, inversions,
                            shrink_frontier, shrink_history,
                            verify_certificate)


def _failing_history(model, n=1, pids=None, ops=None, prefix="tshr",
                     scan=60):
    """Seeded VIOLATION histories from the registry's racy impl."""
    entry = MODELS[model]
    spec, _ = make(model, "racy")
    racy = entry.impls["racy"]
    eng = host_fallback(spec)
    out = []
    for seed in range(scan):
        if len(out) >= n:
            break
        prog = generate_program(spec, seed=seed,
                                n_pids=pids or entry.default_pids,
                                max_ops=ops or entry.default_ops,
                                min_ops=ops or entry.default_ops)
        h = run_concurrent(racy(spec), prog,
                           seed=f"{prefix}:{model}:{seed}").completed()
        if int(eng.check_histories(spec, [h])[0]) == int(Verdict.VIOLATION):
            out.append(h)
    assert out, f"no failing {model} history in {scan} seeds"
    return spec, out


# --- 1-minimality ---------------------------------------------------------

def test_minimized_is_one_minimal_violation():
    spec, (h,) = _failing_history("kv", pids=8, ops=64)
    res = shrink_history(spec, h, certificate=False)
    assert res.ok and res.complete and res.one_minimal
    assert res.final_ops < res.initial_ops
    oracle = WingGongCPU(memo=True)
    # the claim itself, independent of the shrinker: still a VIOLATION,
    # and EVERY further single-op drop passes
    assert int(oracle.check_histories(spec, [res.history])[0]) \
        == int(Verdict.VIOLATION)
    n = len(res.history.ops)
    for j in range(n):
        neighbor = res.history.subhistory(
            [i for i in range(n) if i != j])
        assert int(oracle.check_histories(spec, [neighbor])[0]) \
            == int(Verdict.LINEARIZABLE), f"drop {j} still fails"


def test_shrink_not_a_violation_returns_unshrunken():
    spec, _ = make("register", "atomic")
    from qsm_tpu.core.history import sequential_history

    h = sequential_history([(0, 1, 1, 0), (0, 0, 0, 1)])  # W(1); R->1
    res = shrink_history(spec, h)
    assert not res.ok and res.verdict == int(Verdict.LINEARIZABLE)
    assert res.history.fingerprint() == h.fingerprint()
    assert any("not a VIOLATION" in w for w in res.why)


# --- determinism ----------------------------------------------------------

def test_shrink_is_deterministic():
    spec, (h,) = _failing_history("cas", pids=4, ops=32)
    a = shrink_history(spec, h, certificate=False)
    b = shrink_history(spec, h, certificate=False)
    assert a.history.fingerprint() == b.history.fingerprint()
    assert (a.rounds, a.engine_calls, a.lanes_checked) \
        == (b.rounds, b.engine_calls, b.lanes_checked)


# --- decomposed == undecomposed parity ------------------------------------

@pytest.mark.parametrize("model", ["multireg", "multicas"])
def test_decomposed_equals_undecomposed_shrink(model):
    spec, (h,) = _failing_history(model, pids=6, ops=24)
    from qsm_tpu.ops.pcomp import PComp

    dec = shrink_history(
        spec, h, backend=PComp(spec, make_inner=host_fallback),
        certificate=False)
    whole = shrink_history(
        spec, h, backend=FailoverBackend(spec, host_fallback(spec)),
        certificate=False)
    assert dec.ok and whole.ok
    assert dec.history.fingerprint() == whole.history.fingerprint()
    assert dec.final_ops == whole.final_ops


# --- certificates ---------------------------------------------------------

@pytest.mark.parametrize("model,pids,ops", [
    ("register", 3, 12), ("cas", 4, 24), ("queue", 4, 16),
    ("kv", 6, 32),
])
def test_certificate_replays_across_families(model, pids, ops):
    spec, (h,) = _failing_history(model, pids=pids, ops=ops)
    res = shrink_history(spec, h, certificate=True)
    assert res.ok and res.complete
    assert res.certificate is not None
    n = len(res.history.ops)
    assert len(res.certificate) == n
    for row in res.certificate:
        assert not row.get("undecided"), row
        neighbor = res.history.subhistory(
            [i for i in range(n) if i != row["drop"]])
        assert verify_witness(spec, neighbor,
                              [tuple(p) for p in row["witness"]])
    audit = verify_certificate(spec, res.history, res.certificate)
    assert audit["one_minimal_proved"] and audit["violation_reconfirmed"]


def test_kv_certificate_uses_stitched_witness_when_split_pays():
    # a multi-key minimized history is rare; instead pin the mechanism:
    # the certificate of a >bucket-gain neighbor goes through PComp
    spec, (h,) = _failing_history("kv", pids=8, ops=64)
    from qsm_tpu.shrink import minimality_certificate

    # certificate of the INPUT history's neighbors: 64-op kv neighbors
    # split (smaller buckets), so stitched witnesses appear wherever the
    # neighbor is linearizable — and every witness must still replay
    rows = minimality_certificate(spec, h)
    stitched = [r for r in rows if r.get("stitched")]
    for row in rows:
        if row.get("undecided"):
            continue
        n = len(h.ops)
        neighbor = h.subhistory(
            [i for i in range(n) if i != row["drop"]])
        assert verify_witness(spec, neighbor,
                              [tuple(p) for p in row["witness"]])
    # the racy 64-op input has at least one linearizable neighbor only
    # sometimes; the mechanism pin is that stitched rows, when present,
    # replayed above — and that the flag is populated either way
    assert all("stitched" in r for r in rows if not r.get("undecided"))
    assert isinstance(stitched, list)


# --- frontier unit behavior ----------------------------------------------

def test_frontier_sorted_deduped_and_capped():
    spec, (h,) = _failing_history("kv", pids=8, ops=64)
    cands, trunc = shrink_frontier(spec, h, max_lanes=16)
    assert len(cands) == 16 and trunc > 0
    sizes = [len(c.history) for c in cands]
    assert sizes == sorted(sizes)
    fps = {c.history.fingerprint() for c in cands}
    assert len(fps) == len(cands)


def test_swap_candidates_reduce_inversions():
    spec, (h,) = _failing_history("cas", pids=4, ops=24)
    from qsm_tpu.shrink.frontier import swap_candidates

    base = inversions(h)
    swaps = list(swap_candidates(h))
    for c in swaps:
        assert len(c.history) == len(h)
        assert inversions(c.history) == base - 1


def test_truncated_final_frontier_forfeits_one_minimality():
    # a 2-op-minimal violation (W(1) strictly before R->0): with a
    # 1-lane frontier the FINAL round can only check one of its two
    # single-op drops — the claim must be forfeited, and the why must
    # say so (candidates never generated cannot be claimed checked)
    from qsm_tpu.core.history import overlapping_history
    from qsm_tpu.models.register import READ, WRITE

    spec, _ = make("register", "atomic")
    h = overlapping_history([(1, WRITE, 1, 0, 0, 1), (0, READ, 0, 0, 2, 3)])
    res = shrink_history(spec, h, max_lanes=1, certificate=False)
    assert res.ok and res.complete and res.final_ops == 2
    assert not res.one_minimal
    assert any("truncated" in w and "1-minimality" in w for w in res.why)
    # intermediate truncation alone does NOT forfeit: the final
    # history's complete frontier is what the claim is about
    full = shrink_history(spec, h, certificate=False)
    assert full.one_minimal and full.final_ops == 2


def test_deep_shrink_ratio_never_reads_as_never_shrank():
    from qsm_tpu.core.history import History
    from qsm_tpu.shrink.shrinker import ShrinkResult

    res = ShrinkResult(ok=True, verdict=0, history=History([]),
                       initial_ops=1024, final_ops=2)
    st = res.search_stats()
    assert st.shrink_ratio_pct == 1  # clamped: 0 is the sentinel
    from qsm_tpu.search.stats import SearchStats

    merged = SearchStats().absorb(st)
    assert merged.shrink_ratio_pct == 1  # survives the min-merge guard


# --- stats threading ------------------------------------------------------

def test_shrink_stats_thread_through_search_stats():
    spec, (h,) = _failing_history("cas", pids=4, ops=24)
    res = shrink_history(spec, h, certificate=False)
    st = collect_shrink_stats(res)
    assert st.shrink_rounds == res.rounds
    assert st.shrink_lanes == res.lanes_checked
    assert 0 < st.shrink_ratio_pct <= 100
    compact = st.to_compact()
    for key in ("shr", "shl", "shm", "sho"):
        assert key in compact
    t = st.to_timings()
    assert t["shrink_rounds"] == float(res.rounds)
    assert "shrink_ratio" in t
    # a record that never shrank emits NO shrink keys
    from qsm_tpu.search.stats import SearchStats

    assert "shrink_rounds" not in SearchStats().to_timings()


# --- the planner's decomposed-corpus segdc re-gate ------------------------

def test_planner_sub_segment_gate():
    from qsm_tpu.search.planner import (_DECOMPOSE_MEAN_SEGMENTS,
                                        _DECOMPOSE_MEAN_SEGMENTS_SUB,
                                        CorpusProfile, plan_search,
                                        profile_corpus)

    # the pinned threshold (provenance in planner.py: kv-64 subs 1.65,
    # kv-256 subs 4.26, multireg-64 subs 1.77 — all above; the gate
    # sits above the whole-history one because short sub-histories
    # benefit less per cut)
    assert _DECOMPOSE_MEAN_SEGMENTS_SUB == 1.35
    assert _DECOMPOSE_MEAN_SEGMENTS_SUB > _DECOMPOSE_MEAN_SEGMENTS

    spec, hs = _failing_history("kv", n=2, pids=8, ops=64)
    profile = profile_corpus(hs, spec)
    assert profile.sub_mean_segments > 0  # measured, not defaulted

    # decompose_keys on + sub density BELOW the gate: segdc must stay
    # OFF even though the whole-history density clears ITS gate —
    # exactly the mis-gating the leftover named
    base = dict(n=4, max_ops=256, mean_ops=256.0, pending_fraction=0.0,
                cut_fraction=1.0, mean_segments=2.0, sub_max_ops=16,
                mean_partitions=8.0)
    plan = plan_search(spec, CorpusProfile(**base, sub_mean_segments=1.2),
                       platform="cpu")
    assert plan.decompose_keys and not plan.decompose
    assert any("sub-history" in w for w in plan.why)
    plan = plan_search(spec, CorpusProfile(**base, sub_mean_segments=1.6),
                       platform="cpu")
    assert plan.decompose_keys and plan.decompose
    # refused projection ⇒ the whole-history gate still rules
    rspec, _ = make("register", "atomic")
    plan = plan_search(rspec, CorpusProfile(**base, sub_mean_segments=0.0),
                       platform="cpu")
    assert not plan.decompose_keys and plan.decompose


# --- property-layer integration ------------------------------------------

def test_prop_concurrent_minimize_history_flag():
    from qsm_tpu.core.property import PropertyConfig, prop_concurrent

    spec, sut = make("register", "racy")
    cfg = PropertyConfig(n_trials=60, n_pids=2, max_ops=12, seed=0,
                         minimize_history=True)
    res = prop_concurrent(spec, sut, cfg)
    assert not res.ok and res.counterexample is not None
    cx = res.counterexample
    assert cx.minimized_history is not None
    assert len(cx.minimized_history) <= len(cx.history)
    oracle = WingGongCPU(memo=True)
    assert int(oracle.check_histories(
        spec, [cx.minimized_history])[0]) == int(Verdict.VIOLATION)
    # the shrink counters ride the per-run timings
    assert res.timings.get("shrink_rounds", 0) > 0
    assert "shrink_minimize" in res.timings
    # and the program-level counterexample is untouched (it replays)
    base = prop_concurrent(spec, sut, PropertyConfig(
        n_trials=60, n_pids=2, max_ops=12, seed=0))
    assert base.counterexample.history.fingerprint() \
        == cx.history.fingerprint()
    assert base.counterexample.minimized_history is None
    assert "shrink_rounds" not in base.timings


# --- serve: the shrink verb ----------------------------------------------

@pytest.fixture
def kv_failing():
    return _failing_history("kv", n=2, pids=8, ops=64)


def _serve(tmp_path, **kw):
    from qsm_tpu.serve.server import CheckServer

    return CheckServer(unix_path=str(tmp_path / "sock"), **kw).start()


def test_serve_shrink_identical_to_inprocess_and_banked(tmp_path,
                                                        kv_failing):
    from qsm_tpu.serve.client import CheckClient
    from qsm_tpu.serve.protocol import rows_to_history

    spec, hs = kv_failing
    kwargs = spec.spec_kwargs()
    srv = _serve(tmp_path)
    try:
        with CheckClient(srv.address, timeout_s=120) as c:
            for h in hs:
                r = c.shrink("kv", h, spec_kwargs=kwargs,
                             certificate=True, deadline_s=120)
                assert r["ok"] and r["complete"] and r["one_minimal"]
                inproc = shrink_history(spec, h, certificate=False)
                assert rows_to_history(r["history"]).fingerprint() \
                    == inproc.history.fingerprint()
                audit = verify_certificate(
                    spec, rows_to_history(r["history"]),
                    r["certificate"])
                assert audit["one_minimal_proved"]
            # duplicate: answered O(1) from the shrink bank
            r2 = c.shrink("kv", hs[0], spec_kwargs=kwargs,
                          certificate=True)
            assert r2.get("cached") is True
            st = c.stats()["stats"]["shrink"]
            assert st["requests"] == len(hs) + 1
            assert st["bank_hits"] == 1 and st["rounds"] > 0
    finally:
        srv.stop()


class _SlowBackend:
    """Delegates to the memo oracle after a fixed stall per dispatch —
    the mid-shrink deadline bait."""

    name = "slow"

    def __init__(self, spec, stall_s=0.35):
        self.oracle = WingGongCPU(memo=True)
        self.stall_s = stall_s

    def check_histories(self, spec, histories):
        time.sleep(self.stall_s)
        return self.oracle.check_histories(spec, histories)


def test_serve_shrink_deadline_mid_shrink_returns_best_so_far(
        tmp_path, kv_failing):
    from qsm_tpu.serve.client import CheckClient
    from qsm_tpu.serve.protocol import rows_to_history

    spec, hs = kv_failing
    srv = _serve(tmp_path, engine_factory=lambda s: _SlowBackend(s))
    try:
        with CheckClient(srv.address, timeout_s=30) as c:
            # the input check (~one stall) fits; the first frontier
            # round cannot — the verb must answer best-so-far honestly,
            # not a wrong/fabricated minimization and not a bare drop
            r = c.shrink("kv", hs[0], spec_kwargs=spec.spec_kwargs(),
                         deadline_s=0.6)
            assert r["ok"] is True and r["complete"] is False
            assert r["one_minimal"] is False
            assert any("shed" in w or "deadline" in w for w in r["why"])
            # best-so-far here is the untouched input — still the exact
            # history the client sent, never a partial fabrication
            assert rows_to_history(r["history"]).fingerprint() \
                == hs[0].fingerprint()
            # a deadline already gone at admission SHEDs like check
            r0 = c.shrink("kv", hs[0], spec_kwargs=spec.spec_kwargs(),
                          deadline_s=0.0)
            assert r0["ok"] is False and r0.get("shed") is True
    finally:
        srv.stop()


# --- CLI ------------------------------------------------------------------

def test_shrink_cli_roundtrip(tmp_path, capsys, kv_failing):
    from qsm_tpu.serve.protocol import history_to_rows
    from qsm_tpu.utils.cli import main

    spec, hs = kv_failing
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({
        "model": "kv", "spec_kwargs": spec.spec_kwargs(),
        "history": history_to_rows(hs[0])}))
    out_path = tmp_path / "min.json"
    rc = main(["shrink", "--trace", str(trace), "--certificate",
               "--save", str(out_path)])
    out = capsys.readouterr().out.strip().splitlines()
    doc = json.loads(out[-1])
    assert rc == 0
    assert doc["verdict"] == "VIOLATION" and doc["one_minimal"]
    assert doc["final_ops"] < doc["initial_ops"]
    assert doc["certificate_audit"]["one_minimal_proved"]
    assert doc["search"]["shr"] == doc["rounds"]
    saved = json.loads(out_path.read_text())
    assert saved["model"] == "kv" and saved["history"] == doc["history"]
    # the saved minimized trace round-trips through `check` as the
    # violation it claims to be
    rc = main(["check", "--trace", str(out_path)])
    assert rc == 1
    doc2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc2["verdict"] == "VIOLATION"


# --- lint family h --------------------------------------------------------

def test_shrink_lint_fixture_and_twin():
    import qsm_tpu.analysis.fixtures as fixtures
    from qsm_tpu.analysis.shrink_passes import check_shrink_file

    findings = [f for f in check_shrink_file(fixtures.__file__)
                if f.rule_id == "QSM-SHRINK-UNBOUNDED"]
    assert len(findings) == 1
    assert "frontier_forever" in findings[0].location


def test_shrink_live_tree_clean_and_family_registered():
    import qsm_tpu.shrink.frontier as frontier
    import qsm_tpu.shrink.shrinker as shrinker
    from qsm_tpu.analysis.engine import FAMILIES
    from qsm_tpu.analysis.shrink_passes import check_shrink_file

    fam = FAMILIES["h"]
    assert fam.key == "shrink"
    scanned = set(fam.files)
    assert "qsm_tpu/shrink/frontier.py" in scanned
    assert "tools/bench_shrink.py" in scanned
    # the race family's whole-program scan covers the plane too
    assert "qsm_tpu/shrink/shrinker.py" in FAMILIES["g"].files
    # and family (a) re-validates projections on shrink changes
    assert any(t.startswith("qsm_tpu/shrink") for t in FAMILIES["a"].triggers)
    for mod in (frontier, shrinker):
        assert check_shrink_file(mod.__file__) == []
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(frontier.__file__))), "..", "tools",
        "bench_shrink.py")
    assert check_shrink_file(os.path.normpath(bench)) == []
