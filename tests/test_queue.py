"""Bounded FIFO queue (config #4, BASELINE.json:10): vector-state spec;
correct impl passes, the two-phase dequeue duplicates heads and fails."""

import pytest

import numpy as np

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     generate_program, prop_concurrent, run_concurrent,
                     sequential_history)
from qsm_tpu.models.queue import (DEQ, ENQ, AtomicQueueSUT, QueueSpec,
                                  RacyTwoPhaseQueueSUT)
from qsm_tpu.ops.jax_kernel import JaxTPU

SPEC = QueueSpec(capacity=3, n_values=4)
CFG = PropertyConfig(n_trials=60, n_pids=8, max_ops=48, seed=11)


def test_step_py_fifo_semantics():
    s = list(SPEC.initial_state())
    s, ok = SPEC.step_py(s, ENQ, 2, 0)
    assert ok and s == [1, 2, 0, 0]
    s, ok = SPEC.step_py(s, ENQ, 3, 0)
    assert ok and s == [2, 2, 3, 0]
    s, ok = SPEC.step_py(s, DEQ, 0, 2)
    assert ok and s == [1, 3, 0, 0]  # head out, canonical zero tail
    s, ok = SPEC.step_py(s, DEQ, 0, SPEC.EMPTY)
    assert not ok  # queue wasn't empty: sentinel response is wrong
    s2, ok = SPEC.step_py([0, 0, 0, 0], DEQ, 0, SPEC.EMPTY)
    assert ok and s2 == [0, 0, 0, 0]
    full = [3, 1, 2, 3]
    s3, ok = SPEC.step_py(full, ENQ, 1, 1)
    assert ok and s3 == full  # FULL response, unchanged


def test_step_jax_matches_py():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    step = jax.jit(SPEC.step_jax)
    for _ in range(300):
        length = int(rng.integers(0, SPEC.capacity + 1))
        slots = [int(rng.integers(0, SPEC.n_values)) if i < length else 0
                 for i in range(SPEC.capacity)]
        state = [length] + slots
        cmd = int(rng.integers(0, 2))
        arg = int(rng.integers(0, SPEC.CMDS[cmd].n_args))
        resp = int(rng.integers(0, SPEC.CMDS[cmd].n_resps))
        py_s, py_ok = SPEC.step_py(state, cmd, arg, resp)
        jx_s, jx_ok = step(jnp.asarray(state, jnp.int32),
                           jnp.int32(cmd), jnp.int32(arg), jnp.int32(resp))
        assert list(map(int, jx_s)) == list(py_s), (state, cmd, arg, resp)
        assert bool(jx_ok) == py_ok, (state, cmd, arg, resp)


def test_golden_duplicate_dequeue_rejected():
    # enq 1; two sequential deqs both claiming the head → not linearizable
    h = sequential_history([
        (0, ENQ, 1, 0),
        (0, DEQ, 0, 1),
        (1, DEQ, 0, 1),
    ])
    assert check_one(WingGongCPU(), SPEC, h) == Verdict.VIOLATION


def test_atomic_queue_passes():
    res = prop_concurrent(SPEC, AtomicQueueSUT(SPEC), CFG)
    assert res.ok, res.counterexample


def test_racy_queue_fails_and_shrinks():
    res = prop_concurrent(SPEC, RacyTwoPhaseQueueSUT(SPEC), CFG)
    assert not res.ok, "duplicate dequeues were never caught"
    cx = res.counterexample
    assert check_one(WingGongCPU(), SPEC, cx.history) == Verdict.VIOLATION
    assert any(op.cmd == DEQ for op in cx.program.ops), cx.program


@pytest.mark.slow
def test_queue_backend_parity():
    from conftest import assert_backend_parity

    hists = []
    for seed in range(25):
        prog = generate_program(SPEC, seed=seed, n_pids=6, max_ops=32)
        for sut in (AtomicQueueSUT(SPEC), RacyTwoPhaseQueueSUT(SPEC)):
            hists.append(run_concurrent(sut, prog, seed=f"q{seed}"))
    # the deepest violating history in this corpus needs ~1M kernel
    # iterations to exhaust; raise the budget so raw verdicts stay
    # bit-identical (default-budget users get honest BUDGET_EXCEEDED,
    # resolved by the oracle in the property layer)
    assert_backend_parity(SPEC, hists, JaxTPU(SPEC, budget=5_000_000))
