"""Root splitting (ops/rootsplit.py): the first-op decomposition must
partition the search exactly — verdict parity with the oracle through
both host and device inners — and the frontier bookkeeping (dedupe,
all-roots-die, pending routing) must hold."""

import numpy as np

from qsm_tpu import (Verdict, WingGongCPU, generate_program, run_concurrent,
                     sequential_history)
from qsm_tpu.core.history import History, Op
from qsm_tpu.models.cas import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.models.register import READ, WRITE, RegisterSpec
from qsm_tpu.ops.jax_kernel import JaxTPU
from qsm_tpu.ops.rootsplit import RootSplit, split_history

SPEC = CasSpec(n_values=5)


def _corpus(n=40, n_pids=8, max_ops=24):
    hists = []
    for seed in range(n // 2):
        prog = generate_program(SPEC, seed=seed, n_pids=n_pids,
                                max_ops=max_ops)
        for sut in (AtomicCasSUT(SPEC), RacyCasSUT(SPEC)):
            hists.append(run_concurrent(sut, prog, seed=f"rs{seed}"))
    return hists


def test_split_children_are_first_choice_partition():
    rspec = RegisterSpec(n_values=5)
    # two overlapping ops: both minimal, both ok as the FIRST choice
    # (read -> 0 sees the initial value; write -> 0 is uncondition-ok), so
    # two children of one op each
    h = History([Op(0, WRITE, 3, 0, 0, 5), Op(1, READ, 0, 0, 1, 2)])
    kids = split_history(rspec, h, depth=1)
    assert kids is not None and len(kids) == 2
    assert all(len(k.ops) == 1 for k, _ in kids)
    states = sorted(s for _, s in kids)
    assert states == [(0,), (3,)]  # read-first keeps 0, write-first sets 3

    # sequential history: only ONE minimal op at the root
    h2 = sequential_history([(0, WRITE, 2, 0), (0, READ, 0, 2)])
    kids2 = split_history(rspec, h2, depth=1)
    assert kids2 is not None and len(kids2) == 1


def test_split_all_roots_die_is_violation():
    rspec = RegisterSpec(n_values=5)
    # single op whose postcondition fails from the initial state: read -> 4
    h = sequential_history([(0, READ, 0, 4)])
    assert split_history(rspec, h, depth=1) == []
    rs = RootSplit(rspec, WingGongCPU(memo=True), min_ops=0, eager=True)
    assert rs.check_histories(rspec, [h])[0] == int(Verdict.VIOLATION)
    assert rs.split_histories == 1


def test_split_depth2_dedupes_permutations():
    rspec = RegisterSpec(n_values=5)
    # two overlapping READS of the initial value: both orders reach the
    # same (empty-rest, state) configuration -> deduped to fewer children
    h = History([Op(0, READ, 0, 0, 0, 5), Op(1, READ, 0, 0, 1, 4)])
    kids = split_history(rspec, h, depth=2)
    assert kids is not None and len(kids) == 1  # not 2


def test_pending_histories_route_whole():
    rspec = RegisterSpec(n_values=5)
    h = History([Op(0, WRITE, 1, -1, 0, 1 << 30),
                 Op(1, READ, 0, 1, 2, 3)])
    assert split_history(rspec, h, depth=1) is None
    rs = RootSplit(rspec, WingGongCPU(memo=True), min_ops=0, eager=True)
    want = WingGongCPU().check_histories(rspec, [h])
    np.testing.assert_array_equal(rs.check_histories(rspec, [h]), want)


def test_rootsplit_parity_host_inner_eager():
    hists = _corpus()
    want = WingGongCPU(memo=True).check_histories(SPEC, hists)
    for depth in (1, 2):
        rs = RootSplit(SPEC, WingGongCPU(memo=True), depth=depth,
                       min_ops=0, eager=True)
        got = rs.check_histories(SPEC, hists)
        np.testing.assert_array_equal(got, want, err_msg=f"depth={depth}")
        assert rs.split_histories > 0 and rs.children_checked > 0
    assert (want == int(Verdict.VIOLATION)).any()
    assert (want == int(Verdict.LINEARIZABLE)).any()


def test_rootsplit_parity_device_inner_eager():
    hists = _corpus(n=20, max_ops=20)
    want = WingGongCPU(memo=True).check_histories(SPEC, hists)
    rs = RootSplit(SPEC, JaxTPU(SPEC), depth=1, min_ops=0, eager=True)
    got = rs.check_histories(SPEC, hists)
    # the device inner may defer (BUDGET_EXCEEDED) — decided must agree
    undecided = got == int(Verdict.BUDGET_EXCEEDED)
    np.testing.assert_array_equal(got[~undecided], want[~undecided])
    assert (~undecided).sum() >= 0.9 * len(hists)
    assert rs.split_histories > 0


def test_rootsplit_escalation_rescues_budget_lanes():
    """Escalation (the default): a budget-starved device inner defers
    some histories; splitting multiplies the effective per-lane budget by
    the fanout, so the combinator decides strictly more of them — and
    every decided verdict still matches the oracle."""
    hists = _corpus(n=40, max_ops=24)
    want = WingGongCPU(memo=True).check_histories(SPEC, hists)

    def starved():
        return JaxTPU(SPEC, budget=150, mid_budget=0, rescue_budget=0)

    plain = starved().check_histories(SPEC, hists)
    n_undecided_plain = int((plain == int(Verdict.BUDGET_EXCEEDED)).sum())
    assert n_undecided_plain > 0, "corpus too easy to exercise escalation"

    rs = RootSplit(SPEC, starved(), depth=1)
    got = rs.check_histories(SPEC, hists)
    undecided = got == int(Verdict.BUDGET_EXCEEDED)
    np.testing.assert_array_equal(got[~undecided], want[~undecided])
    assert int(undecided.sum()) < n_undecided_plain
    assert rs.split_histories > 0
