"""SearchStats.absorb / stats_delta merge semantics over the FULL
compact-key set (ISSUE 11 satellite).

The absorb rules are load-bearing for every bench row and ``qsm-tpu
stats`` aggregate — a composed engine's cost record is built by folding
sub-engine records, and a field merged with the wrong rule silently
corrupts every artifact downstream.  Three rule classes exist and each
is pinned here field-by-field:

* ADDITIVE counters (the bulk): ``a.absorb(b)`` sums them;
* the MAX field ``pcomp_max_sub`` (compact ``pcm``): the composed
  record's worst sub-history is the worst either side saw;
* the MIN-merged ratio ``shrink_ratio_pct`` (compact ``sho``): the
  composed record keeps the BEST shrink, with 0 = "never shrank"
  treated as absent, not as a minimum;

plus the first-wins strings (``plan``/``fallback_engine``), the OR'd
``ordering`` flag, the ``count_histories`` gate, and ``stats_delta``'s
counter-subtraction with its keep-``after`` exemptions for the
max/ratio fields.  The span-bridge counter ``obs_events`` (compact
``obe``) and the four monitor-session counters ``session_events`` /
``frontier_advances`` / ``flips_pushed`` / ``prefix_hits`` (compact
``sev``/``fad``/``flp``/``pfh`` — ISSUE 14) ride the additive class.
"""

from __future__ import annotations

import dataclasses

import pytest

from qsm_tpu.search.stats import (SearchStats, _COUNTER_FIELDS,
                                  collect_search_stats, stats_delta)

# every additive counter absorb() folds (histories is additive too but
# gated behind count_histories — tested separately)
_ADDITIVE = ("lockstep_iters", "nodes_explored", "memo_prunes",
             "memo_inserts", "compactions", "chunk_rounds", "rescued",
             "deferred", "tail_histories", "segments_split",
             "segments_total", "degradations", "retries",
             "worker_faults", "node_faults", "lease_faults",
             "pcomp_split", "pcomp_subs",
             "pcomp_recombine_ms", "shrink_rounds", "shrink_lanes",
             "shrink_memo_hits", "obs_events", "session_events",
             "frontier_advances", "flips_pushed", "prefix_hits",
             "gen_seqs", "gen_mutations", "gen_flips",
             "gen_feedback_rounds")


def _filled(base: int) -> SearchStats:
    """A record with every numeric field set to a distinct value
    derived from ``base`` — any field merged with the wrong rule (or
    dropped) produces a visibly wrong number."""
    st = SearchStats(engine=f"e{base}", histories=base)
    for i, f in enumerate(_ADDITIVE):
        setattr(st, f, base * 100 + i)
    st.pcomp_max_sub = base * 7
    st.shrink_ratio_pct = base * 11
    return st


def test_every_dataclass_counter_is_classified():
    """Completeness gate: a counter added to SearchStats without an
    absorb/delta classification would silently merge wrong.  Every
    non-string, non-bool numeric field must be either additive, the
    max field, the ratio field, or the gated histories count."""
    classified = set(_ADDITIVE) | {"histories", "pcomp_max_sub",
                                   "shrink_ratio_pct"}
    numeric = {
        f.name for f in dataclasses.fields(SearchStats)
        if f.type == "int" and f.name not in ("",)
    }
    assert numeric == classified
    # stats_delta subtracts exactly the additive set + histories; the
    # max/ratio fields keep `after` by design
    assert set(_COUNTER_FIELDS) == set(_ADDITIVE) | {"histories"}


def test_absorb_additive_fields_sum():
    a, b = _filled(1), _filled(2)
    a.absorb(b)
    for i, f in enumerate(_ADDITIVE):
        assert getattr(a, f) == (100 + i) + (200 + i), f


def test_absorb_histories_gated_by_count_histories():
    a, b = _filled(1), _filled(2)
    a.absorb(b)
    assert a.histories == 1                 # default: wrapper counts
    a2, b2 = _filled(1), _filled(2)
    a2.absorb(b2, count_histories=True)
    assert a2.histories == 3


def test_absorb_pcomp_max_sub_is_max_not_sum():
    a, b = _filled(1), _filled(2)
    a.absorb(b)
    assert a.pcomp_max_sub == 14            # max(7, 14), never 21
    c, d = _filled(3), _filled(1)
    c.absorb(d)
    assert c.pcomp_max_sub == 21            # larger side already held


@pytest.mark.parametrize("mine,theirs,want", [
    (30, 20, 20),   # both shrank: keep the BEST (smallest) ratio
    (20, 30, 20),
    (0, 40, 40),    # 0 = "never shrank" adopts the other side
    (40, 0, 40),    # ...and is never treated as a minimum
    (0, 0, 0),
])
def test_absorb_shrink_ratio_min_merges_with_zero_as_absent(
        mine, theirs, want):
    a, b = SearchStats(), SearchStats()
    a.shrink_ratio_pct, b.shrink_ratio_pct = mine, theirs
    a.absorb(b)
    assert a.shrink_ratio_pct == want


def test_absorb_strings_first_wins_and_ordering_ors():
    a = SearchStats(plan="", fallback_engine="", ordering=False)
    b = SearchStats(plan="cpu-fine-v1", fallback_engine="memo",
                    ordering=True)
    a.absorb(b)
    assert a.plan == "cpu-fine-v1"
    assert a.fallback_engine == "memo"
    assert a.ordering is True
    # an already-set plan/fallback is NOT overwritten by the inner's
    c = SearchStats(plan="outer", fallback_engine="cpp")
    c.absorb(b)
    assert c.plan == "outer" and c.fallback_engine == "cpp"


def test_absorb_none_is_identity():
    a = _filled(1)
    before = dataclasses.asdict(a)
    assert a.absorb(None) is a
    assert dataclasses.asdict(a) == before


def test_stats_delta_subtracts_counters_keeps_max_and_ratio():
    before = _filled(1)
    after = _filled(3)
    d = stats_delta(after, before)
    for i, f in enumerate(_ADDITIVE):
        assert getattr(d, f) == (300 + i) - (100 + i), f
    assert d.histories == 2
    # a maximum/ratio has no per-run difference: keep `after` verbatim
    assert d.pcomp_max_sub == after.pcomp_max_sub == 21
    assert d.shrink_ratio_pct == after.shrink_ratio_pct == 33
    # `after`'s originals are untouched (replace, not mutate)
    assert after.nodes_explored == 301


def test_stats_delta_none_handling():
    assert stats_delta(None, _filled(1)) is None
    st = _filled(2)
    assert stats_delta(st, None) is st


def test_to_compact_full_key_set_and_values():
    """The compact record bench rows embed: every key pinned, so a
    renamed or dropped key breaks HERE, not in an archived artifact."""
    st = _filled(2)
    st.ordering = True
    st.plan = "p"
    st.fallback_engine = "memo"
    c = st.to_compact()
    assert sorted(c) == sorted(
        ("iph", "nph", "prunes", "rescued", "segs", "ord", "plan",
         "deg", "fb", "wf", "ndf", "lsf", "pcs", "pcn", "pcm", "shr",
         "shl", "shm", "sho", "obe", "sev", "fad", "flp", "pfh",
         "gsq", "gmu", "gfl", "gfr"))
    assert c["gsq"] == st.gen_seqs
    assert c["gmu"] == st.gen_mutations
    assert c["gfl"] == st.gen_flips
    assert c["gfr"] == st.gen_feedback_rounds
    assert c["pcm"] == st.pcomp_max_sub
    assert c["sho"] == st.shrink_ratio_pct
    assert c["obe"] == st.obs_events
    assert c["sev"] == st.session_events
    assert c["fad"] == st.frontier_advances
    assert c["flp"] == st.flips_pushed
    assert c["pfh"] == st.prefix_hits
    assert c["wf"] == st.worker_faults
    assert c["ndf"] == st.node_faults
    assert c["lsf"] == st.lease_faults
    assert c["iph"] == round(st.lockstep_iters / st.histories, 1)
    assert c["nph"] == round(st.nodes_explored / st.histories, 1)


def test_to_timings_gates_optional_blocks():
    """Zeros must NOT emit for the gated planes (pcomp/shrink/obs/
    resilience): a zero would claim the plane ran and did nothing on
    every unrelated run, and would clobber the property layer's own
    additive resilience accounting."""
    clean = SearchStats(histories=4, nodes_explored=8)
    t = clean.to_timings()
    assert "pcomp_subs" not in t
    assert "shrink_rounds" not in t
    assert "obs_events" not in t
    assert "session_events" not in t
    assert "gen_seqs" not in t
    assert "resilience_degradations" not in t
    full = _filled(2)
    t2 = full.to_timings()
    assert t2["pcomp_max_sub"] == float(full.pcomp_max_sub)
    assert t2["shrink_ratio"] == round(full.shrink_ratio_pct / 100, 3)
    assert t2["obs_events"] == float(full.obs_events)
    assert t2["resilience_worker_faults"] == float(full.worker_faults)
    assert t2["session_events"] == float(full.session_events)
    assert t2["prefix_hits"] == float(full.prefix_hits)
    assert t2["flips_pushed"] == float(full.flips_pushed)
    assert t2["gen_seqs"] == float(full.gen_seqs)
    assert t2["gen_flips"] == float(full.gen_flips)


def test_absorb_round_trips_through_collect_composition():
    """The collection path engines actually ride: a wrapper whose
    ``search_stats`` absorbs an inner's record reports the composed
    rules (additive + max + min-ratio) through collect_search_stats."""
    inner = _filled(2)

    class _Inner:
        def search_stats(self):
            return dataclasses.replace(inner)

    class _Wrapper:
        def __init__(self):
            self.inner = _Inner()

        def search_stats(self):
            st = _filled(1)
            st.absorb(self.inner.search_stats())
            return st

    st = collect_search_stats(_Wrapper())
    assert st.nodes_explored == 101 + 201
    assert st.pcomp_max_sub == 14
    assert st.shrink_ratio_pct == 11
    assert st.obs_events == (100 + _ADDITIVE.index("obs_events")) + (
        200 + _ADDITIVE.index("obs_events"))
