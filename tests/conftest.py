"""Test-wide environment: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; sharding code is validated on a
virtual CPU mesh exactly as the build instructions prescribe.  Must run
before any ``import jax`` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
