"""Test-wide environment: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; sharding code is validated on a
virtual CPU mesh exactly as the build instructions prescribe.

Two environment quirks make this trickier than setting ``JAX_PLATFORMS``:

* The image ships ``JAX_PLATFORMS=axon`` plus a sitecustomize that registers
  the axon TPU plugin in every interpreter, so ``setdefault`` is a no-op and
  even an explicit env override is ignored once the plugin registered.
  ``jax.config.update("jax_platforms", "cpu")`` *after* import does win.
* Initializing the axon backend contacts the single-chip tunnel; doing that
  from test workers can wedge (and a wedged tunnel then hangs every later
  ``jax.devices()``).  Forcing cpu before any device query keeps the tests
  entirely off the chip — which is also the point: tests must not depend on
  TPU availability (bench.py owns the real-chip path).
"""

N_DEVICES = 8

# One construction site for the force-cpu dance (env flags + config update);
# it replaces any pre-existing (possibly smaller) device count: this file's
# contract is "at least an 8-device mesh", not "whatever the caller exported".
from qsm_tpu.utils.device import force_cpu_platform  # noqa: E402

force_cpu_platform(N_DEVICES)

import jax  # noqa: E402  (must follow the platform forcing above)

assert jax.devices()[0].platform == "cpu" and len(jax.devices()) >= N_DEVICES, (
    "conftest failed to materialize the 8-device virtual CPU mesh; "
    f"got {jax.devices()}")


# ---------------------------------------------------------------------------
# Shared helpers
# ---------------------------------------------------------------------------

def assert_backend_parity(spec, histories, device_backend, oracle=None,
                          expect_violations=True):
    """Assert device verdicts == oracle verdicts on ``histories`` and that
    the sample isn't vacuous (SURVEY.md §4: cross-backend parity suite)."""
    from qsm_tpu import Verdict, WingGongCPU

    oracle = oracle or WingGongCPU()
    cpu = oracle.check_histories(spec, histories)
    dev = device_backend.check_histories(spec, histories)
    mismatch = [(i, int(c), int(d))
                for i, (c, d) in enumerate(zip(cpu, dev)) if c != d]
    assert not mismatch, f"CPU/device verdict mismatches: {mismatch}"
    assert (cpu == Verdict.LINEARIZABLE).any(), "parity sample vacuous: no passes"
    if expect_violations:
        assert (cpu == Verdict.VIOLATION).any(), "parity sample vacuous: no fails"
    return cpu
