"""Fleet tier (qsm_tpu/fleet, ISSUE 12): routing identity, node-loss
re-dispatch, quarantine/re-admission, the segmented replicated verdict
log with anti-entropy catch-up, SHED fleet blocks, and the
kill-a-node acceptance (flight dump names the doomed trace ids and the
span log shows the hop off the dead node)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from qsm_tpu.fleet.membership import HashRing, Membership
from qsm_tpu.fleet.replog import SegmentedLog, segment_fingerprint
from qsm_tpu.fleet.router import FleetRouter
from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.obs import Observability, load_dump, load_events, \
    recent_events
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.resilience.policy import preset
from qsm_tpu.serve.cache import VerdictCache, fingerprint_key
from qsm_tpu.serve.client import CheckClient
from qsm_tpu.serve.protocol import VERDICT_NAMES
from qsm_tpu.serve.server import CheckServer
from qsm_tpu.utils.corpus import build_corpus

SPEC = CasSpec()


@pytest.fixture(scope="module")
def corpus():
    return build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=12,
                        n_pids=4, max_ops=10, seed_base=0,
                        seed_prefix="fleet")


@pytest.fixture(scope="module")
def expected(corpus):
    oracle = WingGongCPU(memo=True)
    return [VERDICT_NAMES[int(v)]
            for v in oracle.check_histories(SPEC, corpus)]


def _failing_history():
    oracle = WingGongCPU(memo=True)
    pool = build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=24,
                        n_pids=6, max_ops=16, seed_base=0,
                        seed_prefix="bench_fleet_shrink")
    for h in pool:
        if int(oracle.check_histories(SPEC, [h])[0]) == 0:
            return h
    raise AssertionError("seeded pool produced no violation")


def _fleet(tmp_path, n_nodes=2, seal_rows=8, router_kw=None,
           node_kw=None):
    nodes = [CheckServer(node_id=f"n{i}",
                         replog_dir=str(tmp_path / f"replog{i}"),
                         replog_seal_rows=seal_rows, flush_s=0.005,
                         **(node_kw or {})).start()
             for i in range(n_nodes)]
    router = FleetRouter(
        [(s.node_id, s.address) for s in nodes],
        policy=preset("fleet-route").with_(timeout_s=3.0),
        probe_policy=preset("fleet-probe").with_(timeout_s=1.0),
        heartbeat_s=0.2, anti_entropy_s=0.0,
        **(router_kw or {})).start()
    return router, nodes


def _teardown(router, nodes):
    router.stop()
    for s in nodes:
        s.stop()


# --- routing identity ------------------------------------------------------

def test_hash_ring_is_deterministic_and_stable_under_exclusion():
    ring = HashRing(["n0", "n1", "n2"], vnodes=32)
    allowed = {"n0", "n1", "n2"}
    keys = [f"key{i}" for i in range(200)]
    owners = {k: ring.node_for(k, allowed) for k in keys}
    assert owners == {k: ring.node_for(k, allowed) for k in keys}
    assert set(owners.values()) == allowed  # all nodes take traffic
    # consistent: excluding one node moves ONLY its keys
    for k in keys:
        moved = ring.node_for(k, allowed, exclude={"n1"})
        if owners[k] != "n1":
            assert moved == owners[k]
        else:
            assert moved in ("n0", "n2")
    assert ring.node_for("x", set()) is None


def test_membership_quarantine_and_readmission():
    """One-way quarantine after repeated failures; re-admission only
    on SUSTAINED health (readmit_after consecutive good probes)."""
    m = Membership([("n0", "unused:1"), ("n1", "unused:2")],
                   quarantine_after=3, readmit_after=2)
    err = RuntimeError("boom")
    m.note_failure("n0", err)
    # one failure is suspicion, not death (down_after grace): the node
    # stays routable so a single slow probe can't flap its keys away
    assert "n0" in m.healthy_ids()
    m.note_failure("n0", err)
    assert "n0" not in m.healthy_ids()     # down after the streak
    assert not m._nodes["n0"].quarantined  # but not yet quarantined
    # an empty healthy set never starves routing: non-quarantined
    # nodes stay routable (the dispatch ladder handles true death)
    m.note_failure("n1", err)
    m.note_failure("n1", err)
    assert m.healthy_ids() == set()
    assert m.routable_ids() == {"n0", "n1"}
    m.note_success("n1")
    m.note_failure("n0", err)
    assert m._nodes["n0"].quarantined
    assert m.shed_state() == {"nodes": 2, "live": 1, "quarantined": 1}
    # one good answer is luck, not health
    m.note_success("n0")
    assert "n0" not in m.healthy_ids()
    m.note_success("n0")
    assert "n0" in m.healthy_ids()
    assert m.readmissions == 1
    # a fresh failure streak needs the full threshold again
    m.note_failure("n0", err)
    assert not m._nodes["n0"].quarantined


# --- the routed check path -------------------------------------------------

def test_routed_verdicts_match_oracle_and_stamp_nodes(tmp_path, corpus,
                                                      expected):
    router, nodes = _fleet(tmp_path, n_nodes=2)
    try:
        with CheckClient(router.address, timeout_s=60.0) as c:
            res = c.check("cas", corpus)
            assert res["ok"]
            assert res["verdicts"] == expected
            assert res["node"] == "router"          # egress stamp
            assert sum(res["nodes"].values()) == len(corpus)
            assert set(res["nodes"]) <= {"n0", "n1"}
            # identical traffic routes to the same nodes: every lane a
            # banked O(1) hit the second time (the hot-cache identity)
            res2 = c.check("cas", corpus)
            assert res2["verdicts"] == expected
            assert all(res2["cached"])
            # witnesses ride through the router unchanged
            resw = c.check("cas", corpus[:4], witness=True)
            assert resw["verdicts"] == expected[:4]
            assert len(resw["witnesses"]) == 4
            # shrink routes to the owner node and answers 1-minimal
            viol = _failing_history()
            sres = c.shrink("cas", viol)
            assert sres["ok"] and sres["verdict"] == "VIOLATION"
            assert sres["final_ops"] <= len(viol)
            assert sres["node"] in ("n0", "n1")
            # stats carries the fleet view
            st = c.stats()["stats"]
            assert st["role"] == "router"
            assert sorted(st["fleet_nodes"]) == ["n0", "n1"]
    finally:
        _teardown(router, nodes)


def test_pcomp_split_traffic_routes_and_matches(tmp_path):
    """kv traffic decomposes into per-key sub-lanes ON the nodes; the
    routed whole-history verdicts still match the oracle."""
    from qsm_tpu.models.registry import MODELS

    entry = MODELS["kv"]
    spec = entry.make_spec()
    hists = build_corpus(spec,
                         (entry.impls["atomic"], entry.impls["racy"]),
                         n=6, n_pids=8, max_ops=24, seed_base=100,
                         seed_prefix="fleet_kv")
    oracle = WingGongCPU(memo=True)
    want = [VERDICT_NAMES[int(v)]
            for v in oracle.check_histories(spec, hists)]
    router, nodes = _fleet(tmp_path, n_nodes=2)
    try:
        with CheckClient(router.address, timeout_s=120.0) as c:
            res = c.check("kv", hists)
            assert res["ok"] and res["verdicts"] == want
        assert any(s.pcomp_split > 0 for s in nodes)  # really split
    finally:
        _teardown(router, nodes)


def test_fleet_shed_carries_node_state_block(tmp_path, corpus):
    router, nodes = _fleet(tmp_path, n_nodes=2,
                           router_kw={"queue_depth": 1})
    try:
        with CheckClient(router.address, timeout_s=30.0) as c:
            res = c.check("cas", corpus)  # 12 lanes > depth 1
            assert res.get("shed") and not res.get("ok")
            assert res["node"] == "router"
            assert res["fleet"]["nodes"] == 2
            assert res["fleet"]["live"] == 2
            assert "trace" in res
    finally:
        _teardown(router, nodes)


def test_full_partition_degrades_to_ladder(tmp_path, corpus, expected,
                                           monkeypatch):
    """partition:node@1 drops EVERY router→node exchange both
    directions: the exclude-and-re-dispatch ladder runs dry and the
    router's own in-process host ladder answers — exact verdicts,
    node_faults counted, fault site fired."""
    router, nodes = _fleet(tmp_path, n_nodes=2)
    try:
        monkeypatch.setenv("QSM_TPU_FAULTS", "partition:node@1")
        with CheckClient(router.address, timeout_s=60.0) as c:
            res = c.check("cas", corpus)
            assert res["ok"] and res["verdicts"] == expected
            assert res["node_faults"] >= 1
            assert res["nodes"] == {"router": len(corpus)}
            assert any(b["flush"] == "ladder" for b in res["batches"])
            # the batch cost record says the batch survived node loss
            assert any(b.get("search", {}).get("ndf", 0) >= 1
                       for b in res["batches"])
        monkeypatch.delenv("QSM_TPU_FAULTS")
        st = router.stats()
        assert st["node_faults"] >= 1
        assert st["ladder_lanes"] >= len(corpus)
    finally:
        _teardown(router, nodes)


def test_partial_partition_redispatches_to_survivor(tmp_path, corpus,
                                                    expected,
                                                    monkeypatch):
    """partition:node@2 (the link dies mid-request and STAYS dead):
    whatever sub-request hits it re-dispatches — to the other node if
    its link still answers, else down to the ladder — with a
    route.hop span either way, and verdicts exact."""
    trace_log = str(tmp_path / "trace.jsonl")
    router, nodes = _fleet(tmp_path, n_nodes=2,
                           router_kw={"trace_log": trace_log})
    try:
        monkeypatch.setenv("QSM_TPU_FAULTS", "partition:node@2")
        with CheckClient(router.address, timeout_s=60.0) as c:
            res = c.check("cas", corpus)
            assert res["ok"] and res["verdicts"] == expected
        monkeypatch.delenv("QSM_TPU_FAULTS")
        router.obs.tracer.close()
        events = load_events(trace_log, trace_id=res["trace"])
        hops = [e for e in events if e.get("name") == "route.hop"]
        assert hops, "re-dispatch must leave a route.hop span"
    finally:
        _teardown(router, nodes)


# --- the kill-a-node acceptance (subprocess nodes, real SIGKILL) ----------

def _spawn_node(nid: str, tmp_path) -> tuple:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("QSM_TPU_FAULTS", None)
    unix = str(tmp_path / f"{nid}.sock")
    proc = subprocess.Popen(
        [sys.executable, "-m", "qsm_tpu", "serve", "--unix", unix,
         "--node-id", nid,
         "--replog-dir", str(tmp_path / f"replog_{nid}")],
        stdout=subprocess.PIPE, text=True, env=env)
    banner = json.loads(proc.stdout.readline())
    assert banner["serving"] == unix
    return proc, unix


def test_sigkill_node_mid_soak_redispatches_with_artifacts(tmp_path,
                                                           corpus,
                                                           expected):
    """THE acceptance pin: a mid-soak SIGKILLed node produces a flight
    dump naming the re-dispatched trace ids, and the span log (what
    ``qsm-tpu trace <id>`` renders) shows the hop from the dead node
    to the surviving one — while every verdict stays exact."""
    procs = {}
    for nid in ("n0", "n1"):
        procs[nid] = _spawn_node(nid, tmp_path)
    trace_log = str(tmp_path / "router_trace.jsonl")
    flight_dir = str(tmp_path / "flight")
    router = FleetRouter(
        [(nid, unix) for nid, (_p, unix) in procs.items()],
        policy=preset("fleet-route").with_(timeout_s=2.0),
        probe_policy=preset("fleet-probe").with_(timeout_s=1.0),
        heartbeat_s=0.3, anti_entropy_s=0.0,
        trace_log=trace_log, flight_dir=flight_dir).start()
    try:
        # the victim: whichever node owns the first history's key
        key = fingerprint_key(SPEC, corpus[0])
        victim = router.membership.node_for(key)
        survivor = "n1" if victim == "n0" else "n0"
        wrong = []
        errors = []

        def drive():
            with CheckClient(router.address, timeout_s=60.0) as c:
                for _ in range(6):
                    res = c.check("cas", corpus)
                    if not res.get("ok"):
                        errors.append(res)
                    elif res["verdicts"] != expected:
                        wrong.append(res["verdicts"])

        t = threading.Thread(target=drive)
        t.start()
        time.sleep(0.2)
        os.kill(procs[victim][0].pid, signal.SIGKILL)
        t.join(120.0)
        assert not wrong and not errors, (wrong, errors)
        assert router.stats()["node_faults"] >= 1
        router.obs.tracer.close()
        # 1) the flight dump names the doomed dispatches' trace ids
        dumps = [f for f in sorted(os.listdir(flight_dir))
                 if "node_death" in f]
        assert dumps, os.listdir(flight_dir)
        doomed = []
        for name in dumps:
            dump = load_dump(os.path.join(flight_dir, name))
            for ev in recent_events(dump, "node"):
                at = ev.get("attrs") or {}
                if (ev.get("name") == "node.shed"
                        and at.get("node") == victim):
                    doomed.extend(at.get("traces") or [])
        assert doomed, "dump must name the re-dispatched trace ids"
        # 2) qsm-tpu trace <id>: the hop off the dead node is visible
        hop = None
        for trace_id in doomed:
            for ev in load_events(trace_log, trace_id=trace_id):
                at = ev.get("attrs") or {}
                if (ev.get("name") == "route.hop"
                        and at.get("hop_from") == victim):
                    hop = at
        assert hop is not None
        assert hop["hop_to"] in (survivor, "ladder")
    finally:
        router.stop()
        for proc, _unix in procs.values():
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5.0)


# --- the replicated log ----------------------------------------------------

def test_replog_concurrent_catchup_banks_exactly_once(tmp_path):
    """Anti-entropy adoption CONCURRENT with live put_many: every
    adopted verdict lands on disk exactly once (in its adopted
    segment, never re-banked into the local active segment), and the
    live set holds each key exactly once."""
    a = SegmentedLog(str(tmp_path / "a"), node_id="a", seal_rows=4)
    ca = VerdictCache(max_entries=4096, store=a)
    ca.put_many([(f"ka{i}", i % 2, None) for i in range(16)])
    b = SegmentedLog(str(tmp_path / "b"), node_id="b", seal_rows=4)
    cb = VerdictCache(max_entries=4096, store=b)

    stop = threading.Event()
    put_batches = [0]

    def live_puts():
        i = 0
        while not stop.is_set():
            cb.put_many([(f"kb{i}_{j}", 0, None) for j in range(3)])
            put_batches[0] += 1
            i += 1
            time.sleep(0.001)

    t = threading.Thread(target=live_puts)
    t.start()
    try:
        for name in b.missing(a.digests()):
            got = a.read_segment(name)
            rows = b.adopt(name, got[0], got[1])
            cb.adopt_rows(rows)
    finally:
        stop.set()
        t.join(5.0)
    cb.flush()
    # adopting again is a no-op (idempotent catch-up)
    for name in a.digests():
        assert b.adopt(name, *a.read_segment(name)) == []
    # exactly-once on disk: ka* rows live ONLY in segments sealed by
    # node a; b's own segments carry only kb* rows
    on_disk = {}
    for name in b.digests():
        _fp, lines = b.read_segment(name)
        for ln in lines:
            row = json.loads(ln)
            on_disk.setdefault(row["key"], []).append(name)
    for key, segs in on_disk.items():
        if key.startswith("ka"):
            assert len(segs) == 1 and segs[0].startswith("seg-a-"), \
                (key, segs)
    # every adopted verdict present and correct in the live set
    for i in range(16):
        assert cb.get(f"ka{i}").verdict == i % 2
    # a restart reloads the union
    cb2 = VerdictCache(max_entries=4096,
                       store=SegmentedLog(str(tmp_path / "b"),
                                          node_id="b", seal_rows=4))
    for i in range(16):
        assert cb2.get(f"ka{i}").verdict == i % 2


def test_replog_torn_tail_truncated_not_replayed(tmp_path):
    log = SegmentedLog(str(tmp_path / "n"), node_id="n", seal_rows=100)
    c = VerdictCache(max_entries=100, store=log)
    c.put("good", 1, None)
    # a SIGKILL mid-append: half a row at the active tail
    with open(os.path.join(str(tmp_path / "n"), "active.jsonl"),
              "a") as f:
        f.write('{"key": "torn", "verd')
    log2 = SegmentedLog(str(tmp_path / "n"), node_id="n",
                        seal_rows=100)
    assert log2.truncated_tails == 1
    c2 = VerdictCache(max_entries=100, store=log2)
    assert c2.get("good").verdict == 1   # everything before the tear
    assert c2.get("torn") is None        # the torn row is NOT a verdict
    # and the truncation restored a clean boundary: appends keep working
    c2.put("after", 0, None)
    c3 = VerdictCache(max_entries=100,
                      store=SegmentedLog(str(tmp_path / "n"),
                                         node_id="n", seal_rows=100))
    assert c3.get("after").verdict == 0


def test_replog_compaction_during_catchup_keeps_later_row_wins(
        tmp_path):
    """Compaction concurrent with catch-up: the post-merge entry (the
    later local row's verdict + the banked witness) survives, the
    absorbed segments are remembered so the anti-entropy diff never
    re-pulls them."""
    a = SegmentedLog(str(tmp_path / "a"), node_id="a", seal_rows=2)
    ca = VerdictCache(max_entries=4096, store=a)
    ca.put_many([(f"x{i}", 1, None) for i in range(4)])
    b = SegmentedLog(str(tmp_path / "b"), node_id="b", seal_rows=2)
    cb = VerdictCache(max_entries=4096, store=b)
    cb.put("k", 1, [(0, 5)])        # banked with witness
    cb.put("k", 0, None)            # later row wins the verdict...
    assert cb.get("k").witness == [(0, 5)]  # ...witness post-merged
    for name in b.missing(a.digests()):
        cb.adopt_rows(b.adopt(name, *a.read_segment(name)))
    # force a compaction mid-catch-up
    cb.put_many([(f"y{i}", 0, None) for i in range(40)])
    pre = b.snapshot()
    b.compact(cb._live_lines())
    assert b.snapshot()["absorbed_segments"] >= pre["sealed_segments"]
    # absorbed segments are never re-pulled
    assert b.missing(a.digests()) == []
    # later-row-wins + witness preserved through compaction
    cb2 = VerdictCache(max_entries=4096,
                       store=SegmentedLog(str(tmp_path / "b"),
                                          node_id="b", seal_rows=2))
    assert cb2.get("k").verdict == 0
    assert cb2.get("k").witness == [(0, 5)]
    for i in range(4):
        assert cb2.get(f"x{i}").verdict == 1


def test_replog_corrupt_segment_quarantined(tmp_path):
    log = SegmentedLog(str(tmp_path / "n"), node_id="n", seal_rows=2)
    VerdictCache(max_entries=100, store=log).put_many(
        [("a", 1, None), ("b", 0, None)])
    (name,) = log.digests()
    path = os.path.join(str(tmp_path / "n"), name)
    with open(path, "a") as f:
        f.write('{"key": "evil", "verdict": 0}\n')  # fingerprint broken
    log2 = SegmentedLog(str(tmp_path / "n"), node_id="n", seal_rows=2)
    assert log2.quarantined_segments == 1
    assert log2.digests() == {}              # never served or offered
    assert os.path.exists(path + ".quarantine")
    # and a forged push is refused
    with pytest.raises(ValueError):
        log2.adopt("seg-x-000001-000000000000.jsonl",
                   segment_fingerprint(["row"]), ["other"])


def test_anti_entropy_sweep_converges_fleet(tmp_path, corpus,
                                            expected):
    """The router's sweep ships every sealed segment everywhere; a
    node that saw none of the traffic then answers the whole corpus
    from its adopted bank."""
    router, nodes = _fleet(tmp_path, n_nodes=2, seal_rows=1)
    try:
        with CheckClient(router.address, timeout_s=60.0) as c:
            c.check("cas", corpus)
        for s in nodes:
            s.cache.flush()
        for _ in range(8):
            if router.anti_entropy_sweep()["segments_shipped"] == 0:
                break
        d0 = nodes[0].replog.digests()
        d1 = nodes[1].replog.digests()
        assert set(d0) == set(d1) and d0 == d1
        # every node now holds every whole-history verdict
        for s in nodes:
            for h in corpus:
                key = fingerprint_key(SPEC, h)
                e = s.cache.get(key)
                assert e is not None
                assert VERDICT_NAMES[e.verdict] == \
                    expected[corpus.index(h)]
    finally:
        _teardown(router, nodes)


# --- CLI surfaces ----------------------------------------------------------

def test_stats_fleet_render(tmp_path, corpus):
    from qsm_tpu.utils.cli import _render_stats_fleet

    router, nodes = _fleet(tmp_path, n_nodes=2)
    try:
        with CheckClient(router.address, timeout_s=60.0) as c:
            c.check("cas", corpus)
        text = _render_stats_fleet(router.stats())
        assert "fleet router" in text
        assert "n0 [up]" in text and "n1 [up]" in text
    finally:
        _teardown(router, nodes)


def test_node_stamps_on_plain_server(tmp_path, corpus):
    """A node started with --node-id stamps every response — ok, error
    and stats alike (the protocol `node` stamp satellite)."""
    srv = CheckServer(node_id="solo",
                      replog_dir=str(tmp_path / "replog")).start()
    try:
        with CheckClient(srv.address, timeout_s=30.0) as c:
            res = c.check("cas", corpus[:2])
            assert res["node"] == "solo"
            bad = c.check("nope", corpus[:1])
            assert bad["node"] == "solo" and not bad["ok"]
            st = c.stats()
            assert st["node"] == "solo"
            assert st["stats"]["node"] == "solo"
            assert st["stats"]["cache"]["replog"]["node"] == "solo"
    finally:
        srv.stop()


def test_link_saturation_is_busy_not_node_death(tmp_path, corpus):
    """Every pooled link slot mid-request is router-local backpressure
    (NodeBusy), never node-health evidence — a hot node must not be
    probed toward quarantine by its own popularity (the WorkerBusy
    lesson one level down)."""
    from qsm_tpu.fleet.router import NodeBusy, NodeFault, NodeLink

    srv = CheckServer().start()
    try:
        link = NodeLink("n0", srv.address)
        link._sema = threading.BoundedSemaphore(1)
        link._sema.acquire()
        with pytest.raises(NodeBusy) as ei:
            link.request({"op": "stats"}, timeout_s=0.2)
        assert not isinstance(ei.value, NodeFault)  # not shed-worthy
        link._sema.release()
        assert link.request({"op": "stats"}, timeout_s=5.0)["ok"]
    finally:
        srv.stop()


def test_cache_path_and_replog_dir_refused(tmp_path):
    """Two banks, one truth: --cache and --replog-dir together would
    silently abandon the single-file bank — refused loudly instead."""
    with pytest.raises(ValueError, match="mutually exclusive"):
        CheckServer(cache_path=str(tmp_path / "bank.jsonl"),
                    replog_dir=str(tmp_path / "replog"))


def test_stale_pooled_socket_retries_on_fresh_connection(tmp_path,
                                                         corpus):
    """A pooled socket dying across a node restart must read as 'this
    socket died', not 'this node died': the link retries once fresh
    (safe — every fleet op is idempotent) before raising NodeDead."""
    from qsm_tpu.fleet.router import NodeDead, NodeLink

    unix = str(tmp_path / "n.sock")
    srv = CheckServer(unix_path=unix, node_id="n0").start()
    link = NodeLink("n0", unix)
    try:
        assert link.request({"op": "stats"}, timeout_s=5.0)["ok"]
        assert len(link._free) == 1          # pooled
        srv.stop()                           # restart on the SAME path
        srv = CheckServer(unix_path=unix, node_id="n0").start()
        # the pooled socket is stale; the request must still succeed
        resp = link.request({"op": "stats"}, timeout_s=5.0)
        assert resp["ok"] and resp["node"] == "n0"
        srv.stop()
        # with the node REALLY gone (socket path unlinked by stop()),
        # the fresh retry fails too: NodeDead.  Pooled sockets dropped
        # first — a half-stopped connection thread may still answer
        # one last pooled request, which is fine in production but
        # nondeterministic here.
        link.close_all()
        with pytest.raises(NodeDead):
            link.request({"op": "stats"}, timeout_s=2.0)
    finally:
        srv.stop()
        link.close_all()


def test_replog_adopt_refuses_name_fingerprint_mismatch(tmp_path):
    """A segment whose NAME disagrees with its content fingerprint
    would persist now and quarantine on every restart (a permanent
    re-pull churn loop) — refused at adoption time."""
    from qsm_tpu.fleet.replog import SegmentedLog, segment_fingerprint

    log = SegmentedLog(str(tmp_path), node_id="b", seal_rows=2)
    lines = ['{"key": "k", "verdict": 1, "witness": null}']
    fp = segment_fingerprint(lines)
    bad_name = "seg-x-000001-aaaaaaaaaaaa.jsonl"
    assert fp[:12] != "aaaaaaaaaaaa"
    with pytest.raises(ValueError, match="name does not match"):
        log.adopt(bad_name, fp, lines)
    assert log.digests() == {}
    # the consistent pair adopts fine
    good = f"seg-x-000001-{fp[:12]}.jsonl"
    assert [r["key"] for r in log.adopt(good, fp, lines)] == ["k"]


# --- elastic membership (ISSUE 18) -----------------------------------------

def test_membership_join_leave_moves_only_affected_ranges():
    """Consistent-hash elasticity: a join moves ONLY the key ranges
    the newcomer's vnode points claim (every other key keeps its
    owner), and the matching leave restores the original ownership
    exactly.  Both verbs are idempotent."""
    m = Membership([("n0", "unused:1"), ("n1", "unused:2")])
    keys = [f"key{i}" for i in range(300)]
    before = {k: m.ring.node_for(k, {"n0", "n1"}) for k in keys}
    assert m.add_node("n2", "unused:3")
    assert not m.add_node("n2", "unused:3")      # idempotent re-join
    allowed = {"n0", "n1", "n2"}
    after = {k: m.ring.node_for(k, allowed) for k in keys}
    moved = [k for k in keys if after[k] != before[k]]
    assert moved, "a 3rd node must claim some ranges"
    assert all(after[k] == "n2" for k in moved)  # only ITS ranges moved
    # a member re-joining from a new address re-addresses in place
    assert m.add_node("n1", "moved:9")
    assert m.address_of("n1") == "moved:9"
    assert {k: m.ring.node_for(k, allowed) for k in keys} == after
    # the leave is the exact inverse
    assert m.remove_node("n2")
    assert not m.remove_node("n2")               # idempotent re-leave
    assert {k: m.ring.node_for(k, {"n0", "n1"}) for k in keys} == before
    snap = m.snapshot()
    assert snap["joins"] == 2 and snap["leaves"] == 1


def test_node_join_leave_rebalances_and_migrates_sessions(tmp_path):
    """The wire verbs: ``node.join`` opens the link and rebalances the
    ring; ``node.leave`` of a session's owner migrates the session
    live — the journal replays onto the new owner on the next verb,
    exactly-once by seq, and the stream closes with the exact
    verdict."""
    from qsm_tpu.core.history import sequential_history
    from qsm_tpu.serve.protocol import history_to_rows

    router, nodes = _fleet(tmp_path, n_nodes=2)
    extra = CheckServer(node_id="n2",
                        replog_dir=str(tmp_path / "replog_extra"),
                        flush_s=0.005).start()
    client = None
    try:
        client = CheckClient(router.address, timeout_s=10.0)
        h = sequential_history([(0, 1, 1, 0), (0, 0, 0, 1),
                                (1, 1, 2, 0), (1, 0, 0, 2)] * 6)
        rows = history_to_rows(h)
        half = len(rows) // 2
        opened = client.session_open("register")
        sid = opened["session"]
        for i, r in enumerate(rows[:half]):
            assert client.session_append(sid, [r], seq=i)["ok"]
        # JOIN: the third node enters the ring and takes traffic
        joined = client.node_join("n2", extra.address)
        assert joined["ok"] and joined["joined"], joined
        assert joined["nodes"] == 3
        assert not client.node_join("n2", extra.address)["joined"]
        assert "n2" in router.membership.all_ids()
        # LEAVE the session's owner: the session migrates live
        owner = router._sessions[sid].node
        assert owner is not None
        left = client.node_leave(owner)
        assert left["ok"] and left["left"], left
        assert left["sessions_migrated"] == 1
        assert left["nodes"] == 2
        assert router._sessions[sid].node is None
        for i, r in enumerate(rows[half:]):
            out = client.session_append(sid, [r], seq=half + i)
            assert out["ok"], out
        fin = client.session_close(sid)
        assert fin["ok"] and fin["verdict"] == "LINEARIZABLE"
        assert fin["ops"] == len(rows)
        assert router.session_migrations == 1
        assert router.stats()["session"]["migrated"] == 1
    finally:
        if client is not None:
            client.close()
        _teardown(router, nodes)
        extra.stop()


def test_session_ladder_takes_over_when_fleet_exhausted(tmp_path):
    """ISSUE 18 satellite: with every node down, the session verbs no
    longer SHED — the router's own in-process SessionManager is the
    last rung (exactly the check path's host ladder), the verdict
    stays exact, and a flip still pushes (unminimized, honestly
    marked)."""
    router, nodes = _fleet(tmp_path, n_nodes=1)
    client = None
    try:
        nodes[0].stop()          # the whole fleet is now unreachable
        client = CheckClient(router.address, timeout_s=10.0)
        opened = client.session_open("register")
        assert opened["ok"] and opened.get("ladder"), opened
        sid = opened["session"]
        out = client.session_append(
            sid, [[0, 1, 1, 0, 0, 1], [1, 1, 2, 2, 2, 3]], seq=0)
        assert out["ok"] and out.get("ladder"), out
        assert out["applied"] == 2
        # a violation decides on the in-router rung too: read 7 was
        # never written
        out = client.session_append(sid, [[2, 0, 0, 7, 4, 5]], seq=2)
        assert out["ok"] and out["verdict"] == "VIOLATION"
        flip = out.get("flip")
        assert flip and not flip["complete"]      # honest: unminimized
        assert flip["repro"], flip
        fin = client.session_close(sid)
        assert fin["ok"] and fin["verdict"] == "VIOLATION"
        assert fin.get("ladder") and fin["flipped"]
        assert router.session_ladder >= 3
        assert router.stats()["session"]["ladder"] >= 3
    finally:
        if client is not None:
            client.close()
        router.stop()
