"""In-kernel memoisation cache (Lowe-style): verdicts unchanged, iteration
counts collapse on violating histories; hash regression for the high-bit
collision bug (FNV-1a over words degenerates — murmur-style mixer required)."""

import pytest

import jax
import numpy as np

from qsm_tpu import generate_program, run_concurrent
from qsm_tpu.core.history import bucket_for, encode_batch
from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.ops.jax_kernel import build_kernel
from qsm_tpu.utils.corpus import build_corpus

SPEC = CasSpec()


def _hard_violating_history():
    """bench-corpus history #35: WingGongCPU(memo) needs ~7k nodes, the
    cache-less kernel millions of iterations."""
    corpus = build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=36, n_pids=8,
                          max_ops=32, seed_base=1000, seed_prefix="bench")
    return corpus[35]


def _run(h, budget, slots):
    n = bucket_for(len(h))
    enc = encode_batch([h], SPEC.initial_state(), max_ops=n)
    single = build_kernel(SPEC, n, budget=budget, cache_slots=slots)
    fn = jax.jit(jax.vmap(single, in_axes=(0, 0, 0, 0, 0, None)))
    s, it = fn(enc.ops[:, :, 1], enc.ops[:, :, 2], enc.ops[:, :, 3],
               enc.valid, enc.precedes(), enc.init_state)
    return int(s[0]), int(it[0])


def test_cache_collapses_iterations_same_verdict():
    h = _hard_violating_history()
    s_cache, it_cache = _run(h, budget=500_000, slots=4096)
    assert s_cache == 2  # FAILURE (= violation), decided
    assert it_cache < 50_000, it_cache
    # without the cache the same budget is exhausted undecided
    s_plain, it_plain = _run(h, budget=500_000, slots=0)
    assert s_plain == 3 and it_plain == 500_000  # BUDGET
    assert it_cache * 10 < it_plain


@pytest.mark.slow
def test_cache_verdicts_match_plain_on_easy_corpus():
    corpus = build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=24, n_pids=4,
                          max_ops=12, seed_base=7, seed_prefix="cc")
    for h in corpus:
        s_cache, _ = _run(h, budget=200_000, slots=1024)
        s_plain, _ = _run(h, budget=200_000, slots=0)
        assert s_cache == s_plain


def test_hash_spreads_high_bit_keys():
    """Regression: keys differing only in high taken-bits must not collide.
    FNV-1a over 32-bit words mapped ALL of these to one slot (its small
    multiplier never propagates high bits into the low slot-index bits).
    Exercises the kernel's OWN hash (make_hash_slot), not a copy."""
    import jax.numpy as jnp

    from qsm_tpu.ops.jax_kernel import make_hash_slot

    hash_slot = make_hash_slot(key_words=2, cache_slots=4096)
    keys = [(0x01FFFFFF, 0), (0x00FFFFFF, 0), (0x01FBFFFF, 0),
            (0x00FBFFFF, 0), (0x017BFFFF, 0), (0x007BFFFF, 0)]
    out = {int(hash_slot(jnp.asarray(k, jnp.uint32))) for k in keys}
    assert len(out) == len(keys), out


def test_numpy_hash_mirror_matches_kernel():
    """hash_slots_np (used to re-hash cache entries host-side when the
    compacting driver grows the table) must be bit-identical to the
    in-kernel mixer, or grown tables would silently lose every entry."""
    import jax.numpy as jnp

    from qsm_tpu.ops.jax_kernel import hash_slots_np, make_hash_slot

    rng = np.random.default_rng(3)
    for key_words in (2, 3, 5):
        for slots in (64, 512, 4096):
            keys = rng.integers(0, 2**32, size=(50, key_words),
                                dtype=np.uint32)
            kern = make_hash_slot(key_words, slots)
            expect = [int(kern(jnp.asarray(k))) for k in keys]
            got = hash_slots_np(keys, slots).tolist()
            assert got == expect


@pytest.mark.slow
def test_chunked_driver_compaction_parity():
    """Verdicts from the chunked lane-compacting driver must match the
    oracle on a corpus hard enough to force several compaction rounds and
    a cache growth (bucket 256 -> 64 -> 8)."""
    from qsm_tpu import WingGongCPU
    from qsm_tpu.ops.jax_kernel import JaxTPU

    corpus = build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=48, n_pids=8,
                          max_ops=32, seed_base=1000, seed_prefix="bench")
    backend = JaxTPU(SPEC, budget=2000)
    dev = backend.check_histories(SPEC, corpus)
    cpu = WingGongCPU(memo=True).check_histories(SPEC, corpus)
    decided = dev != 2
    assert decided.all(), "corpus should decide fully at default budgets"
    assert (dev == cpu).all()
    assert backend.rounds_run > 1
    # compaction (batch shrink and/or cache growth) must actually have
    # fired — rounds_run alone also counts plain chunk continuations
    assert backend.compactions >= 1
    assert backend.effective_rescue_slots == 4096  # cache reached the cap


def test_dus_cache_write_matches_onehot():
    """The O(1) dynamic_update_slice cache write must produce the SAME
    verdicts as the conservative one-hot masked write (regression guard for
    the alternate lowering; the upstream vmapped-boolean-scatter bug this
    kernel works around does not involve dynamic_update_slice, but trust is
    earned, not assumed)."""
    corpus = build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=32, n_pids=8,
                          max_ops=24, seed_base=77, seed_prefix="dus")
    n = bucket_for(max(len(h) for h in corpus))
    enc = encode_batch(corpus, SPEC.initial_state(), max_ops=n)
    args = (enc.ops[:, :, 1], enc.ops[:, :, 2], enc.ops[:, :, 3],
            enc.valid, enc.precedes(), enc.init_state)
    out = {}
    for mode in ("dus", "onehot"):
        single = build_kernel(SPEC, n, budget=100_000, cache_slots=512,
                              cache_write=mode)
        fn = jax.jit(jax.vmap(single, in_axes=(0, 0, 0, 0, 0, None)))
        s, it = fn(*args)
        out[mode] = (np.asarray(s), np.asarray(it))
    np.testing.assert_array_equal(out["dus"][0], out["onehot"][0])
    np.testing.assert_array_equal(out["dus"][1], out["onehot"][1])


# --- the segmented replicated verdict bank (qsm_tpu/fleet/replog.py) -------
# The serve-plane verdict cache generalizes to content-fingerprinted
# segments a fleet replicates (ISSUE 12); these pin the edge cases the
# single-file bank never had: torn ACTIVE tails on restart, catch-up
# adoption concurrent with live banking, and compaction's absorbed-set
# memory.  (tests/test_fleet.py carries the full-tier twins.)

def test_segmented_bank_restart_after_seal_and_tear(tmp_path):
    """A restarted node adopts every sealed segment plus the clean
    prefix of the active segment; a garbled tail (SIGKILL mid-append)
    is truncated, never replayed as a verdict."""
    import os

    from qsm_tpu.fleet.replog import SegmentedLog
    from qsm_tpu.serve.cache import VerdictCache

    log = SegmentedLog(str(tmp_path), node_id="n0", seal_rows=4)
    cache = VerdictCache(max_entries=64, store=log)
    for i in range(10):
        cache.put(f"k{i}", i % 2, None)
    assert log.snapshot()["sealed_segments"] == 2  # 8 rows sealed
    with open(os.path.join(str(tmp_path), "active.jsonl"), "a") as f:
        f.write('{"key": "k10", "verd')  # the torn row
    log2 = SegmentedLog(str(tmp_path), node_id="n0", seal_rows=4)
    assert log2.truncated_tails == 1
    cache2 = VerdictCache(max_entries=64, store=log2)
    assert len(cache2) == 10
    for i in range(10):
        assert cache2.get(f"k{i}").verdict == i % 2
    assert cache2.get("k10") is None


def test_segmented_bank_adoption_is_fingerprint_gated(tmp_path):
    """Replication trusts nothing: an adopted segment must re-derive
    its advertised content fingerprint or be refused outright, and a
    re-adoption of a held segment is a no-op (idempotent catch-up)."""
    import pytest as _pytest

    from qsm_tpu.fleet.replog import SegmentedLog, segment_fingerprint
    from qsm_tpu.serve.cache import VerdictCache

    a = SegmentedLog(str(tmp_path / "a"), node_id="a", seal_rows=2)
    VerdictCache(max_entries=64, store=a).put_many(
        [("x", 1, None), ("y", 0, None)])
    (name,) = a.digests()
    fp, lines = a.read_segment(name)
    b = SegmentedLog(str(tmp_path / "b"), node_id="b", seal_rows=2)
    with _pytest.raises(ValueError):
        b.adopt(name, fp, lines + ['{"key": "evil", "verdict": 0}'])
    assert b.digests() == {}
    rows = b.adopt(name, fp, lines)
    assert [r["key"] for r in rows] == ["x", "y"]
    assert b.adopt(name, fp, lines) == []  # idempotent
    assert b.missing(a.digests()) == []
