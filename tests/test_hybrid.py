"""HybridDevice: device majority under a tight budget + host tail
(ops/hybrid.py) — verdict parity with the exact oracle, real tail
traffic when the budget forces deferral, and witness delegation."""

from __future__ import annotations

import numpy as np

import qsm_tpu as q
from qsm_tpu.models import CasSpec
from qsm_tpu.models.register import RegisterSpec
from qsm_tpu.ops.backend import Verdict, verify_witness
from qsm_tpu.ops.hybrid import HybridDevice
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.utils.corpus import build_corpus
from qsm_tpu.models import AtomicCasSUT, RacyCasSUT


def _corpus(n=24, ops=24):
    return build_corpus(CasSpec(), (AtomicCasSUT, RacyCasSUT), n=n,
                        n_pids=4, max_ops=ops, seed_base=77,
                        seed_prefix="hybrid")


def test_parity_with_oracle_and_tail_traffic():
    spec = CasSpec()
    corpus = _corpus()
    memo = WingGongCPU(memo=True)
    want = np.asarray(memo.check_histories(spec, corpus))

    # budget 1 defers essentially every lane -> the tail decides; parity
    # must hold and the counters must show the traffic honestly
    hb = HybridDevice(spec, budget=1)
    got = np.asarray(hb.check_histories(spec, corpus))
    assert (got == want).all()
    assert hb.tail_histories > 0
    assert hb.tail_histories + hb.device_decided == len(corpus)


def test_device_decides_majority_under_real_budget():
    spec = CasSpec()
    corpus = _corpus()
    memo = WingGongCPU(memo=True)
    want = np.asarray(memo.check_histories(spec, corpus))

    hb = HybridDevice(spec, budget=2_000)
    got = np.asarray(hb.check_histories(spec, corpus))
    assert (got == want).all()
    assert hb.device_decided > 0  # the device really did the easy part


def test_no_budget_exceeded_leaks_with_exact_tail():
    """The default tail is exact on these sizes (its node budget is far
    beyond them), so the hybrid's output contains no BUDGET_EXCEEDED."""
    spec = CasSpec()
    corpus = _corpus()
    hb = HybridDevice(spec, budget=1)
    got = np.asarray(hb.check_histories(spec, corpus))
    assert not (got == int(Verdict.BUDGET_EXCEEDED)).any()


def test_witness_delegation_both_sides():
    spec = RegisterSpec(n_values=4)
    ok = q.overlapping_history(
        [(0, 1, 3, 0, 0, 1), (1, 0, 0, 3, 2, 3)])  # write then read: OK

    # device side decides it (generous budget)
    hb = HybridDevice(spec, budget=2_000)
    v, order = hb.check_witness(spec, ok)
    assert v == Verdict.LINEARIZABLE
    assert verify_witness(spec, ok, order)

    # tail side decides it (budget 1 forces deferral)
    hb1 = HybridDevice(spec, budget=1)
    v1, order1 = hb1.check_witness(spec, ok)
    assert v1 == Verdict.LINEARIZABLE
    assert verify_witness(spec, ok, order1)
