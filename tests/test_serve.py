"""Serving plane (qsm_tpu/serve) — the tier-1 gate for ISSUE 5.

What is pinned, in order of importance:

* served verdicts and witnesses are BIT-IDENTICAL to the direct host
  path across ≥4 model families (the server changes where checking
  happens, never what it answers);
* a cache hit returns a banked witness that still replays through the
  search-free ``verify_witness`` audit;
* a server killed mid-bank and restarted serves the persisted cache
  (atomic bank: a torn tail is dropped, banked entries survive);
* deadline-exceeded and queue-full requests get an explicit ``SHED``,
  never a wrong or partial verdict;
* the ``serve`` fault site (hang/raise at request-dispatch) degrades
  the batch to the exact host ladder — the server survives with
  unchanged verdicts, CPU-only;
* the fast serve smoke (in-process server, 2 concurrent clients, tiny
  corpus) rides the default ``-m "not slow"`` lane.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from qsm_tpu.models.registry import MODELS
from qsm_tpu.ops.backend import Verdict, verify_witness
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.resilience.policy import preset
from qsm_tpu.serve import (CheckClient, CheckServer, Lane, MicroBatcher,
                           VERDICT_NAMES, VerdictCache)
from qsm_tpu.utils.corpus import build_corpus

# small everywhere: the serving plane moves checking, it does not need
# big searches to prove that
FAMILIES = ("register", "cas", "queue", "kv")


def _corpus(family, n=10, pids=3, ops=8, prefix="serve"):
    entry = MODELS[family]
    spec = entry.make_spec()
    hists = build_corpus(
        spec, (entry.impls["atomic"], entry.impls["racy"]), n=n,
        n_pids=pids, max_ops=ops, seed_prefix=f"{prefix}_{family}")
    return spec, hists


def _names(verdicts):
    return [VERDICT_NAMES[int(v)] for v in verdicts]


@pytest.fixture()
def server(tmp_path):
    srv = CheckServer(flush_s=0.005, max_lanes=16,
                      cache_path=str(tmp_path / "bank.jsonl")).start()
    yield srv
    srv.stop()


# --- verdict/witness parity with the direct path --------------------------

def test_served_verdicts_bit_identical_across_families(server):
    """The acceptance pin: across register/cas/queue/kv, the served path
    answers exactly what the direct host checker answers — the engines
    prop_concurrent dispatches to are the engines the server keeps warm."""
    with CheckClient(server.address) as client:
        for family in FAMILIES:
            spec, hists = _corpus(family)
            direct = WingGongCPU(memo=True).check_histories(spec, hists)
            res = client.check(family, hists)
            assert res["ok"], res
            assert res["verdicts"] == _names(direct), family
            # the parity sample must not be vacuous
            assert "LINEARIZABLE" in res["verdicts"], family


def test_served_witnesses_bit_identical(server):
    """Witness requests ride the one-search rule (verdict AND witness
    from the same host-oracle search): served witnesses equal the
    direct oracle's and replay search-free."""
    spec, hists = _corpus("cas", n=6)
    oracle = WingGongCPU(memo=True)
    with CheckClient(server.address) as client:
        res = client.check("cas", hists, witness=True)
    assert res["ok"]
    for h, v, w in zip(hists, res["verdicts"], res["witnesses"]):
        dv, dw = oracle.check_witness(spec, h)
        assert v == VERDICT_NAMES[int(dv)]
        if v == "LINEARIZABLE":
            w = [tuple(p) for p in w]
            assert w == dw
            assert verify_witness(spec, h, w)
        else:
            assert w is None


# --- caching --------------------------------------------------------------

def test_cache_hit_returns_banked_witness_that_replays(server):
    spec, hists = _corpus("register", n=6)
    with CheckClient(server.address) as client:
        first = client.check("register", hists, witness=True)
        second = client.check("register", hists, witness=True)
    assert first["ok"] and second["ok"]
    assert not any(first["cached"])
    assert all(second["cached"])
    assert second["verdicts"] == first["verdicts"]
    for h, v, w in zip(hists, second["verdicts"], second["witnesses"]):
        if v == "LINEARIZABLE":
            assert verify_witness(spec, h, [tuple(p) for p in w])


def test_verdict_only_hit_then_witness_request_upgrades(server):
    """A verdict-only bank must not starve a later witness request: the
    hit without a witness falls through to the one-search path and the
    bank upgrades."""
    spec, hists = _corpus("register", n=4)
    with CheckClient(server.address) as client:
        plain = client.check("register", hists)
        with_w = client.check("register", hists, witness=True)
    assert plain["ok"] and with_w["ok"]
    assert with_w["verdicts"] == plain["verdicts"]
    for h, v, w in zip(hists, with_w["verdicts"], with_w["witnesses"]):
        if v == "LINEARIZABLE":
            assert w is not None
            assert verify_witness(spec, h, [tuple(p) for p in w])


def test_kill_mid_bank_then_restart_serves_persisted_cache(tmp_path):
    """The bank is atomic per put: an abrupt kill (no graceful flush)
    plus a torn trailing line still leaves every banked entry servable
    by the next server generation — duplicates answer cached, O(1)."""
    bank = str(tmp_path / "bank.jsonl")
    spec, hists = _corpus("cas", n=8)
    direct = WingGongCPU(memo=True).check_histories(spec, hists)

    srv = CheckServer(flush_s=0.005, max_lanes=16, cache_path=bank).start()
    try:
        with CheckClient(srv.address) as client:
            res = client.check("cas", hists)
            assert res["ok"] and not any(res["cached"])
    finally:
        # abrupt: no cache.flush() beyond the per-put ones — the
        # atomic-per-put discipline IS what this test pins
        srv.stop()
    with open(bank, "a") as f:
        f.write('{"key": "torn-mid-wr')  # simulated torn tail

    srv2 = CheckServer(flush_s=0.005, max_lanes=16, cache_path=bank).start()
    try:
        with CheckClient(srv2.address) as client:
            res2 = client.check("cas", hists)
        assert res2["ok"]
        assert all(res2["cached"]), res2["cached"]
        assert res2["verdicts"] == _names(direct)
        assert srv2.stats()["cache"]["hits"] == len(hists)
    finally:
        srv2.stop()


# --- shedding: explicit, never wrong --------------------------------------

class _SlowEngine:
    """Delegates to the memo oracle after a fixed stall (deadline bait)."""

    name = "slow_stub"

    def __init__(self, spec, stall_s=0.4):
        self.inner = WingGongCPU(memo=True)
        self.stall_s = stall_s

    def check_histories(self, spec, histories):
        time.sleep(self.stall_s)
        return self.inner.check_histories(spec, histories)


def test_deadline_exceeded_gets_shed_never_wrong(tmp_path):
    srv = CheckServer(flush_s=0.005, max_lanes=16,
                      engine_factory=lambda spec: _SlowEngine(spec)).start()
    try:
        with CheckClient(srv.address) as client:
            spec, hists = _corpus("register", n=4)
            res = client.check("register", hists, deadline_s=0.05)
            assert res["ok"] is False
            assert res["shed"] is True and res["reason"] == "deadline"
            assert "verdicts" not in res  # shed carries NO verdicts
        assert srv.stats()["admission"]["shed_deadline"] == 1
    finally:
        srv.stop()


def test_bad_requests_do_not_leak_admission_slots(tmp_path):
    """Review regression: a request that dies after validation (bogus
    spec_kwargs, oracle trouble) must release every admitted lane —
    leaked slots would shrink queue_depth until the server sheds ALL
    traffic."""
    srv = CheckServer(flush_s=0.005, max_lanes=16, queue_depth=8).start()
    try:
        spec, hists = _corpus("register", n=6)
        with CheckClient(srv.address) as client:
            for _ in range(3):
                res = client.check("cas", hists,
                                   spec_kwargs={"bogus": 1})
                assert res["ok"] is False and "error" in res
            assert srv.admission.snapshot()["in_flight"] == 0
            # a valid 6-lane request still fits the depth-8 queue
            res = client.check("register", hists)
            assert res["ok"], res
            direct = WingGongCPU(memo=True).check_histories(spec, hists)
            assert res["verdicts"] == _names(direct)
    finally:
        srv.stop()


def test_queue_full_gets_shed(tmp_path):
    srv = CheckServer(flush_s=0.005, max_lanes=16, queue_depth=2).start()
    try:
        with CheckClient(srv.address) as client:
            spec, hists = _corpus("register", n=5)
            res = client.check("register", hists)
            assert res["ok"] is False and res["shed"] is True
            assert res["reason"] == "queue full"
        assert srv.stats()["admission"]["shed_queue"] == 1
    finally:
        srv.stop()


# --- the `serve` fault site -----------------------------------------------

def test_serve_fault_raise_degrades_batch_not_server(monkeypatch, server):
    """raise:serve fires at request-dispatch; the batch re-dispatches on
    the emergency host ladder and verdicts stay exact — the degraded
    SERVER keeps answering."""
    spec, hists = _corpus("queue", n=6)
    direct = WingGongCPU(memo=True).check_histories(spec, hists)
    monkeypatch.setenv("QSM_TPU_FAULTS", "raise:serve")
    with CheckClient(server.address) as client:
        res = client.check("queue", hists)
    assert res["ok"]
    assert res["verdicts"] == _names(direct)
    assert server.stats()["serve_faults"] >= 1
    assert any(b.get("degraded") for b in res["batches"])


def test_serve_fault_hang_is_watchdogged(monkeypatch, tmp_path):
    """hang:serve wedges the dispatch; the serve policy's watchdog
    abandons it and the emergency ladder answers — bounded, exact."""
    monkeypatch.setenv("QSM_TPU_FAULTS", "hang:serve")
    monkeypatch.setenv("QSM_TPU_FAULT_HANG_S", "5")
    srv = CheckServer(flush_s=0.005, max_lanes=16,
                      policy=preset("serve").with_(timeout_s=0.2)).start()
    try:
        spec, hists = _corpus("register", n=4)
        direct = WingGongCPU(memo=True).check_histories(spec, hists)
        t0 = time.monotonic()
        with CheckClient(srv.address) as client:
            res = client.check("register", hists)
        assert res["ok"]
        assert res["verdicts"] == _names(direct)
        assert time.monotonic() - t0 < 4.0  # abandoned, not slept out
        assert srv.stats()["serve_faults"] >= 1
    finally:
        srv.stop()


# --- the CI serve smoke: 2 concurrent clients, default lane ---------------

def test_serve_smoke_two_concurrent_clients(server):
    """The fast serve smoke (ISSUE 5 satellite): in-process server, two
    concurrent clients on distinct families, one shared micro-batching
    plane — both get exact answers."""
    results = {}

    def drive(family):
        spec, hists = _corpus(family, n=6)
        direct = WingGongCPU(memo=True).check_histories(spec, hists)
        with CheckClient(server.address) as client:
            res = client.check(family, hists)
        results[family] = (res, _names(direct))

    threads = [threading.Thread(target=drive, args=(f,))
               for f in ("register", "cas")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert set(results) == {"register", "cas"}
    for family, (res, direct_names) in results.items():
        assert res["ok"], family
        assert res["verdicts"] == direct_names, family
    st = server.stats()
    assert st["requests"] == 2
    assert st["batcher"]["batches"] >= 1
    # every batch stamp is self-describing provenance
    for res, _ in results.values():
        for b in res["batches"]:
            assert {"batch", "lanes", "width", "occupancy",
                    "flush"} <= set(b)


# --- CLI: submit + stats --serve ------------------------------------------

def test_submit_and_stats_cli_roundtrip(server, tmp_path, capsys):
    from qsm_tpu.utils.cli import main

    spec, hists = _corpus("cas", n=4)
    from qsm_tpu.serve.protocol import history_to_rows

    trace = {"model": "cas",
             "histories": [history_to_rows(h) for h in hists]}
    path = tmp_path / "trace.json"
    path.write_text(json.dumps(trace))
    rc = main(["submit", "--addr", server.address, "--trace", str(path)])
    doc = json.loads(capsys.readouterr().out.strip())
    direct = WingGongCPU(memo=True).check_histories(spec, hists)
    assert doc["verdicts"] == _names(direct)
    n_vio = sum(v == "VIOLATION" for v in doc["verdicts"])
    assert rc == (1 if n_vio else 0)

    rc = main(["stats", "--serve", server.address])
    stats = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert stats["requests"] >= 1
    assert "cache" in stats and "batcher" in stats and "admission" in stats


# --- unit: batcher / cache / admission ------------------------------------

def test_batcher_flushes_full_batches_immediately():
    done = threading.Event()
    batches = []

    def dispatch(key, lanes, why):
        batches.append((key, lanes, why))
        for lane in lanes:
            lane.resolve(1, why)
        done.set()

    b = MicroBatcher(dispatch, max_lanes=4, flush_s=5.0)
    b.start()
    try:
        far = time.monotonic() + 60
        for i in range(4):
            assert b.submit("g", Lane(key=str(i), history=None,
                                      deadline=far,
                                      resolve=lambda v, w: None))
        assert done.wait(2.0), "full batch did not flush"
        key, lanes, why = batches[0]
        assert len(lanes) == 4 and why["flush"] == "full"
        assert why["occupancy"] == 1.0
    finally:
        b.stop()


def test_batcher_interval_flush_for_lone_lane():
    done = threading.Event()
    stamps = []

    def dispatch(key, lanes, why):
        stamps.append(why)
        done.set()

    b = MicroBatcher(dispatch, max_lanes=64, flush_s=0.02)
    b.start()
    try:
        b.submit("g", Lane(key="k", history=None,
                           deadline=time.monotonic() + 60,
                           resolve=lambda v, w: None))
        assert done.wait(2.0), "lone lane never flushed"
        assert stamps[0]["flush"] == "interval"
        assert stamps[0]["lanes"] == 1
    finally:
        b.stop()


def test_batcher_deadline_flush_preempts_interval():
    done = threading.Event()
    stamps = []

    def dispatch(key, lanes, why):
        stamps.append(why)
        done.set()

    b = MicroBatcher(dispatch, max_lanes=64, flush_s=1.0)
    b.start()
    try:
        t0 = time.monotonic()
        b.submit("g", Lane(key="k", history=None,
                           deadline=time.monotonic() + 0.05,
                           resolve=lambda v, w: None))
        assert done.wait(2.0)
        assert time.monotonic() - t0 < 0.9  # did not wait the interval
        assert stamps[0]["flush"] == "deadline"
    finally:
        b.stop()


def test_verdict_cache_lru_persistence_and_honesty(tmp_path):
    bank = str(tmp_path / "bank.jsonl")
    c = VerdictCache(max_entries=2, path=bank)
    c.put("a", 1, witness=[(0, 1)])
    c.put("b", 0)
    c.put("undecided", 2)  # BUDGET_EXCEEDED must never bank
    assert c.get("undecided") is None
    assert c.get("a").witness == [(0, 1)]
    c.put("c", 1)  # evicts LRU ("b": "a" was touched above)
    assert c.get("b") is None
    assert c.get("a") is not None

    c2 = VerdictCache(max_entries=8, path=bank)
    assert c2.get("a").verdict == 1
    assert c2.get("a").witness == [(0, 1)]
    assert c2.get("c").verdict == 1
    # a verdict-only refresh must not drop a banked witness
    c2.put("a", 1)
    assert c2.get("a").witness == [(0, 1)]


def test_verdict_bank_append_log_supersede_and_witness(tmp_path):
    """The bank is an append log: later rows supersede earlier ones on
    load, and a verdict-only refresh row still carries the banked
    witness (serialized post-merge) — so witnesses survive restarts
    even when the LAST write for a key had none."""
    bank = str(tmp_path / "bank.jsonl")
    c = VerdictCache(max_entries=8, path=bank)
    c.put("a", 1, witness=[(0, 1)])
    c.put("a", 1)  # verdict-only refresh APPENDS; must not drop witness
    c2 = VerdictCache(max_entries=8, path=bank)
    assert c2.get("a").witness == [(0, 1)]
    # two rows on disk (append log), one live entry
    assert c2.stats()["bank_rows"] == 2
    assert len(c2) == 1


def test_verdict_bank_append_after_torn_tail_compacts_first(tmp_path):
    """Review regression: a bank whose tail line is torn (killed
    mid-append) must NOT be appended to directly — the first new row
    would weld onto the partial line and poison every later load.  The
    loader forces the next flush to compact, so banking keeps working
    across repeated kill/restart generations."""
    bank = str(tmp_path / "bank.jsonl")
    c = VerdictCache(max_entries=8, path=bank)
    c.put("a", 1)
    c.put("b", 0)
    with open(bank, "a") as f:
        f.write('{"key": "c", "verd')  # torn mid-append, no newline
    c2 = VerdictCache(max_entries=8, path=bank)
    assert c2.get("a") is not None and c2.get("b") is not None
    c2.put("d", 1)  # must compact, not append after the partial line
    c3 = VerdictCache(max_entries=8, path=bank)
    assert c3.get("a").verdict == 1
    assert c3.get("b").verdict == 0
    assert c3.get("d").verdict == 1
    c3.put("e", 1)  # and the NEXT generation still banks cleanly
    assert VerdictCache(max_entries=8, path=bank).get("e") is not None
    # the subtler tear: the last line PARSES but has no trailing
    # newline (killed between payload and '\n') — still not
    # appendable-after; the next flush must compact too
    with open(bank) as f:
        body = f.read()
    with open(bank, "w") as f:
        f.write(body.rstrip("\n"))  # strip the final newline only
    c4 = VerdictCache(max_entries=8, path=bank)
    assert c4.get("e") is not None  # the newline-less row still loads
    c4.put("f", 1)
    c5 = VerdictCache(max_entries=8, path=bank)
    assert c5.get("e") is not None and c5.get("f") is not None


def test_verdict_bank_compacts_instead_of_growing_unbounded(tmp_path):
    """Appends are O(batch); the log must compact (atomic rewrite of
    live entries) once it outgrows twice the live set — a long-lived
    server's bank cannot grow without bound."""
    bank = str(tmp_path / "bank.jsonl")
    c = VerdictCache(max_entries=4, path=bank)
    for i in range(40):
        c.put(f"k{i}", 1)
    st = c.stats()
    assert st["compactions"] >= 1
    assert st["bank_rows"] <= 2 * 40  # bounded, not 40 appends forever
    # the live set survives a reload
    c2 = VerdictCache(max_entries=4, path=bank)
    assert c2.get("k39") is not None


def test_verdict_cache_preserves_alien_file(tmp_path):
    path = tmp_path / "not_a_bank.json"
    path.write_text('{"something": "else"}\n')
    c = VerdictCache(path=str(path))
    assert len(c) == 0
    # the alien file was preserved aside, never clobbered
    assert (tmp_path / "not_a_bank.json.pre-resume").exists()
    c.put("k", 1)
    assert VerdictCache(path=str(path)).get("k") is not None


def test_admission_bounds_and_counters():
    from qsm_tpu.serve import AdmissionController

    a = AdmissionController(queue_depth=4)
    assert a.try_admit(3)
    assert not a.try_admit(2)  # over depth: shed
    assert a.try_admit(1)
    a.release(4)
    snap = a.snapshot()
    assert snap["in_flight"] == 0
    assert snap["shed_queue"] == 1
    assert snap["admitted_lanes"] == 4
    assert snap["completed_lanes"] == 4
    assert snap["peak_in_flight"] == 4
    assert snap["policy"] == "serve"


def test_verdict_names_match_verdict_enum():
    for v in Verdict:
        assert VERDICT_NAMES[int(v)] == v.name
