"""The generation-plane model families (ISSUE 17): rangeset, semaphore,
txn — parity pins plus the txn family's REFUSAL pins.

rangeset/semaphore are scalar-state specs riding every fast path, so
they get the standard treatment: exhaustive py/jax step agreement over
the full domain, atomic-impl-passes, racy-impl-fails-with-a-shrinkable
counterexample.

txn is deliberately different: its ``copy`` command writes TWO cells,
so the spec is NOT P-decomposable — and it declares a per-key
projection anyway, precisely so the validation layer has something to
refuse.  The pins here are the refusals themselves, verbatim: the
``projection_report`` problem string, ``PComp`` raising
``NotDecomposableError``, the planner's ``decompose_keys=off
(refused: …)`` why stamp, and the serve plane's ``pcomp=off
(refused: …)`` plan_why.  A consumer that silently splits a txn
history would verdict on a corpus the spec semantics don't describe —
every refusal is a soundness gate, and each one is test-pinned so a
refactor cannot quietly remove it.
"""

from __future__ import annotations

import pytest

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     prop_concurrent)
from qsm_tpu.core.spec import compile_step_table, projection_report
from qsm_tpu.models.lock import (AtomicSemaphoreSUT,
                                 RacyCheckThenActSemaphoreSUT,
                                 SemaphoreSpec)
from qsm_tpu.models.rangeset import (AtomicRangeSetSUT, RangeSetSpec,
                                     ScanningRangeSetSUT)
from qsm_tpu.models.txn import (AtomicTxnSUT, TornCopyTxnSUT,
                                TxnRegisterSpec)
from qsm_tpu.ops.pcomp import NotDecomposableError, PComp
from qsm_tpu.utils.corpus import build_corpus

RANGESET = RangeSetSpec(n_keys=4)
SEMAPHORE = SemaphoreSpec(permits=2)
TXN = TxnRegisterSpec(n_cells=2, n_values=3)

RANGESET_CFG = PropertyConfig(n_trials=120, n_pids=4, max_ops=32, seed=11)
SEMAPHORE_CFG = PropertyConfig(n_trials=80, n_pids=4, max_ops=24, seed=11)
TXN_CFG = PropertyConfig(n_trials=60, n_pids=6, max_ops=24, seed=11)


def _step_table_matches_step_jax(spec, n_states):
    import jax.numpy as jnp

    trans, ok = compile_step_table(spec, n_states)
    for s in range(n_states):
        for c, sig in enumerate(spec.CMDS):
            for a in range(sig.n_args):
                for r in range(sig.n_resps):
                    ns, good = spec.step_jax(
                        jnp.asarray([s], jnp.int32), jnp.int32(c),
                        jnp.int32(a), jnp.int32(r))
                    assert int(ns[0]) == trans[s, c, a, r], (s, c, a, r)
                    assert bool(good) == ok[s, c, a, r], (s, c, a, r)


def test_rangeset_step_table_matches_step_jax():
    _step_table_matches_step_jax(RANGESET, 1 << RANGESET.n_keys)


def test_semaphore_step_table_matches_step_jax():
    _step_table_matches_step_jax(SEMAPHORE, SEMAPHORE.permits + 1)


# -- parity pins: atomic clean, racy violates --------------------------

def test_atomic_rangeset_passes():
    res = prop_concurrent(RANGESET, AtomicRangeSetSUT(RANGESET),
                          RANGESET_CFG)
    assert res.ok, res.counterexample


def test_scanning_rangeset_fails_and_shrinks():
    res = prop_concurrent(RANGESET, ScanningRangeSetSUT(RANGESET),
                          RANGESET_CFG)
    assert not res.ok, "torn count_below scan was never caught"
    cx = res.counterexample
    assert check_one(WingGongCPU(), RANGESET,
                     cx.history) == Verdict.VIOLATION


def test_atomic_semaphore_passes():
    res = prop_concurrent(SEMAPHORE, AtomicSemaphoreSUT(SEMAPHORE),
                          SEMAPHORE_CFG)
    assert res.ok, res.counterexample


def test_racy_semaphore_fails_and_shrinks():
    res = prop_concurrent(SEMAPHORE,
                          RacyCheckThenActSemaphoreSUT(SEMAPHORE),
                          SEMAPHORE_CFG)
    assert not res.ok, "check-then-act over-grant was never caught"
    cx = res.counterexample
    assert check_one(WingGongCPU(), SEMAPHORE,
                     cx.history) == Verdict.VIOLATION


def test_atomic_txn_passes():
    res = prop_concurrent(TXN, AtomicTxnSUT(TXN), TXN_CFG)
    assert res.ok, res.counterexample


def test_torn_copy_txn_fails():
    res = prop_concurrent(TXN, TornCopyTxnSUT(TXN), TXN_CFG)
    assert not res.ok, "torn copy was never caught"
    cx = res.counterexample
    assert check_one(WingGongCPU(), TXN, cx.history) == Verdict.VIOLATION


# -- cross-backend parity on runner-produced corpora -------------------

@pytest.mark.parametrize("family,spec,suts,cfg", [
    ("rangeset", RANGESET, (AtomicRangeSetSUT, ScanningRangeSetSUT),
     RANGESET_CFG),
    ("semaphore", SEMAPHORE, (AtomicSemaphoreSUT,
                              RacyCheckThenActSemaphoreSUT),
     SEMAPHORE_CFG),
])
def test_new_scalar_family_backend_parity(family, spec, suts, cfg):
    """The scalar families ride every fast path: memo ladder, quiescent
    -cut segdc, the device kernel's table-gather path, and the native
    C++ table checker must all agree on a mixed atomic/racy corpus.
    The racy bug fires rarely under the runner's fixed seeds, so the
    property layer's counterexample anchors the violating side."""
    import numpy as np

    from conftest import assert_backend_parity
    from qsm_tpu.native import CppOracle
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.segdc import SegDC

    hists = build_corpus(spec, suts, n=10, n_pids=3, max_ops=12,
                         seed_prefix=f"genpar_{family}")
    res = prop_concurrent(spec, suts[1](spec), cfg)
    assert not res.ok
    hists.append(res.counterexample.history)
    cpu = assert_backend_parity(spec, hists, JaxTPU(spec))

    seg = SegDC(spec).check_histories(spec, hists)
    np.testing.assert_array_equal(np.asarray(seg), cpu)

    cpp = CppOracle(spec)
    np.testing.assert_array_equal(cpp.check_histories(spec, hists), cpu)
    assert cpp.native_histories == len(hists)  # no silent fallback


def test_txn_backend_parity_memo_vs_segdc():
    """txn is vector-state and non-decomposable — the whole-history
    paths (memo oracle, segdc with its whole-history fallback) must
    still agree; decomposition never enters (refusal pins below)."""
    import numpy as np

    from qsm_tpu.ops.segdc import SegDC
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    hists = build_corpus(TXN, (AtomicTxnSUT, TornCopyTxnSUT), n=8,
                         n_pids=4, max_ops=12, seed_prefix="genpar_txn")
    res = prop_concurrent(TXN, TornCopyTxnSUT(TXN), TXN_CFG)
    assert not res.ok
    hists.append(res.counterexample.history)
    cpu = WingGongCPU(memo=True).check_histories(TXN, hists)
    seg = SegDC(TXN).check_histories(TXN, hists)
    np.testing.assert_array_equal(np.asarray(seg), np.asarray(cpu))
    assert (np.asarray(cpu) == int(Verdict.VIOLATION)).any()
    assert (np.asarray(cpu) == int(Verdict.LINEARIZABLE)).any()


# -- txn refusal pins: every consumer must refuse to decompose ---------

def test_txn_projection_report_names_the_leak():
    """The validator's problem string, verbatim: ``copy`` steps leak
    past their own key, so keys are not independent.  Planner and serve
    render this exact string in their refusal stamps."""
    spec = TxnRegisterSpec(n_cells=2, n_values=3)
    assert projection_report(spec) == [
        "copy(arg=0): step leaks into keys [1] beyond its own key 0 "
        "— keys are not independent"]


def test_txn_pcomp_construction_refuses():
    with pytest.raises(NotDecomposableError):
        PComp(TxnRegisterSpec(n_cells=2, n_values=3))


def test_txn_planner_refuses_with_why_stamp():
    from qsm_tpu.search.planner import plan_search, profile_corpus

    spec = TxnRegisterSpec(n_cells=2, n_values=3)
    hists = build_corpus(spec, (AtomicTxnSUT, TornCopyTxnSUT), n=6,
                         n_pids=4, max_ops=16, seed_prefix="txnplan")
    plan = plan_search(spec, profile_corpus(hists, spec), platform="cpu")
    assert not plan.decompose_keys
    assert any(w.startswith("decompose_keys=off (refused: copy(arg=0)")
               for w in plan.why), plan.why


def test_txn_serve_refuses_with_plan_why():
    from qsm_tpu.serve import CheckClient, CheckServer

    spec = TxnRegisterSpec()
    hists = build_corpus(spec, (AtomicTxnSUT, TornCopyTxnSUT), n=4,
                         n_pids=4, max_ops=12, seed_prefix="txnserve")
    srv = CheckServer(flush_s=0.005, max_lanes=8).start()
    try:
        with CheckClient(srv.address) as client:
            res = client.check("txn", hists)
        assert res["ok"], res
        assert any(w.startswith("pcomp=off (refused: copy(arg=0)")
                   for w in res["plan_why"]), res["plan_why"]
    finally:
        srv.stop()
