"""The resilience-plane tier-1 gate (ISSUE 3 acceptance): a device
engine that hangs or dies mid-run degrades to the host fallback with
verdicts and witnesses bit-identical to a clean host run across four
model families; a bench scan killed after N cells resumes with
``--resume`` re-running zero completed cells; the retry/deadline policy
and the fault plane behave exactly as documented — all on the CPU
platform, no hardware."""

from __future__ import annotations

import importlib.util
import json
import os

import numpy as np
import pytest

from qsm_tpu.models.registry import MODELS
from qsm_tpu.ops.backend import Verdict, device_error_types
from qsm_tpu.ops.jax_kernel import JaxTPU
from qsm_tpu.resilience import faults as faults_mod
from qsm_tpu.resilience.checkpoint import (CellJournal, atomic_write_json,
                                           atomic_write_text)
from qsm_tpu.resilience.failover import (FailoverBackend,
                                         collect_resilience,
                                         host_fallback)
from qsm_tpu.resilience.faults import FaultPlane, InjectedFault
from qsm_tpu.resilience.policy import (PRESETS, RetryPolicy,
                                       WatchdogTimeout, preset, watchdog)
from qsm_tpu.utils.corpus import build_corpus

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

# The acceptance families: every one has an atomic and a racy impl, so
# the degraded corpus carries both LINEARIZABLE and VIOLATION verdicts.
FAMILIES = ("register", "cas", "queue", "kv")


@pytest.fixture
def faultenv(monkeypatch):
    """Install a fault-plane schedule and force a fresh parse — the
    process-global plane carries per-site hit counts, and an @nth rule
    in one test must not inherit another test's hits."""

    def set_faults(spec: str, seed: str = "0", hang_s=None):
        monkeypatch.setenv(faults_mod.ENV_VAR, spec)
        monkeypatch.setenv(faults_mod.SEED_VAR, seed)
        if hang_s is not None:
            monkeypatch.setenv(faults_mod.HANG_VAR, str(hang_s))
        monkeypatch.setattr(faults_mod, "_plane", None)

    yield set_faults
    monkeypatch.setattr(faults_mod, "_plane", None)


def _corpus(name, n=6, pids=2, ops=8):
    entry = MODELS[name]
    spec = entry.make_spec()
    impls = (entry.impls["atomic"], entry.impls["racy"])
    return spec, build_corpus(spec, impls, n=n, n_pids=pids, max_ops=ops,
                              seed_prefix=f"resil_{name}")


# =====================================================================
# RetryPolicy / watchdog — ONE policy for the whole stack
# =====================================================================

def test_policy_retries_then_returns_first_success():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    slept = []
    pol = RetryPolicy(attempts=4, backoff_s=1.0, backoff_factor=2.0)
    assert pol.run(flaky, sleep=slept.append) == "ok"
    assert len(calls) == 3
    assert slept == [1.0, 2.0]  # exponential spacing, stops on success


def test_policy_exhausted_ladder_raises_last_error():
    pol = RetryPolicy(attempts=2, backoff_s=0.0)
    with pytest.raises(OSError, match="always"):
        pol.run(lambda: (_ for _ in ()).throw(OSError("always")),
                sleep=lambda d: None)


def test_policy_should_retry_returns_last_rejected_value():
    vals = iter([1, 2, 3])
    pol = RetryPolicy(attempts=3, backoff_s=0.0)
    out = pol.run(lambda: next(vals), should_retry=lambda v: v < 10,
                  sleep=lambda d: None)
    assert out == 3  # ladder exhausted: the caller sees the final state


def test_policy_deadline_stops_ladder_before_attempts():
    calls = []
    pol = RetryPolicy(attempts=10, backoff_s=100.0, deadline_s=1.0)
    with pytest.raises(OSError):
        # first retry would start at t+100s > deadline: one attempt only
        pol.run(lambda: calls.append(1) or
                (_ for _ in ()).throw(OSError("x")),
                sleep=lambda d: None)
    assert len(calls) == 1


def test_policy_jitter_is_bounded_and_seeded():
    import random

    pol = RetryPolicy(attempts=4, backoff_s=10.0, backoff_factor=1.0,
                      jitter_frac=0.5)
    d1 = list(pol.delays(random.Random(7)))
    d2 = list(pol.delays(random.Random(7)))
    assert d1 == d2  # replayable
    assert all(5.0 <= d <= 15.0 for d in d1)


def test_presets_exist_and_unknown_name_is_a_clean_error():
    for name in ("probe", "watcher-probe", "window-reprobe",
                 "bench-probe", "seize-probe", "dispatch"):
        assert PRESETS[name].name == name
    assert preset("bench-probe").attempts == 3
    with pytest.raises(KeyError, match="bench-probe"):
        preset("nope")
    # derived overrides keep provenance in the name
    assert preset("probe").with_(timeout_s=1.0).name == "probe*"


def test_watchdog_abandons_hung_call_and_relays_errors():
    import time as _time

    assert watchdog(lambda: 42, None) == 42          # inline, no thread
    assert watchdog(lambda: 42, 5.0) == 42
    with pytest.raises(WatchdogTimeout, match="abandoned"):
        watchdog(lambda: _time.sleep(3.0), 0.05, label="t")
    with pytest.raises(ValueError, match="mine"):
        watchdog(lambda: (_ for _ in ()).throw(ValueError("mine")), 5.0)


# =====================================================================
# Fault plane — QSM_TPU_FAULTS
# =====================================================================

def test_fault_rule_parsing_and_errors():
    plane = FaultPlane.parse("hang:dispatch:0.3,raise:seize,wedge:probe")
    assert [(r.action, r.site, r.p) for r in plane.rules] == [
        ("hang", "dispatch", 0.3), ("raise", "seize", 1.0),
        ("wedge", "probe", 1.0)]
    assert FaultPlane.parse("raise:dispatch@2").rules[0].nth == 2
    for bad in ("explode:dispatch", "raise:", "raise:x:2.0",
                "raise:dispatch@0", "raise:dispatch@x", "justasite"):
        with pytest.raises(ValueError):
            FaultPlane.parse(bad)


def test_fault_nth_fires_on_nth_hit_and_every_later_one():
    plane = FaultPlane.parse("raise:dispatch@3")
    assert [plane.action_for("dispatch") for _ in range(5)] == \
        [None, None, "raise", "raise", "raise"]  # a lost device stays lost


def test_fault_probability_draws_are_seed_replayable():
    a = FaultPlane.parse("raise:dispatch:0.5", seed="11")
    b = FaultPlane.parse("raise:dispatch:0.5", seed="11")
    fires = [a.action_for("dispatch") for _ in range(32)]
    assert fires == [b.action_for("dispatch") for _ in range(32)]
    assert None in fires and "raise" in fires  # actually probabilistic


def test_inject_is_a_noop_when_plane_is_off(faultenv, monkeypatch):
    monkeypatch.delenv(faults_mod.ENV_VAR, raising=False)
    assert faults_mod.inject("dispatch") is None


def test_inject_raise_wedge_and_bounded_hang(faultenv):
    faultenv("raise:seize,wedge:probe,hang:dispatch", hang_s=0.01)
    with pytest.raises(InjectedFault, match="seize"):
        faults_mod.inject("seize")
    assert faults_mod.inject("probe") == "wedge"
    with pytest.raises(InjectedFault, match="dispatch"):
        faults_mod.inject("dispatch")  # hang_s elapses, then raises


def test_probe_wedge_fault_yields_not_ok_without_hardware(faultenv):
    from qsm_tpu.utils.device import probe_default_backend

    faultenv("wedge:probe")
    p = probe_default_backend(policy=preset("probe"))
    assert not p.is_device and "wedge" in p.detail


# =====================================================================
# The acceptance core: degraded runs bit-identical to a clean host run
# =====================================================================

@pytest.mark.parametrize("family", FAMILIES)
def test_dead_device_degrades_bit_identical(family, faultenv):
    """Every dispatch raises (device dead on arrival): verdicts across
    atomic+racy corpora equal a clean host-ladder run, bit for bit."""
    spec, hists = _corpus(family)
    clean = host_fallback(spec).check_histories(spec, hists)

    faultenv("raise:dispatch")
    fo = FailoverBackend(spec, JaxTPU(spec),
                         policy=preset("dispatch").with_(
                             attempts=1, backoff_s=0.0))
    got = fo.check_histories(spec, hists)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(clean))
    r = fo.resilience()
    assert r["degradations"] == 1
    assert r["fallback_histories"] == len(hists)
    assert r["device_histories"] == 0
    assert r["fallback_engine"]
    # the corpora genuinely exercise both verdicts
    assert {int(Verdict.LINEARIZABLE)} <= set(np.asarray(clean).tolist())


def test_midrun_loss_banks_device_verdicts_and_degrades_rest(faultenv):
    """The device dies on the SECOND dispatch slice: slice-1 verdicts
    are preserved from the device, the undecided remainder re-dispatches
    to the host ladder, and the merged result equals a clean host run."""
    spec, hists = _corpus("cas", n=8)
    clean = host_fallback(spec).check_histories(spec, hists)

    faultenv("raise:dispatch@2")
    fo = FailoverBackend(spec, JaxTPU(spec), dispatch_lanes=3,
                         policy=preset("dispatch").with_(
                             attempts=2, backoff_s=0.0))
    got = fo.check_histories(spec, hists)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(clean))
    r = fo.resilience()
    assert r["degradations"] == 1
    assert r["device_histories"] == 3      # slice 1 banked
    assert r["fallback_histories"] == 5    # slices 2+3 degraded
    assert r["retries"] == 1               # the policy retried once first
    # the cost record carries the same story into bench rows
    st = fo.search_stats()
    assert st.degradations == 1 and st.fallback_engine


def test_hung_dispatch_is_abandoned_and_degrades(faultenv):
    """A HANGING dispatch (the round-1 wedged-tunnel mode): the watchdog
    abandons the call and the run completes on the host ladder with
    identical verdicts."""
    spec, hists = _corpus("cas")
    clean = host_fallback(spec).check_histories(spec, hists)

    faultenv("hang:dispatch", hang_s=5)
    fo = FailoverBackend(spec, JaxTPU(spec),
                         policy=preset("dispatch").with_(
                             attempts=1, timeout_s=0.1, backoff_s=0.0))
    got = fo.check_histories(spec, hists)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(clean))
    assert fo.degraded and "abandoned" in fo.last_error


@pytest.mark.parametrize("family", FAMILIES)
def test_degraded_witness_is_bit_identical(family, faultenv):
    """Witnesses after degradation are the host oracle's own — the
    (verdict, linearization) pair equals a clean host run's exactly."""
    spec, hists = _corpus(family)
    ref = host_fallback(spec)

    faultenv("raise:dispatch")
    fo = FailoverBackend(spec, JaxTPU(spec),
                         policy=preset("dispatch").with_(
                             attempts=1, backoff_s=0.0))
    for h in hists[:3]:
        assert fo.check_witness(spec, h) == ref.check_witness(spec, h)
    assert fo.degraded


def test_hybrid_backend_degrades_in_place(faultenv):
    """The hybrid engine's own degradation hook: device loss sends the
    whole batch to the exact tail; verdicts equal a clean host run and
    the resilience block records the event."""
    from qsm_tpu.ops.hybrid import HybridDevice

    spec, hists = _corpus("queue")
    clean = host_fallback(spec).check_histories(spec, hists)

    faultenv("raise:dispatch")
    hy = HybridDevice(spec)
    got = hy.check_histories(spec, hists)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(clean))
    r = hy.resilience()
    assert r["degradations"] == 1 and r["fallback_engine"]
    assert hy.search_stats().degradations == 1


def test_property_run_survives_midrun_device_loss(faultenv):
    """The property layer itself: a backend that dies mid-run degrades
    dispatch to the resolution oracle — the run completes, ok semantics
    are unchanged, and timings record the degradation."""
    from qsm_tpu.core.property import PropertyConfig, prop_concurrent

    entry = MODELS["cas"]
    spec = entry.make_spec()
    cfg = PropertyConfig(n_trials=6, n_pids=2, max_ops=8, seed=5)

    faultenv("raise:dispatch")
    res = prop_concurrent(spec, entry.impls["atomic"](spec), cfg,
                          backend=JaxTPU(spec))
    assert res.ok, res.counterexample
    assert res.timings.get("resilience_degradations", 0) >= 1


def test_collect_resilience_zeros_for_plain_backends():
    """Bench rows stamp the block unconditionally: an engine with no
    resilience hook reports explicit zeros (a claim), not a missing key
    (a shrug)."""
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    r = collect_resilience(WingGongCPU())
    assert r == {"degradations": 0, "retries": 0, "fallback_engine": None}


def test_injected_fault_is_in_the_device_error_taxonomy():
    errs = device_error_types()
    assert InjectedFault in errs and WatchdogTimeout in errs


# =====================================================================
# Checkpoint/resume — partial progress is bankable
# =====================================================================

def test_atomic_write_leaves_no_tmp_and_replaces_whole(tmp_path):
    p = tmp_path / "a.json"
    atomic_write_json(str(p), {"x": 1})
    atomic_write_json(str(p), {"x": 2}, indent=1)
    assert json.loads(p.read_text()) == {"x": 2}
    assert [f.name for f in tmp_path.iterdir()] == ["a.json"]


def test_cell_journal_banks_resumes_and_counts(tmp_path):
    path = str(tmp_path / "scan.jsonl")
    j1 = CellJournal(path, {"artifact": "s", "device_fallback": "cpu"})
    j1.emit("b256", {"rate": 1.0})
    j1.emit("b512", {"rate": 2.0})
    j1.emit("b1024", {"skipped": "time box exhausted"})

    j2 = CellJournal(path, {"artifact": "s", "device_fallback": "cpu"},
                     resume=True)
    assert j2.complete("b256") == {"cell": "b256", "rate": 1.0}
    assert j2.complete("b512")["rate"] == 2.0
    assert j2.complete("b1024") is None   # skipped markers re-run
    assert j2.resumed_cells == 2
    assert j2.header["resumed_cells"] == 2


def test_cell_journal_rejects_mismatched_provenance(tmp_path):
    """A CPU-fallback scan must never pre-satisfy a device scan's
    cells — and the mismatch guard must not DESTROY the incompatible
    artifact either (it exists to protect banked measurements): the
    prior file moves aside to <path>.pre-resume."""
    path = str(tmp_path / "scan.jsonl")
    j1 = CellJournal(path, {"artifact": "s", "device_fallback": "cpu"})
    j1.emit("b256", {"rate": 1.0})
    j2 = CellJournal(path, {"artifact": "s", "device_fallback": None},
                     resume=True)
    assert j2.resumed_cells == 0 and j2.complete("b256") is None
    saved = [json.loads(ln)
             for ln in open(path + ".pre-resume").read().splitlines()]
    assert saved[1]["rate"] == 1.0  # the incompatible bank survives


def test_cell_journal_drops_truncated_trailing_line(tmp_path):
    """A mid-write kill under a pre-journal scheme leaves half a row;
    resume adopts everything before it and simply re-runs that cell."""
    path = tmp_path / "scan.jsonl"
    path.write_text(
        json.dumps({"artifact": "s", "device_fallback": "cpu"}) + "\n"
        + json.dumps({"cell": "b256", "rate": 1.0}) + "\n"
        + '{"cell": "b512", "ra')  # killed mid-write
    j = CellJournal(str(path), {"artifact": "s",
                                "device_fallback": "cpu"}, resume=True)
    assert j.resumed_cells == 1
    assert j.complete("b256") is not None
    assert j.complete("b512") is None
    # and the rewrite healed the file: every line parses now
    for ln in path.read_text().splitlines():
        json.loads(ln)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_scan_killed_after_n_cells_resumes_with_zero_reruns(
        tmp_path, monkeypatch, capsys):
    """THE acceptance scenario: a bench_configs scan killed after 3 of 7
    cells banks those 3; the ``--resume`` re-run measures ONLY the other
    4 and inherits the banked rows bit-identically."""
    bc = _load_tool("bench_configs")
    out = str(tmp_path / "BENCH_CONFIGS.json")
    measured = []

    def fake_bench_config(model, on_tpu, n_corpus):
        if len(measured) == 3:
            raise KeyboardInterrupt  # the window closes / kill -INT
        measured.append(model)
        return {"model": model, "rate": float(len(measured))}

    monkeypatch.setattr(bc, "bench_config", fake_bench_config)
    with pytest.raises(KeyboardInterrupt):
        bc.main(["--out", out, "--force-cpu"])
    banked = [json.loads(ln) for ln in open(out)]
    assert len(banked) == 1 + 3  # header + the 3 cells paid for

    # --- the next window: --resume re-runs ZERO completed cells -------
    measured2 = []
    monkeypatch.setattr(
        bc, "bench_config",
        lambda model, on_tpu, n_corpus:
            measured2.append(model) or {"model": model, "rate": -1.0})
    assert bc.main(["--out", out, "--force-cpu", "--resume"]) == 0
    assert not set(measured) & set(measured2)   # zero re-runs
    assert len(measured2) == 7 - 3
    rows = [json.loads(ln) for ln in open(out)]
    assert rows[0]["resumed_cells"] == 3
    assert len(rows) == 1 + 7
    by_model = {r["cell"]: r for r in rows[1:]}
    for i, m in enumerate(measured):
        assert by_model[m]["rate"] == float(i + 1)  # inherited, not -1
