"""Witness extraction: a LINEARIZABLE verdict carries its own proof — the
successful linearization order — and ``verify_witness`` replays it with
NO search, so the exponential checker never has to be trusted.  Oracle,
native, and device witnesses may differ (any valid path suffices) but
every one must replay cleanly; tampered witnesses must be rejected."""

import numpy as np

from qsm_tpu import (Verdict, WingGongCPU, generate_program, run_concurrent,
                     verify_witness)
from qsm_tpu.core.history import History, Op
from qsm_tpu.models.cas import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.models.queue import AtomicQueueSUT, QueueSpec
from qsm_tpu.models.register import READ, WRITE, RegisterSpec
from qsm_tpu.native import CppOracle
from qsm_tpu.ops.jax_kernel import JaxTPU

SPEC = CasSpec(n_values=5)


def _corpus(n_pairs=12, n_pids=6, max_ops=20):
    hists = []
    for seed in range(n_pairs):
        prog = generate_program(SPEC, seed=seed, n_pids=n_pids,
                                max_ops=max_ops)
        for sut in (AtomicCasSUT(SPEC), RacyCasSUT(SPEC)):
            hists.append(run_concurrent(sut, prog, seed=f"w{seed}"))
    return hists


def test_oracle_witnesses_verify():
    oracle = WingGongCPU(memo=True)
    n_lin = n_vio = 0
    for h in _corpus():
        v, w = oracle.check_witness(SPEC, h)
        if v == Verdict.LINEARIZABLE:
            assert w is not None and verify_witness(SPEC, h, w), w
            n_lin += 1
        else:
            assert w is None
            n_vio += 1
    assert n_lin > 0 and n_vio > 0, "witness corpus vacuous"


def test_device_witnesses_verify():
    dev = JaxTPU(SPEC)
    n_lin = 0
    for h in _corpus(n_pairs=6, max_ops=16):
        v, w = dev.check_witness(SPEC, h)
        if v == Verdict.LINEARIZABLE and h.n_pending == 0:
            assert w is not None and verify_witness(SPEC, h, w), w
            n_lin += 1
    assert n_lin > 0


def test_native_witnesses_verify():
    cpp = CppOracle(SPEC)
    for h in _corpus(n_pairs=4):
        v, w = cpp.check_witness(SPEC, h)
        if v == Verdict.LINEARIZABLE:
            assert verify_witness(SPEC, h, w)


def test_pending_op_witness_carries_completion():
    spec = RegisterSpec(n_values=5)
    # pending write; the read observed 1, so the only valid witness
    # COMPLETES the write with effect before the read
    h = History([Op(0, WRITE, 1, -1, 0, 1 << 30),
                 Op(1, READ, 0, 1, 2, 3)])
    v, w = WingGongCPU().check_witness(spec, h)
    assert v == Verdict.LINEARIZABLE
    assert verify_witness(spec, h, w)
    assert (0, 0) in w  # write linearized with its (only) response 0


def test_tampered_witnesses_rejected():
    spec = RegisterSpec(n_values=5)
    h = History([Op(0, WRITE, 3, 0, 0, 1),       # write completes first
                 Op(1, READ, 0, 3, 2, 3)])       # then read sees 3
    v, w = WingGongCPU().check_witness(spec, h)
    assert v == Verdict.LINEARIZABLE and verify_witness(spec, h, w)
    # reversed order: read linearized before its real-time predecessor
    assert not verify_witness(spec, h, list(reversed(w)))
    # wrong response for a completed op
    assert not verify_witness(spec, h, [(0, 1), (1, 3)])
    # duplicate op
    assert not verify_witness(spec, h, [(0, 0), (0, 0)])
    # missing required op
    assert not verify_witness(spec, h, [(0, 0)])
    # postcondition break: read claims 3 but linearizes before the write
    h2 = History([Op(0, WRITE, 3, 0, 0, 5), Op(1, READ, 0, 3, 1, 2)])
    assert not verify_witness(spec, h2, [(1, 3), (0, 0)])
    assert verify_witness(spec, h2, [(0, 0), (1, 3)])


def test_fuzz_spec_witnesses_verify():
    """Witnesses on ARBITRARY random specs — including pending-op
    completions, whose chosen responses the witness must carry — all
    replay clean through verify_witness."""
    import random

    from qsm_tpu.utils.fuzz import RandomTableSpec, random_history

    oracle = WingGongCPU(memo=True)
    n_lin = n_pend = 0
    for k in range(6):
        spec = RandomTableSpec(seed=900 + k)
        rng = random.Random(f"w{k}")
        for _ in range(24):
            h = random_history(spec, rng, 4, 10, p_pending=0.15)
            v, w = oracle.check_witness(spec, h)
            if v == Verdict.LINEARIZABLE:
                assert verify_witness(spec, h, w), (k, w)
                n_lin += 1
                n_pend += h.n_pending > 0
    assert n_lin > 10 and n_pend > 0, "witness fuzz sample vacuous"


def test_replay_witness_cli(capsys):
    from qsm_tpu.utils.cli import main

    rc = main(["replay", "--model", "cas", "--impl", "atomic",
               "--trial-seed", "2:3", "--witness"])
    out = capsys.readouterr().out
    assert rc == 0 and "verdict: LINEARIZABLE" in out
    assert "witness verifies (search-free replay): True" in out


def test_vector_state_witness():
    spec = QueueSpec()
    prog = generate_program(spec, seed=2, n_pids=4, max_ops=14)
    h = run_concurrent(AtomicQueueSUT(spec), prog, seed="wq")
    v, w = WingGongCPU(memo=True).check_witness(spec, h)
    if v == Verdict.LINEARIZABLE:
        assert verify_witness(spec, h, w)
    dv, dw = JaxTPU(spec).check_witness(spec, h)
    if dv == Verdict.LINEARIZABLE and h.n_pending == 0:
        assert verify_witness(spec, h, dw)
