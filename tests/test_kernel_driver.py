"""Chunked-driver round-4 mechanics (ops/jax_kernel.py): device-side lane
compaction must behave exactly like the host reference path, and the
double-buffered tail (speculative next-chunk dispatch) must change cost
only, never verdicts."""

import pytest

import numpy as np

from qsm_tpu.models.cas import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.ops.jax_kernel import JaxTPU
from qsm_tpu.utils.corpus import build_corpus

SPEC = CasSpec()


def _corpus(n=48, ops=32):
    return build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=n, n_pids=8,
                        max_ops=ops, seed_base=1000, seed_prefix="drv")


def test_device_compaction_matches_host_reference():
    """Both compaction paths must yield identical verdicts and identical
    compaction/round counts on a corpus that forces bucket shrinks and
    cache growth (lanes retire across rounds)."""
    corpus = _corpus()

    dev = JaxTPU(SPEC)
    v_dev = np.asarray(dev.check_histories(SPEC, corpus))
    assert dev.compactions > 0, "corpus must exercise compaction"

    host = JaxTPU(SPEC)
    host._compact_carry = host._compact_carry_host  # reference path
    v_host = np.asarray(host.check_histories(SPEC, corpus))

    assert (v_dev == v_host).all()
    assert dev.compactions == host.compactions
    assert dev.rounds_run == host.rounds_run


@pytest.mark.slow
def test_device_compaction_rehash_grows_cache_correctly():
    """Force a slot-size change (bucket shrink grows the per-lane cache)
    and pin that post-compaction searches still decide every lane — a
    corrupted re-hash would surface as wrong verdicts or blown budgets."""
    from qsm_tpu import WingGongCPU

    corpus = _corpus(n=80)
    dev = JaxTPU(SPEC)
    v = np.asarray(dev.check_histories(SPEC, corpus))
    want = np.asarray(WingGongCPU(memo=True).check_histories(SPEC, corpus))
    both = (v != 2) & (want != 2)
    assert both.any()
    assert ((v == want) | ~both).all()


@pytest.mark.slow
def test_double_buffer_parity_and_accounting():
    """DOUBLE_BUFFER=True must produce identical verdicts and identical
    round structure (the speculative chunk IS the next round's work);
    its cost shows up only in the speculated/wasted counters."""
    corpus = _corpus()
    # a short schedule reaches the settled tail (where speculation is
    # allowed) within the corpus's round count
    sched = (64, 256)

    plain = JaxTPU(SPEC)
    plain.CHUNK_SCHEDULE = sched
    plain.DOUBLE_BUFFER = False
    v0 = np.asarray(plain.check_histories(SPEC, corpus))
    assert plain.speculated_chunks == 0 and plain.wasted_chunks == 0

    spec_on = JaxTPU(SPEC)
    spec_on.CHUNK_SCHEDULE = sched
    spec_on.DOUBLE_BUFFER = True  # forced on (auto is off on CPU)
    v1 = np.asarray(spec_on.check_histories(SPEC, corpus))

    assert (v0 == v1).all()
    assert spec_on.rounds_run == plain.rounds_run
    assert spec_on.speculated_chunks > 0
    # every speculative chunk is either consumed as the next round or
    # wasted at a compaction/termination boundary
    consumed = spec_on.speculated_chunks - spec_on.wasted_chunks
    assert 0 <= consumed <= spec_on.rounds_run


def test_double_buffer_auto_off_on_cpu():
    b = JaxTPU(SPEC)
    assert b._double_buffer_on() is False  # conftest pins the CPU platform


def test_host_sync_accounting_accumulates():
    b = JaxTPU(SPEC)
    b.check_histories(SPEC, _corpus(n=16))
    assert b.host_sync_s > 0.0
    assert b.rounds_run > 0


@pytest.mark.slow
def test_unroll_bit_identical_to_single_step():
    """UNROLL=K applies K freeze-guarded micro-steps per while trip:
    verdicts AND per-lane iteration counts must be bit-identical to
    UNROLL=1 across the full chunked driver (compaction included)."""
    import numpy as np

    from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.utils.corpus import build_corpus

    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=24,
                          n_pids=4, max_ops=24, seed_base=55,
                          seed_prefix="unroll")

    base = JaxTPU(spec, budget=2_000)
    v1 = np.asarray(base.check_histories(spec, corpus))

    k8 = JaxTPU(spec, budget=2_000)
    k8.UNROLL = 8
    v8 = np.asarray(k8.check_histories(spec, corpus))

    assert (v1 == v8).all()
    # same total lockstep work was *needed*: iters are counted per real
    # step, frozen micro-steps don't increment, so the accounted cost is
    # iteration-identical (rescued is 0==0 on this corpus — vacuous —
    # but lockstep_cost is sensitive to every per-trip iter delta)
    assert base.lockstep_cost == k8.lockstep_cost
    assert base.rescued == k8.rescued


def test_compaction_out_of_cache_off_bucket():
    """The widest buckets run cache-off (slots=0, MAX_SLOTS_FOR_BATCH);
    survivors compacting into a cached bucket must get a fresh empty
    table (nothing to re-hash) with verdicts still oracle-identical."""
    import numpy as np

    from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.utils.corpus import build_corpus

    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=40,
                          n_pids=4, max_ops=24, seed_base=91,
                          seed_prefix="cacheoff")

    b = JaxTPU(spec, budget=2_000)
    # corpus of 40 starts in the 64 bucket CACHE-OFF; survivors compact
    # into the 8-bucket with a real cache -> exercises the 0 -> K path
    b.MAX_SLOTS_FOR_BATCH = dict(b.MAX_SLOTS_FOR_BATCH)
    b.MAX_SLOTS_FOR_BATCH[64] = 0
    b.CHUNK_SCHEDULE = (16, 64, 2048)
    got = np.asarray(b.check_histories(spec, corpus))
    want = np.asarray(WingGongCPU(memo=True).check_histories(spec, corpus))
    both = (got != 2) & (want != 2)
    assert both.all() and (got == want).all()
    assert b.compactions >= 1  # the 0 -> K transition really happened
