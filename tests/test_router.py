"""auto-tpu router (ops/router.py): per-history strategy routing must
change COST only — verdicts stay oracle-exact on every route, the
segment-structure rule sends shattered histories to segdc and dense ones
to the plain kernel, and partitionable specs decompose per key first."""

import numpy as np

from qsm_tpu import Verdict, WingGongCPU
from qsm_tpu.core.history import History, Op
from qsm_tpu.models.cas import CasSpec
from qsm_tpu.models.queue import QueueSpec
from qsm_tpu.ops.router import AutoDevice
from qsm_tpu.utils.corpus import build_corpus


def _seq_ops(specs):
    """Fully sequential ops (every op a singleton segment)."""
    ops = []
    t = 0
    for pid, cmd, arg, resp in specs:
        ops.append(Op(pid=pid, cmd=cmd, arg=arg, resp=resp,
                      invoke_time=t, response_time=t + 1))
        t += 2
    return ops


def test_router_parity_with_oracle_queue():
    from qsm_tpu.models.queue import AtomicQueueSUT, RacyTwoPhaseQueueSUT

    spec = QueueSpec()
    corpus = build_corpus(spec, (AtomicQueueSUT, RacyTwoPhaseQueueSUT),
                          n=24, n_pids=4, max_ops=24, seed_base=77,
                          seed_prefix="router")
    auto = AutoDevice(spec, budget=2_000, mid_budget=10_000,
                      rescue_budget=100_000)
    got = np.asarray(auto.check_histories(spec, corpus))
    want = np.asarray(WingGongCPU(memo=True).check_histories(spec, corpus))
    both = (got != 2) & (want != 2)
    assert both.any(), "no lane decided — parity check would be vacuous"
    assert ((got == want) | ~both).all()
    assert auto.routed_plain + auto.routed_segdc == len(corpus)


def test_router_sends_shattered_histories_to_segdc():
    """A long, fully sequential history shatters into singleton segments:
    middle segments are trivial and the final-segment bucket collapses —
    the segdc route."""
    spec = CasSpec()
    h = History(_seq_ops([(0, 1, (i % 4) + 1, 0) for i in range(48)]))
    auto = AutoDevice(spec)
    assert auto._route_segdc(h)
    v = auto.check_histories(spec, [h])
    assert auto.routed_segdc == 1 and auto.routed_plain == 0
    # write-only sequential history is trivially linearizable
    assert v[0] == int(Verdict.LINEARIZABLE)


def test_router_keeps_dense_histories_on_plain():
    """One big overlapping block (every op concurrent with every other)
    has no cuts — must go to the plain kernel."""
    spec = CasSpec()
    ops = [Op(pid=p, cmd=1, arg=1, resp=0, invoke_time=0,
              response_time=100 + p) for p in range(6)]
    h = History(ops)
    auto = AutoDevice(spec)
    assert not auto._route_segdc(h)
    auto.check_histories(spec, [h])
    assert auto.routed_plain == 1 and auto.routed_segdc == 0


def test_router_rejects_wide_middle_segments():
    """Cuts exist, but one middle segment is wider than WIDTH_CAP
    concurrent ops: host enumeration risk — plain."""
    spec = CasSpec()
    block = [Op(pid=p, cmd=1, arg=1, resp=0, invoke_time=1,
                response_time=30 + p) for p in range(4)]
    # pad the dense block past MID_CAP ops so it is the oversized middle
    block += [Op(pid=4 + (i % 4), cmd=0, arg=0, resp=1, invoke_time=2 + i,
                 response_time=28 - i) for i in range(14)]
    tail = [Op(pid=0, cmd=0, arg=0, resp=1, invoke_time=200 + 2 * i,
               response_time=201 + 2 * i) for i in range(4)]
    head = [Op(pid=0, cmd=1, arg=1, resp=0, invoke_time=-10,
               response_time=-9)]
    h = History(head + block + tail)
    auto = AutoDevice(spec)
    assert len(h) > 18
    assert not auto._route_segdc(h)


def test_router_decomposes_partitionable_specs():
    from qsm_tpu.models.kv import KvSpec

    spec = KvSpec(n_keys=4)
    auto = AutoDevice(spec)
    assert auto.pcomp is not None
    assert auto.name.startswith("auto(")


def test_router_mixed_batch_verdicts_land_in_order():
    """Routing splits the batch; verdicts must come back in INPUT order,
    pinned against the oracle one history at a time."""
    from qsm_tpu.models.cas import AtomicCasSUT, RacyCasSUT

    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=10,
                          n_pids=3, max_ops=12, seed_base=5,
                          seed_prefix="mix")
    # interleave a shattered sequential history so both routes are used
    corpus.insert(3, History(_seq_ops(
        [(0, 1, (i % 4) + 1, 0) for i in range(48)])))
    auto = AutoDevice(spec)
    got = np.asarray(auto.check_histories(spec, corpus))
    oracle = WingGongCPU(memo=True)
    for i, h in enumerate(corpus):
        want = oracle.check_histories(spec, [h])[0]
        if got[i] != 2 and want != 2:
            assert got[i] == want, i
    assert auto.routed_segdc >= 1 and auto.routed_plain >= 1
