"""Worker for the mesh-parity subprocess lane (tests/test_mesh.py).

Spawned once per mesh shape with a FORCED host device count
(``forced_host_device_env`` — the no-hardware recipe docs/MESH.md
documents): the same seed-derived corpus rides the full mesh substrate
— planned sharded backends (the kv lanes pcomp-split into per-key
sub-lanes), the kernel's witness extraction, one shrink run — and the
report is everything ISSUE 19's parity gate compares bit-for-bit
across shapes: verdicts, witnesses, minimized shrink rows, plan names.

Importable by the parent test for the shared corpus constants; the
``__main__`` path is the subprocess body.
"""

from __future__ import annotations

import json
import sys

# (family, lanes, n_pids, max_ops, seed_base): small enough for the
# default test lane, shaped so kv still crosses the planner's pcomp
# threshold; per-family seeds picked so every family's verdict set is
# MIXED (a single-verdict corpus would make parity vacuous)
FAMILY_SHAPES = (("register", 16, 6, 12, 11), ("cas", 16, 6, 14, 2026),
                 ("queue", 12, 6, 12, 2026), ("kv", 8, 8, 20, 11))
WITNESS_LANES = 4
BUDGET = 200_000


def build_corpora():
    """Seed-derived: every worker builds the identical histories."""
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.utils.corpus import build_corpus

    out = {}
    for fam, lanes, n_pids, max_ops, seed in FAMILY_SHAPES:
        entry = MODELS[fam]
        spec = entry.make_spec()
        out[fam] = (spec, build_corpus(
            spec, (entry.impls["atomic"], entry.impls["racy"]),
            n=lanes, n_pids=n_pids, max_ops=max_ops,
            seed_base=seed, seed_prefix=f"mesh_{fam}"))
    return out


def main(argv) -> int:
    n_devices, out_path = int(argv[0]), argv[1]
    sys.path.insert(0, "/root/repo")
    # env alone is not enough once the image's sitecustomize registered
    # the axon plugin (tests/_distributed_worker.py has the same dance)
    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform(n_devices)
    import jax

    from qsm_tpu.mesh import (backend_sharding, batch_sharding,
                              make_mesh, mesh_shape_key, sharded_backend)
    from qsm_tpu.ops.backend import Verdict, verify_witness
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.search.planner import plan_search, profile_corpus
    from qsm_tpu.serve.protocol import history_to_rows
    from qsm_tpu.shrink.shrinker import shrink_history

    assert jax.device_count() == n_devices, (jax.device_count(),
                                             n_devices)
    sharding = (batch_sharding(make_mesh(n_devices))
                if n_devices > 1 else None)
    corpora = build_corpora()
    report = {"devices": n_devices, "families": {},
              "witness_failures": 0}
    backends = {}
    for fam, (spec, hists) in corpora.items():
        # profiled plans so kv really crosses the pcomp gate: its
        # lanes decide as per-key sub-lanes ON the mesh
        profile = profile_corpus(hists, spec)
        backend = sharded_backend(spec, devices=n_devices,
                                  budget=BUDGET, profile=profile)
        backends[fam] = backend
        plan = plan_search(spec, profile, mesh_devices=n_devices)
        fam_report = {
            "plan": plan.name,
            "pcomp": bool(plan.decompose_keys),
            "mesh_shape_key": list(
                mesh_shape_key(backend_sharding(backend))),
            "verdicts": [int(v)
                         for v in backend.check_histories(spec, hists)],
        }
        # witness lane: the kernel's chosen-stack extraction under the
        # same sharding, every LINEARIZABLE witness replayed
        kern = JaxTPU(spec, budget=BUDGET, sharding=sharding)
        rows = []
        for h in hists[:WITNESS_LANES]:
            v, w = kern.check_witness(spec, h)
            rows.append([int(v), None if w is None else
                         [[int(a), int(b)] for a, b in w]])
            if w is not None and not verify_witness(spec, h, w):
                report["witness_failures"] += 1
        fam_report["witnesses"] = rows
        report["families"][fam] = fam_report

    # shrink lane: minimize the first failing cas history on the
    # mesh-planned backend — rows must be shape-invariant
    cas_spec, cas_hists = corpora["cas"]
    cas_verdicts = report["families"]["cas"]["verdicts"]
    failing = [i for i, v in enumerate(cas_verdicts)
               if v == int(Verdict.VIOLATION)]
    assert failing, "mesh worker corpus lost its failing cas lanes"
    res = shrink_history(cas_spec, cas_hists[failing[0]],
                         backend=backends["cas"], certificate=False)
    report["shrink_ok"] = bool(res.ok)
    report["shrink_rows"] = history_to_rows(res.history)

    with open(out_path, "w") as f:
        json.dump(report, f)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
