"""Batch-width scaling: the wide batch buckets behind ``JaxTPU.MAX_BATCH``
and the bench.py adoption rules for a device-captured bench_scale artifact
(tools/bench_scale.py; motivated by BENCH_TPU_r04.json — per-trip latency
dominated the first real-TPU window, wider lockstep batches amortize it)."""

from __future__ import annotations

import importlib.util
import json
import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_scale(dirpath, rows, fallback=None):
    lines = [{"artifact": "bench_scale", "device_fallback": fallback}]
    lines += rows
    with open(os.path.join(dirpath, "BENCH_SCALE_TPU_WINDOW.json"),
              "w") as f:
        for ln in lines:
            f.write(json.dumps(ln) + "\n")


def test_no_artifact_means_no_adoption(tmp_path):
    bench = _load_bench()
    assert bench.best_scale_batch(dirpath=str(tmp_path)) is None


def test_cpu_fallback_artifact_never_adopted(tmp_path):
    bench = _load_bench()
    _write_scale(tmp_path, [
        {"batch": 4096, "rate_h_per_s": 100.0, "wrong": 0},
        {"batch": 65536, "rate_h_per_s": 900.0, "wrong": 0},
    ], fallback="cpu")
    assert bench.best_scale_batch(dirpath=str(tmp_path)) is None


def test_wrong_verdict_rows_are_disqualified(tmp_path):
    bench = _load_bench()
    _write_scale(tmp_path, [
        {"batch": 4096, "rate_h_per_s": 100.0, "wrong": 0},
        {"batch": 65536, "rate_h_per_s": 900.0, "wrong": 3},
    ])
    assert bench.best_scale_batch(dirpath=str(tmp_path)) is None


def test_gain_gate_keeps_default_on_marginal_wins(tmp_path):
    bench = _load_bench()
    _write_scale(tmp_path, [
        {"batch": 4096, "rate_h_per_s": 100.0, "wrong": 0},
        {"batch": 16384, "rate_h_per_s": 110.0, "wrong": 0},
    ])
    assert bench.best_scale_batch(dirpath=str(tmp_path)) is None


def test_no_valid_4096_baseline_means_no_adoption(tmp_path):
    bench = _load_bench()
    _write_scale(tmp_path, [
        {"batch": 4096, "rate_h_per_s": 100.0, "wrong": 2},
        {"batch": 16384, "rate_h_per_s": 400.0, "wrong": 0},
    ])
    assert bench.best_scale_batch(dirpath=str(tmp_path)) is None


def test_window_sized_wall_clock_gate(tmp_path):
    """A width whose single timed rep would exceed ~300 s is not adopted
    even if it is the fastest row — the next healing window must fit the
    re-bench; a slower-but-window-sized width still wins."""
    bench = _load_bench()
    _write_scale(tmp_path, [
        {"batch": 4096, "rate_h_per_s": 100.0, "wrong": 0},
        {"batch": 16384, "rate_h_per_s": 300.0, "wrong": 0},
        {"batch": 65536, "rate_h_per_s": 210.0, "wrong": 0},  # 312 s/rep
    ])
    assert bench.best_scale_batch(dirpath=str(tmp_path)) == (16384, 300.0)


def test_stale_artifact_rejected(tmp_path):
    bench = _load_bench()
    _write_scale(tmp_path, [
        {"batch": 4096, "rate_h_per_s": 100.0, "wrong": 0},
        {"batch": 65536, "rate_h_per_s": 900.0, "wrong": 0},
    ])
    path = tmp_path / "BENCH_SCALE_TPU_WINDOW.json"
    old = bench.time.time() - bench.WINDOW_MAX_AGE_S - 60
    os.utime(path, (old, old))
    assert bench.best_scale_batch(dirpath=str(tmp_path)) is None


def test_validated_wider_batch_is_adopted(tmp_path):
    bench = _load_bench()
    _write_scale(tmp_path, [
        {"batch": 4096, "rate_h_per_s": 100.0, "wrong": 0},
        {"batch": 16384, "rate_h_per_s": 350.0, "wrong": 0},
        {"batch": 65536, "rate_h_per_s": 900.0, "wrong": 0,
         "undecided": 4},
        {"batch": 262144, "error": "RESOURCE_EXHAUSTED: oom"},
        # diagnostic variant rows never drive adoption, even when their
        # decided-lane rate is the fastest number in the artifact
        {"batch": 65536, "variant": "budget2k", "rate_h_per_s": 5000.0,
         "wrong": 0, "undecided": 30000},
    ])
    assert bench.best_scale_batch(dirpath=str(tmp_path)) == (65536, 900.0)


def test_raised_max_batch_matches_split_path():
    """The same flat batch decided through one wide bucket (MAX_BATCH
    raised) and through the default 4096-split path must agree verdict for
    verdict — the wide buckets change padding, never semantics."""
    from qsm_tpu.models.register import RegisterSpec
    from qsm_tpu.ops.jax_kernel import JaxTPU
    import qsm_tpu as q

    spec = RegisterSpec(n_values=4)
    base = [
        q.overlapping_history(rows) for rows in (
            [(0, 1, 3, 0, 0, 1), (1, 0, 0, 3, 2, 3)],   # seq write, read ok
            [(0, 1, 2, 0, 0, 3), (1, 0, 0, 1, 1, 2)],   # racy read -> bad
            [(0, 1, 1, 0, 0, 1), (1, 1, 2, 0, 0, 1),    # overlapping writes
             (0, 0, 0, 2, 2, 3)],
        )
    ]
    flat = (base * ((4100 + len(base) - 1) // len(base)))[:4100]

    wide = JaxTPU(spec, budget=2_000)
    wide.MAX_BATCH = 16384
    wide_verdicts = np.asarray(wide.check_histories(spec, flat))
    assert wide.batches_run >= 1

    split = JaxTPU(spec, budget=2_000)  # default MAX_BATCH=4096 -> 2 calls
    split_verdicts = np.asarray(split.check_histories(spec, flat))

    assert (wide_verdicts == split_verdicts).all()
    # at least one lane of each verdict kind so the parity is non-vacuous
    assert set(np.unique(split_verdicts)) >= {0, 1}
