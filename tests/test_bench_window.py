"""bench.py window-artifact headline: a real-TPU line cached by the
round-long watcher becomes the round's headline when the tunnel is wedged
again at bench time — with provenance — and a CPU-fallback line never
gets promoted (VERDICT.md round 2, "Next round" #1)."""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _tpu_line():
    return {
        "metric": "histories_per_sec_linearized_32ops_x_8pids",
        "value": 12345.6, "unit": "histories/sec",
        "vs_baseline": 999.0, "vs_best_cpu": 10.4,
        "captured_iso": "2026-07-29T20:45:00+00:00",
        "extras": {"device": "TPU v5 lite0", "device_fallback": None,
                   "wrong_verdicts_on_sample": 0},
    }


def test_window_artifact_loads_and_rejects_fallback(tmp_path, monkeypatch):
    bench = _load_bench()
    art = tmp_path / "BENCH_TPU_WINDOW.json"
    monkeypatch.setattr(bench, "WINDOW_ARTIFACT", str(art))

    assert bench._load_window_artifact() is None  # absent
    art.write_text("not json")
    assert bench._load_window_artifact() is None  # corrupt

    line = _tpu_line()
    line["extras"]["device_fallback"] = "cpu"
    art.write_text(json.dumps(line))
    assert bench._load_window_artifact() is None  # fallback: never promoted

    line["extras"]["device_fallback"] = None
    art.write_text(json.dumps(line))
    got = bench._load_window_artifact()
    assert got is not None and got["value"] == 12345.6


def test_main_uses_cached_window_when_probe_wedged(tmp_path, monkeypatch,
                                                   capsys):
    bench = _load_bench()
    art = tmp_path / "BENCH_TPU_WINDOW.json"
    art.write_text(json.dumps(_tpu_line()))
    monkeypatch.setattr(bench, "WINDOW_ARTIFACT", str(art))
    monkeypatch.setattr(bench, "PROBE_LOG", str(tmp_path / "probes.jsonl"))

    import qsm_tpu.utils.device as device

    monkeypatch.setattr(
        device, "probe_default_backend",
        lambda *a, **kw: device.Probe(False, "none", "wedged (test)"))
    # stub module entry too (bench imports the name from the module)
    monkeypatch.setitem(sys.modules, "qsm_tpu.utils.device", device)

    rc = bench.main(["--retries", "0"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 12345.6
    ex = out["extras"]
    assert ex["headline_from_cached_window"] is True
    assert ex["window_captured_iso"] == "2026-07-29T20:45:00+00:00"
    assert "wedged (test)" in ex["tpu_probe_at_bench_time"]
    assert out.get("captured_iso") is None  # moved into extras
    # the cached line must carry the frozen ratio family too (BENCH_r06
    # always has both families, live and frozen — ISSUE 2 satellite):
    # denominators from the committed per-round BASELINE_HOST file
    frozen = bench._frozen_host_rates()
    assert frozen is not None, "committed frozen-denominator file missing"
    assert ex["vs_baseline_frozen"] == round(12345.6
                                             / frozen["cpu_oracle_rate"], 2)
    assert "vs_best_host_frozen" in ex
    assert ex["frozen_denominator_file"] == bench.FROZEN_HOST_FILE


@pytest.mark.slow
def test_force_cpu_ignores_window_artifact(tmp_path, monkeypatch, capsys):
    """--force-cpu explicitly asks for a live CPU measurement; the cached
    TPU line must not short-circuit it.  (Runs the real fallback bench at
    reduced scale minus the sweep — a few seconds.)"""
    bench = _load_bench()
    art = tmp_path / "BENCH_TPU_WINDOW.json"
    art.write_text(json.dumps(_tpu_line()))
    monkeypatch.setattr(bench, "WINDOW_ARTIFACT", str(art))
    monkeypatch.setattr(bench, "PROBE_LOG", str(tmp_path / "probes.jsonl"))
    monkeypatch.setattr(bench, "_scale", lambda on_tpu: dict(
        n_unique=8, device_batch=8, cpu_sample=2, cpu_timebox_s=5.0,
        reps=1, budget=2_000))

    rc = bench.main(["--force-cpu", "--no-sweep"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] != 12345.6
    assert out["extras"]["device_fallback"] == "cpu"


@pytest.mark.slow
def test_run_sweep_structure_fast():
    """The sweep path (default bench run) at miniature scale: structure,
    solved table, and the honest cpp coverage cap."""
    bench = _load_bench()
    sw = bench.run_sweep(on_tpu=False, buckets=(12, 24), n_sample=2,
                         box_s=30.0)
    assert set(sw["solved"]) == {"cas", "queue"}
    for cname, backends in sw["solved"].items():
        assert "memo" in backends and "device" in backends, cname
        for bname, best in backends.items():
            assert best in (0, 12, 24), (cname, bname, best)
    # cells carry per-bucket measurements with verdict accounting
    cas_memo = sw["cells"]["cas"]["memo"]
    assert "12" in cas_memo and cas_memo["12"]["undecided"] == 0
    assert cas_memo["12"]["solved"] is True


def test_watcher_banks_round_stamped_committed_copy(tmp_path, monkeypatch):
    """A caught window must leave COMMITTED evidence: the watcher writes a
    round-stamped twin next to the gitignored runtime artifact (VERDICT.md
    round 3, "Next round" #1 — the driver's end-of-round commit then picks
    it up even unattended)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "watcher_under_test", os.path.join(REPO, "tools",
                                           "probe_watcher.py"))
    w = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(w)

    src = tmp_path / "BENCH_TPU_WINDOW.json"
    dst = tmp_path / "BENCH_TPU_r04.json"
    monkeypatch.setitem(w.COMMITTED_COPIES, str(src), str(dst))
    src.write_text(json.dumps(_tpu_line()))
    w._bank_committed_copy(str(src))
    assert json.loads(dst.read_text())["value"] == 12345.6
    # unknown runtime paths are a no-op, not an error
    w._bank_committed_copy(str(tmp_path / "unknown.json"))
