"""Streaming monitor sessions (qsm_tpu/monitor) — the ISSUE 14 gates.

What is pinned, in order of importance:

* STREAMING PARITY: a history fed event-by-event through a session
  yields the same verdict — and, through the serve path, a
  bit-identical witness — as the whole-history ``check`` path, across
  register/cas/queue/kv (per-key composition included), with zero
  wrong verdicts;
* INCREMENTALITY: re-feeding a stream resumes every committed cut
  from the decided-prefix bank with ZERO engine folds (pinned by
  making the engine fold unreachable), and a one-key kv event
  re-checks exactly one key's frontier;
* THE FLIP: a seeded mid-stream violation is pushed on the deciding
  append with a 1-minimal shrink-plane repro whose certificate
  replays via ``verify_witness``; a flip is terminal;
* FLEET RESUME: a session routed through a FleetRouter survives its
  owning node being SIGKILLed and respawned on the same replog —
  the replayed journal resumes from the banked decided prefix and the
  flight dump names the session's trace id;
* bounds and refusals: session/event caps SHED, gap seqs and
  backwards timestamps are refused loudly, appends are idempotent
  under seq replay.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from qsm_tpu.core.spec import projection_report
from qsm_tpu.models.registry import MODELS
from qsm_tpu.monitor import (IncrementalFrontier, MonitorSession,
                             PrefixHasher, SessionError, SessionLimit,
                             SessionManager, decode_frontier_states,
                             encode_frontier_states)
from qsm_tpu.ops.backend import Verdict, verify_witness
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.serve import (CheckClient, CheckServer, SessionHandle,
                           VerdictCache)
from qsm_tpu.serve.protocol import history_to_rows
from qsm_tpu.utils.corpus import build_corpus
from qsm_tpu.utils.report import history_from_rows

FAMILIES = ("register", "cas", "queue", "kv")


def _corpus(family, n=8, pids=3, ops=10, prefix="mon"):
    entry = MODELS[family]
    spec = entry.make_spec()
    hists = build_corpus(
        spec, (entry.impls["atomic"], entry.impls["racy"]), n=n,
        n_pids=pids, max_ops=ops, seed_prefix=f"{prefix}_{family}")
    return spec, hists


def _proj_for(spec):
    if projection_report(spec):
        return None
    p = spec.projected_spec()
    return p if p.name in MODELS else None


# --- frontier units --------------------------------------------------------

def test_prefix_hasher_is_incremental_and_spec_scoped():
    spec = MODELS["register"].make_spec()
    a, b = PrefixHasher(spec), PrefixHasher(spec)
    h = history_from_rows([[0, 1, 1, 0, 0, 1], [0, 0, 0, 1, 2, 3]])
    for op in h.ops:
        a.push(op)
    # same ops, one at a time with key() peeks in between: the rolling
    # digest must not depend on when keys were taken
    mid_keys = []
    for op in h.ops:
        b.push(op)
        mid_keys.append(b.key())
    assert a.key() == mid_keys[-1]
    assert len(set(mid_keys)) == len(mid_keys)  # every prefix distinct
    # a different spec identity hashes into a different domain
    c = PrefixHasher(MODELS["cas"].make_spec())
    for op in h.ops:
        c.push(op)
    assert c.key() != a.key()


def test_frontier_states_round_trip_through_witness_slot():
    states = {(0, 3), (1, 2), (2, 0)}
    enc = encode_frontier_states(states)
    assert decode_frontier_states(enc) == states
    # the bank load path converts rows to tuples — decode takes both
    assert decode_frontier_states([tuple(r) for r in enc]) == states
    # an ordinary witness (op_index, resp) payload is NOT a frontier
    assert decode_frontier_states([(0, 1), (1, 0)]) is None
    assert decode_frontier_states(None) is None


def test_frontier_commits_cuts_and_evicts_window():
    spec = MODELS["register"].make_spec()
    f = IncrementalFrontier(spec)
    # two sequential writes: each creates a quiescent cut
    f.invoke(0, 1, 1, 0)
    f.respond(0, 0, 1)
    f.invoke(0, 1, 2, 2)
    f.respond(0, 0, 3)
    f.invoke(1, 0, 0, 4)   # pending read
    assert f.advance() == int(Verdict.LINEARIZABLE)
    assert f.counters.advances >= 1
    assert f.counters.committed_ops >= 1
    assert len(f.window) < 3  # decided prefix evicted
    assert f.check_window() == int(Verdict.LINEARIZABLE)


def test_frontier_empty_fold_is_exact_violation():
    spec = MODELS["register"].make_spec()
    f = IncrementalFrontier(spec)
    f.invoke(0, 1, 1, 0)
    f.respond(0, 0, 1)
    f.invoke(0, 0, 0, 2)
    f.respond(0, 2, 3)     # reads 2: impossible after write 1
    f.invoke(0, 1, 1, 10)  # forces a cut behind the poisoned prefix
    assert f.advance() == int(Verdict.VIOLATION)


# --- streaming parity ------------------------------------------------------

# sized so every family's racy corpus contains at least one violation
# (the parity sample must not be vacuous) while staying test-lane cheap
_PARITY_SHAPE = {"register": (16, 4, 12), "cas": (24, 4, 14),
                 "queue": (8, 3, 10), "kv": (32, 6, 16)}


@pytest.mark.parametrize("family", FAMILIES)
def test_streamed_verdicts_equal_whole_history_check(family):
    """THE parity pin: event-by-event streaming decides identically to
    the one-shot oracle on every history of a racy corpus — per-key
    composition included (kv) — and mid-stream verdicts are exact at
    every step (a flip only ever fires on a real violation)."""
    n, pids, ops = _PARITY_SHAPE[family]
    spec, hists = _corpus(family, n=n, pids=pids, ops=ops)
    oracle = WingGongCPU(memo=True)
    want = [int(v) for v in oracle.check_histories(spec, hists)]
    proj = _proj_for(spec)
    assert (proj is not None) == (family == "kv")
    wrong = 0
    for k, h in enumerate(hists):
        s = MonitorSession(f"p{k}", spec, proj_spec=proj)
        for row in history_to_rows(h):
            s.append([row])
            s.decide()
        if s.close() != want[k]:
            wrong += 1
    assert wrong == 0
    assert any(v == int(Verdict.VIOLATION) for v in want)  # not vacuous


def test_served_session_witness_bit_identical_to_check(server_pair):
    """Through the serve path, a streamed session's close witness is
    BIT-IDENTICAL to `check --witness` of the same history (both ride
    the same machinery and the same cache row)."""
    srv, client = server_pair
    spec, hists = _corpus("cas", n=6)
    oneshot = client.check("cas", hists, witness=True)
    assert oneshot["ok"]
    for h, want_v, want_w in zip(hists, oneshot["verdicts"],
                                 oneshot["witnesses"]):
        handle = SessionHandle(client, "cas")
        for row in history_to_rows(h):
            handle.append([row])
        out = handle.close(witness=True)
        assert out["ok"] and out["verdict"] == want_v
        assert out.get("witness") == want_w
        if want_w is not None:
            assert verify_witness(spec, h,
                                  [tuple(p) for p in out["witness"]])


@pytest.fixture()
def server_pair():
    srv = CheckServer(flush_s=0.005, max_lanes=16).start()
    client = CheckClient(srv.address)
    yield srv, client
    client.close()
    srv.stop()


# --- incrementality --------------------------------------------------------

def test_resume_replays_from_bank_with_zero_engine_folds(monkeypatch):
    """The decided-prefix bank hit pin: re-feeding a stream through a
    fresh session sharing the bank must commit every cut as a bank hit
    — the engine fold is made UNREACHABLE, so a single miss fails."""
    from qsm_tpu.core.history import sequential_history

    spec = MODELS["register"].make_spec()
    h = sequential_history([(0, 1, 1, 0), (0, 0, 0, 1),
                            (1, 1, 2, 0), (1, 0, 0, 2)] * 10)
    rows = history_to_rows(h)
    bank = VerdictCache(max_entries=4096)
    s1 = MonitorSession("a", spec, bank=bank)
    for r in rows:
        s1.append([r])
        s1.decide()
    assert s1.close() == int(Verdict.LINEARIZABLE)
    c1 = s1.counters()
    assert c1["advances"] > 10 and c1["prefix_hits"] == 0

    import qsm_tpu.monitor.frontier as frontier_mod

    def _boom(*_a, **_k):
        raise AssertionError("engine fold reached on a banked resume")

    monkeypatch.setattr(frontier_mod, "_end_states", _boom)
    s2 = MonitorSession("b", spec, bank=bank)
    for r in rows:
        s2.append([r])
        s2.decide()
    assert s2.close() == int(Verdict.LINEARIZABLE)
    c2 = s2.counters()
    assert c2["advances"] == c1["advances"]
    assert c2["prefix_hits"] == c2["advances"]


def test_one_key_event_rechecks_one_keys_frontier():
    """The per-key shape: a kv session's append touching key 0 must
    re-check key 0's window only (pcomp per suffix — the o(n) claim)."""
    spec = MODELS["kv"].make_spec()
    proj = _proj_for(spec)
    assert proj is not None
    nv = spec.n_values
    s = MonitorSession("k", spec, proj_spec=proj)
    # seed three keys with one completed put each — LIVE events, so
    # every response is final on arrival (row responses wait for the
    # invoke horizon by design, re-dirtying keys later)
    for key in (0, 1, 2):
        s.append([{"type": "invoke", "pid": 0, "cmd": 1,
                   "arg": key * nv + 1},
                  {"type": "respond", "pid": 0, "resp": 0}])
        s.decide()
    before = {k: f.counters.window_checks
              for k, f in s._frontiers.items()}
    s.append([{"type": "invoke", "pid": 1, "cmd": 0, "arg": 0},
              {"type": "respond", "pid": 1, "resp": 1}])  # get k0 -> 1
    s.decide()
    after = {k: f.counters.window_checks
             for k, f in s._frontiers.items()}
    assert after[0] == before[0] + 1
    for k in (1, 2):
        assert after[k] == before[k]


# --- the flip --------------------------------------------------------------

def test_flip_is_pushed_with_minimal_repro_and_certificate(server_pair):
    srv, client = server_pair
    spec = MODELS["register"].make_spec()
    handle = SessionHandle(client, "register")
    for _ in range(5):
        handle.append([{"type": "invoke", "pid": 0, "cmd": 1, "arg": 1},
                       {"type": "respond", "pid": 0, "resp": 0}])
    assert handle.verdict == "LINEARIZABLE" and not handle.flips
    out = handle.append([{"type": "invoke", "pid": 1, "cmd": 0,
                          "arg": 0},
                         {"type": "respond", "pid": 1, "resp": 2}])
    assert out["verdict"] == "VIOLATION"
    flip = out["flip"]
    assert flip["one_minimal"] and flip["complete"]
    repro = history_from_rows(flip["repro"])
    assert len(repro) == flip["final_ops"] <= flip["initial_ops"]
    # the repro IS a violation
    assert int(WingGongCPU(memo=True).check_histories(
        spec, [repro])[0]) == int(Verdict.VIOLATION)
    # and its certificate replays via verify_witness, independently
    cert = flip["certificate"]
    assert cert, "flip carries no certificate"
    for entry in cert:
        keep = [i for i in range(len(repro)) if i != entry["drop"]]
        neighbor = repro.subhistory(keep)
        w = [tuple(p) for p in entry["witness"]]
        assert verify_witness(spec, neighbor, w)
    # terminal: a later append answers flipped, no second payload
    out2 = handle.append([{"type": "invoke", "pid": 0, "cmd": 1,
                           "arg": 1},
                          {"type": "respond", "pid": 0, "resp": 0}])
    assert out2["verdict"] == "VIOLATION"
    assert "flip" not in out2 and out2.get("flipped")
    fin = handle.close()
    assert fin["verdict"] == "VIOLATION" and fin["flipped"]
    assert len(handle.flips) == 1
    # the session block counted the push
    st = client.stats()["stats"]["session"]
    assert st["flips_pushed"] == 1


def test_flip_dump_fires_on_session_flip(tmp_path):
    srv = CheckServer(flush_s=0.005,
                      trace_log=str(tmp_path / "trace.jsonl"),
                      flight_dir=str(tmp_path / "flight")).start()
    try:
        client = CheckClient(srv.address)
        handle = SessionHandle(client, "register")
        handle.append([{"type": "invoke", "pid": 0, "cmd": 0, "arg": 0},
                       {"type": "respond", "pid": 0, "resp": 2}])
        assert handle.flips
        dumps = [f for f in os.listdir(tmp_path / "flight")
                 if "session_flip" in f]
        assert dumps, "no session_flip flight dump"
        doc = json.loads((tmp_path / "flight" / dumps[0]).read_text())
        assert handle.trace in json.dumps(doc)
        client.close()
    finally:
        srv.stop()


# --- bounds / refusals -----------------------------------------------------

def test_event_cap_sheds_and_session_cap_sheds():
    mgr = SessionManager(max_sessions=1, max_events=4)
    spec = MODELS["register"].make_spec()
    s, resumed = mgr.open(None, spec, None)
    assert not resumed
    with pytest.raises(SessionLimit):
        mgr.open(None, spec, None)
    s.append([[0, 1, 1, 0, 2 * i, 2 * i + 1] for i in range(4)])
    with pytest.raises(SessionLimit):
        s.append([[0, 1, 1, 0, 10, 11]])
    # served: the cap answers SHED, never an error or a wrong verdict
    srv = CheckServer(flush_s=0.005, max_sessions=1).start()
    try:
        client = CheckClient(srv.address)
        a = client.session_open("register")
        assert a["ok"]
        b = client.session_open("register")
        assert b.get("shed") and "session cap" in b["reason"]
        client.close()
    finally:
        srv.stop()


def test_seq_replay_is_idempotent_and_gaps_refuse():
    spec = MODELS["register"].make_spec()
    s = MonitorSession("r", spec)
    rows = [[0, 1, 1, 0, 0, 1], [0, 0, 0, 1, 2, 3]]
    assert s.append(rows, seq=0) == 2
    assert s.append(rows, seq=0) == 0          # full replay: no-op
    assert s.append([rows[1], [1, 1, 2, 0, 4, 5]], seq=1) == 1
    with pytest.raises(SessionError, match="gap"):
        s.append([[1, 0, 0, 2, 6, 7]], seq=99)


def test_backwards_time_and_mispaired_events_refuse():
    spec = MODELS["register"].make_spec()
    s = MonitorSession("t", spec)
    s.append([{"type": "invoke", "pid": 0, "cmd": 1, "arg": 1,
               "t": 10}])
    with pytest.raises(SessionError, match="runs backwards"):
        s.append([{"type": "respond", "pid": 0, "resp": 0, "t": 5}])
    s2 = MonitorSession("t2", spec)
    with pytest.raises(SessionError, match="no outstanding"):
        s2.append([{"type": "respond", "pid": 3, "resp": 0}])
    s3 = MonitorSession("t3", spec)
    s3.append([[0, 1, 1, 0, 5, 9]])
    with pytest.raises(SessionError, match="behind the stream"):
        s3.append([[1, 1, 1, 0, 2, 3]])


def test_row_responses_wait_for_the_invoke_horizon():
    """A recorded row's response is not final until no future op can
    invoke before it: the overlap case that would otherwise flip
    prematurely (w(1) invoked inside the read's span fixes it)."""
    spec = MODELS["register"].make_spec()
    s = MonitorSession("h", spec)
    s.append([[0, 0, 0, 1, 0, 10]])      # read->1 spanning [0,10]
    assert s.decide() != int(Verdict.VIOLATION)  # a write may still come
    s.append([[1, 1, 1, 0, 2, 3]])       # ...and it does
    s.decide()
    assert s.close() == int(Verdict.LINEARIZABLE)


# --- the fleet resume acceptance (subprocess node, real SIGKILL) -----------

def _spawn_node(nid: str, tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("QSM_TPU_FAULTS", None)
    unix = str(tmp_path / f"{nid}.sock")
    proc = subprocess.Popen(
        [sys.executable, "-m", "qsm_tpu", "serve", "--unix", unix,
         "--node-id", nid,
         "--replog-dir", str(tmp_path / f"replog_{nid}")],
        stdout=subprocess.PIPE, text=True, env=env)
    banner = json.loads(proc.stdout.readline())
    assert banner["serving"] == unix
    return proc, unix


def test_sigkill_node_mid_session_resumes_from_banked_prefix(tmp_path):
    """THE fleet acceptance pin: the owning node is SIGKILLed
    mid-session and respawned on the same replog; the router replays
    the journal, the respawned node resumes every previously-committed
    cut from the BANK (prefix_hits > 0, pinned from the close
    response), the stream finishes with the exact verdict, and the
    router's flight dump names the session's trace id."""
    from qsm_tpu.core.history import sequential_history
    from qsm_tpu.fleet.router import FleetRouter
    from qsm_tpu.resilience.policy import preset

    proc, unix = _spawn_node("n0", tmp_path)
    flight_dir = str(tmp_path / "flight")
    router = FleetRouter(
        [("n0", unix)],
        policy=preset("fleet-route").with_(timeout_s=2.0),
        probe_policy=preset("fleet-probe").with_(timeout_s=1.0),
        heartbeat_s=0.5, anti_entropy_s=0.0,
        trace_log=str(tmp_path / "rt.jsonl"),
        flight_dir=flight_dir).start()
    client = None
    try:
        client = CheckClient(router.address, timeout_s=15.0)
        h = sequential_history([(0, 1, 1, 0), (0, 0, 0, 1),
                                (1, 1, 2, 0), (1, 0, 0, 2)] * 8)
        rows = history_to_rows(h)
        handle = SessionHandle(client, "register")
        half = len(rows) // 2
        for r in rows[:half]:
            assert handle.append([r])["ok"]
        banked = handle.last["decided_prefix"]
        assert banked > 4  # cuts committed (and banked) pre-kill
        # SIGKILL the owning node MID-SESSION; the verb is observed
        # failing on the node (node fault -> flight dump naming the
        # session's trace) but the stream ADVANCES anyway — the
        # router's own SessionManager is the session verbs' last rung
        # (ISSUE 18), never a SHED and never a wrong answer
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        dead = handle.append([rows[half]])
        assert dead.get("ok") and dead.get("ladder"), dead
        # respawn the node on the SAME unix socket + replog dir
        proc, unix2 = _spawn_node("n0", tmp_path)
        assert unix2 == unix
        # continue the stream; every append answers (the ladder covers
        # the readmission window), and once membership readmits the
        # node the router replays the journal onto it — wait for a
        # node-answered append so the close lands on the respawned
        # node's banked prefixes, not the ladder
        for r in rows[half + 1:-1]:
            out = handle.append([r])
            assert out.get("ok"), out
        for _ in range(60):
            out = handle.append([rows[-1]])
            assert out.get("ok"), out
            if not out.get("ladder"):
                break
            time.sleep(0.25)
        assert not out.get("ladder"), "node never readmitted"
        fin = handle.close()
        assert fin["ok"] and fin["verdict"] == "LINEARIZABLE"
        # the respawned node resumed the replayed prefix from its bank
        assert fin["prefix_hits"] > 0
        assert router.session_replays >= 1
        # the flight dump (node death trigger) names the session trace
        dumps = sorted(os.listdir(flight_dir))
        assert dumps, "no flight dump after the node SIGKILL"
        named = any(handle.trace in (tmp_path / "flight" / d).read_text()
                    for d in dumps)
        assert named, f"session trace {handle.trace} not in {dumps}"
    finally:
        if client is not None:
            client.close()
        router.stop()
        try:
            proc.kill()
            proc.wait(timeout=5)
        except Exception:
            pass


def test_idle_sessions_are_evicted_at_the_cap():
    """An abandoned session (crashed client) must not pin its slot
    forever: at the cap, idle sessions reclaim LRU-first and the next
    open succeeds; counters fold into the running totals."""
    mgr = SessionManager(max_sessions=1, idle_s=0.0)
    spec = MODELS["register"].make_spec()
    s, _ = mgr.open("dead", spec, None)
    s.append([[0, 1, 1, 0, 0, 1]])
    s2, resumed = mgr.open("fresh", spec, None)   # evicts "dead"
    assert not resumed and s2.sid == "fresh"
    assert mgr.get("dead") is None
    t = mgr.totals()
    assert t["evicted"] == 1 and t["session_events"] == 1


def test_router_seqless_append_applies_events_exactly_once():
    """A seq-less client append through the router must not
    double-apply: the router journals it, may replay the journal onto
    the node, and forwards the append seq-stamped with its journal
    position — the node applies each event exactly once."""
    from qsm_tpu.core.history import sequential_history
    from qsm_tpu.fleet.router import FleetRouter

    srv = CheckServer(flush_s=0.005).start()
    router = FleetRouter([("n0", srv.address)],
                         heartbeat_s=5.0, anti_entropy_s=0.0).start()
    client = None
    try:
        client = CheckClient(router.address, timeout_s=10.0)
        h = sequential_history([(0, 1, 1, 0), (0, 0, 0, 1)] * 4)
        rows = history_to_rows(h)
        opened = client.session_open("register")
        sid = opened["session"]
        total = 0
        for r in rows:   # NO seq on any append
            out = client.session_append(sid, [r])
            assert out["ok"], out
            total += out["applied"]
            assert out["seq"] == total  # node counter stays in sync
        fin = client.session_close(sid)
        assert fin["ok"] and fin["verdict"] == "LINEARIZABLE"
        assert fin["ops"] == len(rows)
    finally:
        if client is not None:
            client.close()
        router.stop()
        srv.stop()


# --- manager accounting ----------------------------------------------------

def test_manager_totals_and_search_stats_agree():
    mgr = SessionManager()
    spec = MODELS["register"].make_spec()
    s, _ = mgr.open("x", spec, None)
    s.append([[0, 1, 1, 0, 0, 1], [0, 0, 0, 1, 2, 3]])
    s.decide()
    t = mgr.totals()
    st = mgr.search_stats()
    assert st.session_events == t["session_events"] == 2
    assert st.frontier_advances == t["frontier_advances"]
    assert st.prefix_hits == t["prefix_hits"]
    assert st.flips_pushed == t["flips_pushed"] == 0
    mgr.close("x")
    assert mgr.totals()["session_events"] == 2  # folded at close
    c = st.to_compact()
    assert c["sev"] == 2 and "fad" in c and "pfh" in c and "flp" in c


# --- durable sessions (ISSUE 18) -------------------------------------------

def test_session_doc_round_trip_resumes_identically():
    """to_doc/from_doc is a faithful O(doc) codec: a session cut over
    at an arbitrary mid-stream point (per-key composition, pending
    ops, reorder buffer in play) and rebuilt from its JSON doc decides
    the remainder identically to the uninterrupted session."""
    spec, hists = _corpus("kv", n=4, pids=4, ops=14, prefix="dur")
    proj = _proj_for(spec)
    assert proj is not None
    for k, h in enumerate(hists):
        rows = history_to_rows(h)
        half = max(1, len(rows) // 2)
        live = MonitorSession(f"l{k}", spec, proj_spec=proj)
        cutover = MonitorSession(f"l{k}", spec, proj_spec=proj)
        for r in rows[:half]:
            live.append([r])
            live.decide()
            cutover.append([r])
            cutover.decide()
        doc = json.loads(json.dumps(cutover.to_doc()))
        rebuilt = MonitorSession.from_doc(doc, spec, proj_spec=proj)
        assert rebuilt.seq == cutover.seq
        assert rebuilt.rows == cutover.rows
        for r in rows[half:]:
            live.append([r])
            rebuilt.append([r])
        assert rebuilt.close() == live.close()
        assert rebuilt.counters()["ops"] == live.counters()["ops"]


def test_evicted_session_resumes_durably_zero_folds(tmp_path, monkeypatch):
    """THE durable-resume pin (ISSUE 18 satellite): a session evicted
    at the cap comes back from the snapshot+journal substrate — the
    re-open restores in O(doc), a re-append of an old seq is an
    idempotent no-op, and every cut the restored session commits is a
    BANK hit (the engine fold is made unreachable, so one miss
    fails)."""
    from qsm_tpu.core.history import sequential_history
    from qsm_tpu.monitor import SessionStore

    spec = MODELS["register"].make_spec()
    h = sequential_history([(0, 1, 1, 0), (0, 0, 0, 1),
                            (1, 1, 2, 0), (1, 0, 0, 2)] * 10)
    rows = history_to_rows(h)
    bank = VerdictCache(max_entries=4096)
    store = SessionStore(str(tmp_path / "sessions"))
    mgr = SessionManager(bank=bank, max_sessions=1, idle_s=0.0,
                         store=store)
    s, resumed = mgr.open("dur", spec, None)
    assert not resumed
    for r in rows:
        s.append([r])
        s.decide()                       # banks every committed cut
    folds_banked = s.counters()["advances"]
    assert folds_banked > 10 and s.counters()["prefix_hits"] == 0
    # cap-evict "dur" (idle_s=0.0: everything is reclaimable)
    mgr.open("other", spec, None)
    assert mgr.get("dur") is None
    assert mgr.totals()["evicted"] == 1
    # the engine fold becomes unreachable: the restore must cost
    # deserialization + journal replay + bank hits, NEVER a fold
    import qsm_tpu.monitor.frontier as frontier_mod

    def _boom(*_a, **_k):
        raise AssertionError("engine fold reached on a durable resume")

    monkeypatch.setattr(frontier_mod, "_end_states", _boom)
    s2, resumed = mgr.open("dur", spec, None)
    assert resumed and s2 is not s
    assert mgr.totals()["restored"] == 1
    assert s2.seq == len(rows)           # journal tail fully replayed
    # a failover-style re-append of the WHOLE stream at seq 0 is an
    # idempotent no-op — O(1) skip, no re-application
    assert s2.append([list(r) for r in rows], seq=0) == 0
    v = s2.close()
    assert v == int(Verdict.LINEARIZABLE)
    c = s2.counters()
    assert c["prefix_hits"] > 0          # resumed cuts came from the bank
    assert c["advances"] == c["prefix_hits"] == folds_banked


def test_server_restart_resumes_durable_sessions(tmp_path):
    """Cross-layer smoke: a CheckServer started with ``session_dir``
    journals sessions durably — a NEW server process-equivalent on the
    same directory resumes the sid mid-stream (seq intact) and closes
    with the exact verdict."""
    from qsm_tpu.core.history import sequential_history

    sdir = str(tmp_path / "sessions")
    h = sequential_history([(0, 1, 1, 0), (0, 0, 0, 1),
                            (1, 1, 2, 0), (1, 0, 0, 2)] * 6)
    rows = history_to_rows(h)
    half = len(rows) // 2
    srv = CheckServer(flush_s=0.005, session_dir=sdir).start()
    client = CheckClient(srv.address)
    try:
        opened = client.session_open("register", session="boot")
        assert opened["ok"] and not opened["resumed"]
        for r in rows[:half]:
            assert client.session_append("boot", [r])["ok"]
    finally:
        client.close()
        srv.stop()                       # takes the sessions down with it
    srv2 = CheckServer(flush_s=0.005, session_dir=sdir).start()
    client2 = CheckClient(srv2.address)
    try:
        opened = client2.session_open("register", session="boot")
        assert opened["ok"] and opened["resumed"], opened
        assert opened["seq"] == half     # the durable seq survived
        for i, r in enumerate(rows[half:]):
            out = client2.session_append("boot", [r], seq=half + i)
            assert out["ok"], out
        fin = client2.session_close("boot")
        assert fin["ok"] and fin["verdict"] == "LINEARIZABLE"
        assert fin["ops"] == len(rows)
    finally:
        client2.close()
        srv2.stop()
