"""Golden-history unit tests for the Wing–Gong CPU oracle.

Mirrors the reference family's lineariser unit tests on small hand-written
histories with known verdicts (SURVEY.md §4).
"""

import numpy as np
import pytest

from qsm_tpu import (History, Op, Verdict, WingGongCPU, check_one,
                     overlapping_history, sequential_history)
from qsm_tpu.models.register import READ, WRITE, RegisterSpec

SPEC = RegisterSpec(n_values=5)
ORACLE = WingGongCPU()


def verdict(h):
    return check_one(ORACLE, SPEC, h)


def test_empty_history_linearizable():
    assert verdict(History([])) == Verdict.LINEARIZABLE


def test_sequential_valid():
    h = sequential_history([
        (0, WRITE, 3, 0),
        (0, READ, 0, 3),
        (1, WRITE, 1, 0),
        (1, READ, 0, 1),
    ])
    assert verdict(h) == Verdict.LINEARIZABLE


def test_sequential_stale_read_violates():
    h = sequential_history([
        (0, WRITE, 3, 0),
        (1, READ, 0, 0),  # returns initial value after write completed
    ])
    assert verdict(h) == Verdict.VIOLATION


def test_concurrent_read_during_write_either_value_ok():
    # write(3) on pid0 spans [0, 5]; read on pid1 spans [1, 2].
    # The read overlaps the write, so 0 (old) and 3 (new) are both fine.
    for seen in (0, 3):
        h = overlapping_history([
            (0, WRITE, 3, 0, 0, 5),
            (1, READ, 0, seen, 1, 2),
        ])
        assert verdict(h) == Verdict.LINEARIZABLE, seen
    h = overlapping_history([
        (0, WRITE, 3, 0, 0, 5),
        (1, READ, 0, 2, 1, 2),  # value never written
    ])
    assert verdict(h) == Verdict.VIOLATION


def test_new_old_inversion_violates():
    # Two sequential reads after an overlapping write: first sees new value,
    # second sees old value again -> not linearizable.
    h = overlapping_history([
        (0, WRITE, 3, 0, 0, 7),
        (1, READ, 0, 3, 1, 2),
        (1, READ, 0, 0, 3, 4),
    ])
    assert verdict(h) == Verdict.VIOLATION
    # In the other order (old then new) it is fine.
    h2 = overlapping_history([
        (0, WRITE, 3, 0, 0, 7),
        (1, READ, 0, 0, 1, 2),
        (1, READ, 0, 3, 3, 4),
    ])
    assert verdict(h2) == Verdict.LINEARIZABLE


def test_real_time_order_respected():
    # pid1's read completes strictly before pid0's write begins; it must not
    # see the written value.
    h = overlapping_history([
        (1, READ, 0, 3, 0, 1),
        (0, WRITE, 3, 0, 2, 3),
    ])
    assert verdict(h) == Verdict.VIOLATION


def test_pending_write_may_have_taken_effect():
    # write(1) invoked, never responded (crash). A later read may see 1
    # (completed) or 0 (pruned) — both linearizable.
    for seen in (0, 1):
        h = History([
            Op(pid=0, cmd=WRITE, arg=1, resp=-1, invoke_time=0,
               response_time=10**9),
            Op(pid=1, cmd=READ, arg=0, resp=seen, invoke_time=2,
               response_time=3),
        ])
        assert verdict(h) == Verdict.LINEARIZABLE, seen
    h = History([
        Op(pid=0, cmd=WRITE, arg=1, resp=-1, invoke_time=0,
           response_time=10**9),
        Op(pid=1, cmd=READ, arg=0, resp=4, invoke_time=2, response_time=3),
    ])
    assert verdict(h) == Verdict.VIOLATION


def test_budget_exceeded_reported():
    tiny = WingGongCPU(node_budget=3)
    # A history needing more than 3 nodes.
    h = sequential_history([(0, WRITE, i % 5, 0) for i in range(10)])
    assert check_one(tiny, SPEC, h) == Verdict.BUDGET_EXCEEDED


def test_batch_api_shapes():
    hs = [sequential_history([(0, WRITE, 1, 0)]),
          sequential_history([(0, READ, 0, 4)])]
    out = ORACLE.check_histories(SPEC, hs)
    assert out.dtype == np.int8
    assert list(out) == [Verdict.LINEARIZABLE, Verdict.VIOLATION]
