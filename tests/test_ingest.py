"""Foreign-trace ingest (qsm_tpu/ingest) — the ISSUE 14 satellite gates.

What is pinned, in order of importance:

* the golden Jepsen and porcupine logs round-trip BYTE-STABLY
  (parse → History → re-emit → identical bytes) and check end-to-end
  with pinned CLI exit codes — ingested traces are ordinary corpora;
* ``utils/report.py history_from_rows`` is deterministic under row
  permutation (the satellite fix: canonical total order, no
  insertion-order luck) and refuses response-before-invocation rows
  loudly;
* ingested traces are accepted by ``submit`` and ``shrink`` against a
  running server exactly like native corpora;
* adapter errors (unknown ops, out-of-domain values, mis-paired
  events) are refused with line context, never guessed.
"""

from __future__ import annotations

import json
import os

import pytest

from qsm_tpu.ingest import (EdnError, IngestError, emit_trace,
                            parse_trace)
from qsm_tpu.models.registry import MODELS
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.utils.report import history_from_rows

DATA = os.path.join(os.path.dirname(__file__), "data")
GOLDEN_JEPSEN = os.path.join(DATA, "golden_jepsen_register.edn")
GOLDEN_PORCUPINE = os.path.join(DATA, "golden_porcupine_kv.edn")
GOLDEN_RANGESET = os.path.join(DATA, "golden_jepsen_rangeset.edn")
GOLDEN_SEMAPHORE = os.path.join(DATA, "golden_jepsen_semaphore.edn")
GOLDEN_TXN = os.path.join(DATA, "golden_porcupine_txn.edn")


def _golden(path):
    with open(path) as f:
        return f.read()


# --- golden round trips ----------------------------------------------------

def test_golden_jepsen_round_trip_byte_stable():
    text = _golden(GOLDEN_JEPSEN)
    spec = MODELS["register"].make_spec()
    rows = parse_trace("jepsen", text, "register", spec)
    h = history_from_rows(rows)
    assert emit_trace("jepsen", h, "register", spec) == text
    # the trailing :invoke with no completion decodes as a pending op
    assert h.n_pending == 1
    v = int(WingGongCPU(memo=True).check_histories(spec, [h])[0])
    assert v == 1  # LINEARIZABLE


def test_golden_porcupine_round_trip_byte_stable():
    text = _golden(GOLDEN_PORCUPINE)
    spec = MODELS["kv"].make_spec()
    rows = parse_trace("porcupine", text, "kv", spec)
    h = history_from_rows(rows)
    assert emit_trace("porcupine", h, "kv", spec) == text
    v = int(WingGongCPU(memo=True).check_histories(spec, [h])[0])
    assert v == 0  # the seeded stale read on key 1: VIOLATION


def test_golden_rangeset_round_trip_byte_stable():
    """ISSUE 17 family: the torn count-below scan (a count no single
    linearization point produces) plus a ``:fail`` duplicate add and a
    pending count — the full vocabulary round-trips byte-stably."""
    text = _golden(GOLDEN_RANGESET)
    spec = MODELS["rangeset"].make_spec()
    rows = parse_trace("jepsen", text, "rangeset", spec)
    h = history_from_rows(rows)
    assert emit_trace("jepsen", h, "rangeset", spec) == text
    assert h.n_pending == 1
    v = int(WingGongCPU(memo=True).check_histories(spec, [h])[0])
    assert v == 0  # the torn count: VIOLATION


def test_golden_semaphore_round_trip_byte_stable():
    text = _golden(GOLDEN_SEMAPHORE)
    spec = MODELS["semaphore"].make_spec()
    rows = parse_trace("jepsen", text, "semaphore", spec)
    h = history_from_rows(rows)
    assert emit_trace("jepsen", h, "semaphore", spec) == text
    v = int(WingGongCPU(memo=True).check_histories(spec, [h])[0])
    assert v == 1  # refused third acquire is legal: LINEARIZABLE


def test_golden_txn_round_trip_byte_stable():
    """The non-decomposable family still ingests and CHECKS like any
    other — refusal costs decomposition, never verdicts.  The golden
    seeds the stale-read torn copy (models/txn.py TornCopyTxnSUT)."""
    text = _golden(GOLDEN_TXN)
    spec = MODELS["txn"].make_spec()
    rows = parse_trace("porcupine", text, "txn", spec)
    h = history_from_rows(rows)
    assert emit_trace("porcupine", h, "txn", spec) == text
    v = int(WingGongCPU(memo=True).check_histories(spec, [h])[0])
    assert v == 0  # copy installed a value no atomic copy observes


def test_txn_map_refuses_diagonal_copy_and_rangeset_domain():
    spec = MODELS["txn"].make_spec()
    with pytest.raises(IngestError, match="must differ"):
        parse_trace("porcupine",
                    "{:process 0, :type :invoke, :f :copy, :key 1, "
                    ":value 1}\n", "txn", spec)
    rs = MODELS["rangeset"].make_spec()
    with pytest.raises(IngestError, match="outside spec domain"):
        parse_trace("jepsen",
                    "{:process 0, :type :invoke, :f :add, "
                    f":value [{rs.n_keys} nil]}}\n", "rangeset", rs)
    # count-below's bound domain is one wider than the key domain
    rows = parse_trace(
        "jepsen", "{:process 0, :type :invoke, :f :count-below, "
        f":value [{rs.n_keys} nil]}}\n", "rangeset", rs)
    assert rows[0][2] == rs.n_keys


def test_jepsen_cas_fail_completes_with_failure_response():
    spec = MODELS["cas"].make_spec()
    text = ("{:process 0, :type :invoke, :f :cas, :value [1 2]}\n"
            "{:process 0, :type :fail, :f :cas, :value [1 2]}\n")
    rows = parse_trace("jepsen", text, "cas", spec)
    assert rows[0][3] == 0  # cas resp 0 = precondition failed
    h = history_from_rows(rows)
    assert emit_trace("jepsen", h, "cas", spec) == text
    assert int(WingGongCPU().check_histories(spec, [h])[0]) == 1


def test_info_leaves_op_pending():
    spec = MODELS["register"].make_spec()
    text = ("{:process 0, :type :invoke, :f :write, :value 1}\n"
            "{:process 0, :type :info, :f :write, :value 1}\n")
    rows = parse_trace("jepsen", text, "register", spec)
    h = history_from_rows(rows)
    assert h.n_pending == 1


# --- refusal paths ---------------------------------------------------------

def test_adapter_refuses_unknown_op_and_out_of_domain():
    spec = MODELS["register"].make_spec()
    with pytest.raises(IngestError, match="unknown op"):
        parse_trace("jepsen",
                    "{:process 0, :type :invoke, :f :append, "
                    ":value 1}\n", "register", spec)
    nv = spec.CMDS[0].n_resps
    with pytest.raises(IngestError, match="outside spec domain"):
        parse_trace("jepsen",
                    "{:process 0, :type :invoke, :f :write, "
                    f":value {nv + 3}}}\n", "register", spec)


def test_nemesis_info_lines_are_skipped_in_both_paths():
    """Real Jepsen logs carry ``:process :nemesis`` lifecycle lines —
    not history operations.  Both the batch adapter and the live
    tailer (the ONE shared decode) skip them; a non-integer process on
    a real op still refuses."""
    from qsm_tpu.ingest import EventTailer

    spec = MODELS["register"].make_spec()
    text = ("{:process :nemesis, :type :info, :f :start, :value nil}\n"
            "{:process 0, :type :invoke, :f :write, :value 1}\n"
            "{:process :nemesis, :type :info, :f :stop, :value nil}\n"
            "{:process 0, :type :ok, :f :write, :value 1}\n")
    rows = parse_trace("jepsen", text, "register", spec)
    assert len(rows) == 1 and rows[0][:4] == [0, 1, 1, 0]
    tailer = EventTailer("jepsen", "register", spec)
    events = []
    for ln in text.splitlines():
        events.extend(tailer.events_for_line(ln))
    assert [e["type"] for e in events] == ["invoke", "respond"]
    with pytest.raises(IngestError, match="must be an integer"):
        parse_trace("jepsen",
                    "{:process :nemesis, :type :invoke, :f :write, "
                    ":value 1}\n", "register", spec)


def test_adapter_refuses_mispaired_events_with_line_context():
    spec = MODELS["register"].make_spec()
    with pytest.raises(IngestError, match="line 1"):
        parse_trace("jepsen",
                    "{:process 0, :type :ok, :f :read, :value 0}\n",
                    "register", spec)
    with pytest.raises(EdnError, match="line 1"):
        parse_trace("jepsen", "{:process oops}\n", "register", spec)


# --- the history_from_rows satellite (deterministic decode) ----------------

def test_history_from_rows_is_permutation_invariant():
    """The ONE decoder's op order is canonical, not insertion luck:
    any permutation of the same rows decodes to the same History —
    same fingerprint, same cache row, same witness indices."""
    rows = [[0, 1, 1, 0, 0, 3],
            [1, 0, 0, 1, 1, 2],    # overlaps the write
            [2, 1, 2, 0, 4, 5],
            [1, 0, 0, 2, 4, 6]]    # equal invoke_time as row 3
    base = history_from_rows(rows).fingerprint()
    import itertools

    for perm in itertools.permutations(rows):
        assert history_from_rows(list(perm)).fingerprint() == base


def test_history_from_rows_refuses_response_before_invocation():
    with pytest.raises(ValueError, match="precedes invoke_time"):
        history_from_rows([[0, 1, 1, 0, 5, 3]])
    # pending rows (sentinel resp) are exempt: they have no response
    h = history_from_rows([[0, 1, 1, -1, 5, 0]])
    assert h.n_pending == 1


# --- CLI exit codes --------------------------------------------------------

def test_cli_ingest_check_exit_codes(capsys):
    from qsm_tpu.utils.cli import main

    rc = main(["ingest", GOLDEN_JEPSEN, "--format", "jepsen",
               "--spec", "register", "--check"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["verdict"] == "LINEARIZABLE"
    rc = main(["ingest", GOLDEN_PORCUPINE, "--format", "porcupine",
               "--spec", "kv", "--check"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and out["verdict"] == "VIOLATION"


def test_cli_ingest_emit_is_byte_stable(capsys):
    from qsm_tpu.utils.cli import main

    rc = main(["ingest", GOLDEN_PORCUPINE, "--format", "porcupine",
               "--spec", "kv", "--emit"])
    assert rc == 0
    assert capsys.readouterr().out == _golden(GOLDEN_PORCUPINE)


def test_cli_ingest_parse_error_exits_2(tmp_path, capsys):
    from qsm_tpu.utils.cli import main

    bad = tmp_path / "bad.edn"
    bad.write_text("{:process 0, :type :invoke, :f :append, "
                   ":value 1}\n")
    rc = main(["ingest", str(bad), "--format", "jepsen",
               "--spec", "register", "--check"])
    assert rc == 2
    capsys.readouterr()


def test_cli_ingest_out_feeds_check_and_shrink(tmp_path, capsys):
    """An ingested trace document is an ordinary corpus: the `check`
    CLI decides it and the in-process `shrink` CLI minimizes it."""
    from qsm_tpu.utils.cli import main

    out_path = tmp_path / "trace.json"
    rc = main(["ingest", GOLDEN_PORCUPINE, "--format", "porcupine",
               "--spec", "kv", "--out", str(out_path)])
    assert rc == 0
    capsys.readouterr()
    rc = main(["check", "--trace", str(out_path)])
    assert rc == 1  # the golden's seeded violation
    capsys.readouterr()
    rc = main(["shrink", "--trace", str(out_path)])
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and doc["verdict"] == "VIOLATION"
    assert doc["final_ops"] <= doc["initial_ops"]


def test_ingested_trace_accepted_by_submit_and_serve_shrink(tmp_path):
    """The serve tier takes ingested corpora unchanged: `submit` banks
    the verdict, the `shrink` verb minimizes the same rows."""
    from qsm_tpu.serve import CheckClient, CheckServer

    spec = MODELS["kv"].make_spec()
    rows = parse_trace("porcupine", _golden(GOLDEN_PORCUPINE), "kv",
                       spec)
    srv = CheckServer(flush_s=0.005, max_lanes=16).start()
    try:
        c = CheckClient(srv.address)
        res = c.check("kv", [rows])
        assert res["ok"] and res["verdicts"] == ["VIOLATION"]
        sh = c.shrink("kv", rows)
        assert sh["ok"] and sh["verdict"] == "VIOLATION"
        assert sh["final_ops"] <= sh["initial_ops"]
        c.close()
    finally:
        srv.stop()
