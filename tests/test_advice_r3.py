"""Regression tests for the round-3 advisor findings (ADVICE.md).

One test per finding:

* ``history_from_rows`` — a row with a non-negative resp but a null
  response_time (plausible crashed-op dump) is a *pending* op, not a
  TypeError surfacing as a raw traceback from the check CLI;
* ``_blockers2`` — the 2-word mask builder fails loudly past the native
  128-op cap instead of silently dropping precedence bits;
* scripted-choice clamping — replaying a stale exploration script whose
  choices exceed the live branching factor flags the drift instead of
  silently running a different schedule.
"""

import numpy as np
import pytest

from qsm_tpu import Program, run_concurrent
from qsm_tpu.core.generator import ProgOp
from qsm_tpu.models.register import WRITE, AtomicRegisterSUT
from qsm_tpu.sched.runner import PENDING_T
from qsm_tpu.utils.report import history_from_rows


def test_history_from_rows_null_response_time_is_pending():
    # resp recorded (>=0) but response_time null: crashed mid-response.
    h = history_from_rows([
        [0, 0, 0, 2, 0, 3],
        [1, 1, 4, 3, 1, None],
    ])
    assert h.ops[0].resp == 2 and h.ops[0].response_time == 3
    assert h.ops[1].resp == -1
    assert h.ops[1].response_time == PENDING_T


def test_blockers2_rejects_over_cap():
    from qsm_tpu.native.oracle import NATIVE_MAX_OPS, _blockers2

    ok = np.zeros((NATIVE_MAX_OPS, NATIVE_MAX_OPS), bool)
    _blockers2(ok)  # at the cap: fine
    too_big = np.zeros((NATIVE_MAX_OPS + 1, NATIVE_MAX_OPS + 1), bool)
    with pytest.raises(AssertionError, match="exceeds"):
        _blockers2(too_big)


def test_stale_schedule_script_reports_clamp():
    prog = Program((ProgOp(0, WRITE, 1), ProgOp(1, WRITE, 2)), n_pids=2)
    # A branching factor this small never reaches 99: every scripted
    # choice is clamped — exactly what a drifted regression script does.
    info: dict = {}
    run_concurrent(AtomicRegisterSUT(), prog, seed="s",
                   choices=[99, 99, 99], sched_info=info)
    assert info["choice_clamped"] is True


def test_in_range_schedule_script_not_flagged():
    prog = Program((ProgOp(0, WRITE, 1), ProgOp(1, WRITE, 2)), n_pids=2)
    info: dict = {}
    run_concurrent(AtomicRegisterSUT(), prog, seed="s",
                   choices=[0] * 64, sched_info=info)
    assert info["choice_clamped"] is False
