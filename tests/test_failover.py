"""Monitors/links (SURVEY.md §5 failure detection) and the failover
model family: DOWN notifications are delivered deterministically and
fault-exempt; synchronous replication survives every crash schedule,
asynchronous replication loses acknowledged writes and the checker
catches it."""

from qsm_tpu import (FaultPlan, Monitor, PropertyConfig, Recv, Scheduler,
                     Send, Verdict, WingGongCPU, check_one, prop_concurrent)
from qsm_tpu.models.failover import (AsyncReplFailoverSUT,
                                     SyncReplFailoverSUT)
from qsm_tpu.models.register import RegisterSpec

SPEC = RegisterSpec()
CRASH = FaultPlan(crash_at={"primary": 4})
CFG = PropertyConfig(n_trials=120, n_pids=3, max_ops=10, seed=3,
                     faults=CRASH)


# ---------------------------------------------------------------------------
# Monitor primitive (scheduler level)
# ---------------------------------------------------------------------------

def _watcher(log):
    yield Monitor("worker")
    msg = yield Recv()
    log.append(msg.payload)


def _idle_worker():
    yield Recv()  # blocks forever (until crashed)


def test_monitor_fires_on_crash():
    sched = Scheduler(seed=1, faults=FaultPlan(crash_at={"worker": 0}))
    log = []
    sched.spawn("worker", _idle_worker(), daemon=True)
    sched.spawn("watcher", _watcher(log))
    sched.run()
    assert log == [("DOWN", "worker", "crashed")]


def test_monitor_fires_on_normal_completion():
    def quick_worker():
        return
        yield  # pragma: no cover — makes this a generator

    sched = Scheduler(seed=1)
    log = []
    sched.spawn("worker", quick_worker())
    sched.spawn("watcher", _watcher(log))
    sched.run()
    assert log == [("DOWN", "worker", "done")]


def test_monitor_on_dead_or_unknown_target_fires_immediately():
    sched = Scheduler(seed=1)
    log = []

    def watch_ghost(log):
        yield Monitor("ghost")
        msg = yield Recv()
        log.append(msg.payload)

    sched.spawn("watcher", watch_ghost(log))
    sched.run()
    assert log == [("DOWN", "ghost", "noproc")]


def test_down_notification_is_fault_exempt():
    """Heavy drop faults must never eat a DOWN notification."""
    sched = Scheduler(seed=7, faults=FaultPlan(
        p_drop=1.0, crash_at={"worker": 0},
        protected={"nobody"}))  # protect nothing relevant: drop ALL sends
    log = []
    sched.spawn("worker", _idle_worker(), daemon=True)
    sched.spawn("watcher", _watcher(log))
    sched.run()
    assert log == [("DOWN", "worker", "crashed")]


def test_monitor_determinism():
    def run_once():
        sched = Scheduler(seed=5, faults=FaultPlan(crash_at={"worker": 2}))
        log = []
        sched.spawn("worker", _idle_worker(), daemon=True)

        def chatty(n):
            for i in range(n):
                yield Send("worker", i)

        sched.spawn("noise", chatty(4))
        sched.spawn("watcher", _watcher(log))
        sched.run()
        return tuple(log), tuple(sched.trace)

    assert run_once() == run_once()


# ---------------------------------------------------------------------------
# The failover family (property level)
# ---------------------------------------------------------------------------

def test_sync_failover_survives_crash_schedules():
    for k in (2, 4, 8):
        faults = FaultPlan(crash_at={"primary": k})
        cfg = PropertyConfig(n_trials=120, n_pids=3, max_ops=10, seed=3,
                             faults=faults)
        res = prop_concurrent(SPEC, SyncReplFailoverSUT(), cfg)
        assert res.ok, (k, res.counterexample)


def test_sync_failover_reads_do_not_go_back_in_time():
    """Regression (caught by a 400-trial burn-in of the FIRST sync
    design): a primary serving reads from unreplicated state lets a read
    observe a value that failover rolls back — read(1) ... read(0).
    The committed-reads design must survive the exact trial sequence
    that exposed it (seed 9, crash at 4, trial 175)."""
    cfg = PropertyConfig(n_trials=400, n_pids=3, max_ops=10, seed=9,
                         faults=FaultPlan(crash_at={"primary": 4}))
    res = prop_concurrent(SPEC, SyncReplFailoverSUT(), cfg)
    assert res.ok, res.counterexample


def test_async_failover_loses_acked_writes():
    res = prop_concurrent(SPEC, AsyncReplFailoverSUT(), CFG)
    assert not res.ok, "the lost acked write was never caught"
    cx = res.counterexample
    assert check_one(WingGongCPU(), SPEC, cx.history) == Verdict.VIOLATION


def test_failover_without_crash_behaves_like_plain_register():
    cfg = PropertyConfig(n_trials=60, n_pids=3, max_ops=10, seed=1)
    assert prop_concurrent(SPEC, SyncReplFailoverSUT(), cfg).ok
    assert prop_concurrent(SPEC, AsyncReplFailoverSUT(), cfg).ok


def test_failover_over_tcp_bit_identical():
    """Monitors + crash schedules + the loopback-TCP transport: DOWN
    notifications ride the pool (never uplinked) yet deliver through the
    transport's downlink — histories must stay bit-identical to the
    in-memory transport."""
    from qsm_tpu import generate_program, run_concurrent

    prog = generate_program(SPEC, seed=5, n_pids=3, max_ops=8)
    for impl in (SyncReplFailoverSUT, AsyncReplFailoverSUT):
        mem = run_concurrent(impl(), prog, seed="t5", faults=CRASH)
        tcp = run_concurrent(impl(), prog, seed="t5", faults=CRASH,
                             transport="tcp")
        assert mem.fingerprint() == tcp.fingerprint(), impl.__name__


def test_failover_cli_crash_at(capsys):
    from qsm_tpu.utils.cli import main

    rc = main(["run", "--model", "failover", "--impl", "racy",
               "--trials", "120", "--seed", "3",
               "--crash-at", "primary:4"])
    assert rc == 1  # violation found
    out = capsys.readouterr().out
    assert "FAIL: failover/racy" in out
