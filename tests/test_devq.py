"""Window-arbitrage plane (qsm_tpu/devq, ISSUE 20) — tier-1 gates.

What is pinned, in order of importance:

* SOUNDNESS: a drained window banks ONLY fresh-host-oracle verdicts,
  bit-identical to the host ladder, under the exact fingerprint the
  originating plane computed at bank time — the device path can make
  the system faster, never wrong (``wrong_verdicts`` stays 0);
* EXACTLY-ONCE: a drain journal replayed with ``--resume`` semantics
  re-dispatches NOTHING a predecessor already proved, even when the
  queue re-delivers every banked item (gossip redelivery is the
  normal case: ``put`` is idempotent by fingerprint);
* FOUR-PLANE BANKING: check/pcomp/shrink/monitor corpora and the
  planner's warmup item land in one queue with per-plane accounting,
  dedupe by fingerprint, absorbing done tombstones, persistence
  across a reload, and cap-bounded lowest-score eviction;
* FLEET CONVERGENCE: node A banks, node B adopts A's devq segments
  through the queue's anti-entropy surface, B drains, A adopts the
  tombstones — A's backlog converges to zero and A's lanes hit the
  drained bank;
* THE SEAMS: a shrink round's BUDGET_EXCEEDED frontier and a monitor
  session's terminal flip each bank their re-check work through the
  process-global queue, and cost nothing when no queue is configured;
* THE WIRE: ``devq.put``/``digests``/``drain_report`` round-trip
  through a live server, and a reported window folds
  ``window_utilization`` into the ``health`` doc as one more SLO
  objective (no windows yet is zero samples, not a breach).
"""

from __future__ import annotations

import numpy as np
import pytest

from qsm_tpu.devq.drain import DrainScheduler
from qsm_tpu.devq.queue import (DeviceWorkQueue, WorkItem,
                                bank_histories, global_devq,
                                note_device_plan, set_global_devq)
from qsm_tpu.models.registry import MODELS, make
from qsm_tpu.ops.backend import Verdict
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.serve.cache import VerdictCache, fingerprint_key
from qsm_tpu.utils.corpus import build_corpus

# small everywhere: the queue moves checking to a window, it does not
# need big corpora to prove that
PLANE_FAMILIES = (("check", "register"), ("pcomp", "kv"),
                  ("shrink", "cas"), ("monitor", "queue"))


def _corpus(family, n=4, prefix="devq"):
    entry = MODELS[family]
    spec = entry.make_spec()
    hists = build_corpus(
        spec, (entry.impls["atomic"], entry.impls["racy"]), n=n,
        n_pids=entry.default_pids, max_ops=entry.default_ops,
        seed_prefix=f"{prefix}_{family}")
    return spec, hists


def _failing_histories(model, n=1, scan=60, prefix="devq_fail"):
    """Seeded VIOLATION histories from the registry's racy impl (the
    tests/test_shrink.py scan idiom)."""
    from qsm_tpu.core.generator import generate_program
    from qsm_tpu.sched.runner import run_concurrent

    entry = MODELS[model]
    spec, _ = make(model, "racy")
    oracle = WingGongCPU(memo=True)
    out = []
    for seed in range(scan):
        if len(out) >= n:
            break
        prog = generate_program(spec, seed=seed,
                                n_pids=entry.default_pids,
                                max_ops=entry.default_ops)
        h = run_concurrent(entry.impls["racy"](spec), prog,
                           seed=f"{prefix}:{model}:{seed}").completed()
        if int(oracle.check_histories(spec, [h])[0]) \
                == int(Verdict.VIOLATION):
            out.append(h)
    assert out, f"no failing {model} history in {scan} seeds"
    return spec, out


@pytest.fixture(autouse=True)
def _no_global_queue():
    # the seams read the process-global hook: never leak one across
    # tests (or into the rest of the suite)
    set_global_devq(None)
    yield
    set_global_devq(None)


# --- queue semantics ------------------------------------------------------

def test_bank_dedupe_tombstone_and_persistence(tmp_path):
    spec, hists = _corpus("register")
    q = DeviceWorkQueue(str(tmp_path / "q"))
    key = bank_histories(spec, hists, plane="check", queue=q)
    assert key is not None and len(q) == 1
    # idempotent: the same corpus banks under the same fingerprint
    assert bank_histories(spec, hists, plane="check", queue=q) == key
    assert len(q) == 1 and q.banked == 1
    item = q.get(key)
    assert item.plane == "check" and item.model == "register"
    assert item.lane_keys == [fingerprint_key(spec, h) for h in hists]
    # done is absorbing: a re-delivered put after the tombstone no-ops
    assert q.mark_done(key)
    assert len(q) == 0
    assert not q.put(item)
    # the replog replays both row shapes into a fresh instance
    q2 = DeviceWorkQueue(str(tmp_path / "q"))
    assert len(q2) == 0 and not q2.put(item)
    assert q2.snapshot()["done"] >= 1


def test_cap_evicts_lowest_score_only_over_cap():
    q = DeviceWorkQueue(cap=2, now=lambda: 1000.0)
    for i, bucket in enumerate((8, 2, 16)):
        q.put(WorkItem(key=f"k{i}", plane="check", model="register",
                       bucket=bucket, enq_ts=1000.0))
    assert len(q) == 2 and q.evicted == 1
    assert q.get("k1") is None          # smallest bucket went first
    assert [it.key for it in q.pending_items()] == ["k2", "k0"]


def test_drain_order_feeds_plane_starvation():
    q = DeviceWorkQueue(now=lambda: 1000.0)
    q.put(WorkItem(key="a", plane="check", model="register",
                   bucket=4, enq_ts=1000.0))
    q.put(WorkItem(key="b", plane="shrink", model="cas",
                   bucket=4, enq_ts=1000.0))
    # equal scores tie-break on key; draining `check` starves it below
    # the untouched shrink plane on the next ranking
    assert q.pending_items()[0].key == "a"
    q.note_drained("check")
    assert q.pending_items()[0].key == "b"


def test_four_planes_and_warmup_bank_into_one_queue():
    q = DeviceWorkQueue()
    for plane, fam in PLANE_FAMILIES:
        spec, hists = _corpus(fam, n=2)
        bank_histories(spec, hists, plane=plane, queue=q)
    from qsm_tpu.search.planner import plan_search, profile_corpus

    spec, hists = _corpus("kv", n=2)
    plan = plan_search(spec, profile_corpus(hists, spec),
                       mesh_devices=4)
    set_global_devq(q)
    try:
        assert note_device_plan(spec, plan) is not None
    finally:
        set_global_devq(None)
    by_plane = q.snapshot()["pending_by_plane"]
    assert by_plane == {"check": 1, "pcomp": 1, "shrink": 1,
                        "monitor": 1, "warmup": 1}


# --- drain soundness ------------------------------------------------------

def test_drain_banks_oracle_verdicts_bit_identical_to_host(tmp_path):
    """The window's one promise: every banked verdict IS the fresh host
    memo oracle's, landed under the originating fingerprint — the
    device path (a real 2-wide mesh here; conftest forces 8 virtual
    devices) never gets the last word."""
    import jax

    q = DeviceWorkQueue()
    corpora = []
    for plane, fam in (("check", "register"), ("shrink", "cas")):
        spec, hists = _corpus(fam)
        bank_histories(spec, hists, plane=plane, queue=q)
        corpora.append((spec, hists))
    cache = VerdictCache(max_entries=256)
    report = DrainScheduler(q, cache=cache,
                            devices=jax.devices()[:2],
                            window_s=600.0, budget=200_000).drain()
    assert report["drained"] == 2 and report["wrong_verdicts"] == 0
    assert report["key_mismatches"] == 0
    assert 0.0 < report["window_utilization"] <= 1.0
    for plane in ("check", "shrink"):
        stats = report["per_plane"][plane]
        assert stats["items"] == 1 and stats["device_items"] == 1
        assert stats["device_vs_host_ratio"] is not None
    undecided = int(Verdict.BUDGET_EXCEEDED)
    for spec, hists in corpora:
        proofs = WingGongCPU(memo=True).check_histories(spec, hists)
        for h, p in zip(hists, proofs):
            if int(p) == undecided:
                continue  # the bank refuses undecided rows by design
            e = cache.get(fingerprint_key(spec, h))
            assert e is not None and int(e.verdict) == int(p)


def test_drain_refuses_banking_under_mismatched_fingerprint():
    """A corrupted/foreign lane key must not poison the bank: the drain
    re-derives each fingerprint and skips rows that disagree."""
    spec, hists = _corpus("register", n=2)
    q = DeviceWorkQueue()
    key = bank_histories(spec, hists, plane="check", queue=q)
    q.get(key).lane_keys[0] = "sha-of-some-other-history"
    cache = VerdictCache(max_entries=64)
    report = DrainScheduler(q, cache=cache, window_s=600.0,
                            device_dispatch=False).drain()
    assert report["key_mismatches"] == 1
    assert report["banked_rows"] == len(hists) - 1
    assert cache.get(fingerprint_key(spec, hists[1])) is not None
    assert cache.get(fingerprint_key(spec, hists[0])) is None


# --- exactly-once resume --------------------------------------------------

def test_window_close_then_resume_redispatches_nothing(tmp_path):
    """A window that closes mid-drain (clock-driven here; the bench
    SIGKILLs for real) leaves a journal; the successor — handed the
    WHOLE backlog again, as gossip redelivery would — folds every
    journaled completion and re-dispatches zero of them."""
    corpora = [_corpus(f, n=2, prefix="devq_kill")
               for f in ("register", "cas", "queue")]

    def fill(q):
        return [bank_histories(spec, hists, plane="check", queue=q)
                for spec, hists in corpora]

    q1 = DeviceWorkQueue()
    keys = fill(q1)
    journal = str(tmp_path / "drain_journal.jsonl")
    # +10s per clock read, 35s window: the first item lands, then the
    # deadline check stops the drain mid-queue
    t = [0.0]

    def clock():
        t[0] += 10.0
        return t[0]

    r1 = DrainScheduler(q1, window_s=35.0, journal_path=journal,
                        window_id="w", device_dispatch=False,
                        now=clock).drain()
    assert r1["deadline_stopped"] and 1 <= r1["drained"] < len(keys)

    q2 = DeviceWorkQueue()   # every item pending again
    fill(q2)
    r2 = DrainScheduler(q2, window_s=600.0, journal_path=journal,
                        window_id="w", resume=True,
                        device_dispatch=False).drain()
    assert sorted(r2["resumed"]) == sorted(r1["dispatched"])
    assert not set(r2["resumed"]) & set(r2["dispatched"])
    assert sorted(r1["dispatched"] + r2["dispatched"]) == sorted(keys)
    assert len(q2) == 0


# --- fleet convergence ----------------------------------------------------

def test_fleet_bank_adopt_drain_converge(tmp_path):
    """A banks → B adopts A's segments (the legs gossip drives) → B
    drains → A adopts the done tombstones: A's backlog converges to
    zero and every lane A banked hits B's bank with the host verdict."""
    spec, hists = _corpus("register")
    qa = DeviceWorkQueue(str(tmp_path / "a"), node_id="A", seal_rows=1)
    bank_histories(spec, hists, plane="check", queue=qa)
    qb = DeviceWorkQueue(str(tmp_path / "b"), node_id="B", seal_rows=1)

    def reconcile(dst, src):
        for name in dst.missing(src.digests()):
            fp, lines = src.read_segment(name)
            dst.adopt(name, fp, lines)

    reconcile(qb, qa)
    assert len(qb) == 1
    bank = VerdictCache(max_entries=64)
    report = DrainScheduler(qb, cache=bank, window_s=600.0,
                            device_dispatch=False).drain()
    assert report["drained"] == 1 and report["wrong_verdicts"] == 0
    reconcile(qa, qb)
    assert len(qa) == 0 and len(qb) == 0
    proofs = WingGongCPU(memo=True).check_histories(spec, hists)
    for h, p in zip(hists, proofs):
        e = bank.get(fingerprint_key(spec, h))
        assert e is not None and int(e.verdict) == int(p)


# --- the plane seams ------------------------------------------------------

def test_shrink_round_banks_undecided_frontier():
    from qsm_tpu.shrink.shrinker import Shrinker

    spec, failing = _failing_histories("register")
    calls = []

    def decide(batch):
        # input decides VIOLATION; every frontier candidate is left
        # undecided — the exact shape a budget-starved device leaves
        calls.append(len(batch))
        if len(calls) == 1:
            return np.array([int(Verdict.VIOLATION)])
        return np.full(len(batch), int(Verdict.BUDGET_EXCEEDED))

    q = DeviceWorkQueue()
    set_global_devq(q)
    res = Shrinker(spec, decide).run(failing[0])
    assert res.ok and res.undecided_neighbors > 0
    snap = q.snapshot()
    assert snap["pending_by_plane"] == {"shrink": 1}
    item = q.pending_items()[0]
    assert item.model == "register" and len(item.lanes) >= 1


def test_shrink_seam_costs_nothing_without_queue():
    from qsm_tpu.shrink.shrinker import Shrinker

    spec, _ = make("register", "racy")
    sh = Shrinker(spec, lambda batch: np.full(
        len(batch), int(Verdict.LINEARIZABLE)))
    assert global_devq() is None
    sh._bank_undecided([])   # the no-queue path is a no-op, not a raise


def test_monitor_flip_banks_whole_stream_recheck():
    from qsm_tpu.monitor import MonitorSession
    from qsm_tpu.serve.protocol import history_to_rows

    spec, flips = _failing_histories("register")
    q = DeviceWorkQueue()
    set_global_devq(q)
    s = MonitorSession("devq-flip", spec)
    for row in history_to_rows(flips[0]):
        s.append([row])
    assert s.close() == int(Verdict.VIOLATION) and s.flipped
    snap = q.snapshot()
    assert snap["pending_by_plane"] == {"monitor": 1}
    item = q.pending_items()[0]
    assert item.lane_keys == [fingerprint_key(spec, s.history())]


# --- the wire ops + the health SLO ----------------------------------------

def test_serve_devq_ops_and_health_utilization_slo(tmp_path):
    from qsm_tpu.serve import CheckClient, CheckServer

    spec, hists = _corpus("register", n=2)
    srv = CheckServer(flush_s=0.005, max_lanes=16,
                      devq_dir=str(tmp_path / "devq")).start()
    try:
        with CheckClient(srv.address) as client:
            # rare windows are the premise: their absence is zero
            # samples, never a breach
            h0 = client.health()
            assert h0["ok"] and h0["status"] == "ok"
            row0 = h0["devq"]["window_utilization"]
            assert row0["samples"] == 0 and row0["status"] == "ok"

            q = DeviceWorkQueue()
            key = bank_histories(spec, hists, plane="check", queue=q)
            ack = client.devq_put([q.get(key).to_doc()])
            assert ack["ok"] and ack["banked"] == 1
            assert client.devq_put([q.get(key).to_doc()])["banked"] == 0
            dig = client.devq_digests()
            assert dig["ok"] and dig["queue"]["pending"] == 1

            proofs = WingGongCPU(memo=True).check_histories(spec, hists)
            rows = [[fingerprint_key(spec, h), int(p), None]
                    for h, p in zip(hists, proofs)]
            rep = client.devq_drain_report(
                report={"window_id": "w1", "drained": 1,
                        "window_utilization": 0.93},
                rows=rows, done=[key])
            assert rep["ok"] and rep["done"] == 1
            assert client.devq_digests()["queue"]["pending"] == 0
            # the drained verdicts now serve as cache hits
            res = client.check("register", hists)
            assert res["ok"] and all(res["cached"])

            h1 = client.health()
            row1 = h1["devq"]["window_utilization"]
            assert row1["samples"] == 1 and row1["status"] == "ok"
            assert row1["value"] == 0.93
            # read-back form: the banked report itself
            back = client.devq_drain_report()
            assert back["report"]["window_id"] == "w1"
    finally:
        srv.stop()
