"""Differential fuzzing over RANDOM specs (utils/fuzz.py): every backend
must agree with the exact Python oracle on histories against arbitrary
seeded transition tables — the property-tested parity suite with the
property ranging over specifications too (SURVEY.md §4)."""

import pytest

import json
import random

from qsm_tpu.core.spec import compile_step_table
from qsm_tpu.utils.fuzz import (RandomTableSpec, fuzz_parity,
                                random_history)


def test_random_spec_is_reproducible_and_table_consistent():
    # every seed must round-trip through spec_kwargs (seed 6 draws per-cmd
    # sizes below the domain bounds — the case a naive kwargs derivation
    # from the OBSERVED maxima gets wrong)
    for seed in range(20):
        a0 = RandomTableSpec(seed=seed)
        b0 = RandomTableSpec(**a0.spec_kwargs())
        assert (a0._trans == b0._trans).all() and (a0._ok == b0._ok).all()
        assert a0.CMDS == b0.CMDS
    a = RandomTableSpec(seed=7)
    # step_py must agree with the compiled domain table (the native
    # backend consumes the table; drift would be a silent parity hole)
    trans, ok = compile_step_table(a, a.n_states)
    for s in range(a.n_states):
        for c, sig in enumerate(a.CMDS):
            for arg in range(sig.n_args):
                for r in range(sig.n_resps):
                    ns, good = a.step_py([s], c, arg, r)
                    assert ns[0] == trans[s, c, arg, r]
                    assert good == ok[s, c, arg, r]


def test_random_history_well_formed():
    spec = RandomTableSpec(seed=3)
    rng = random.Random(99)
    h = random_history(spec, rng, n_pids=4, n_ops=12, p_pending=0.2)
    assert 0 < len(h) <= 12  # fewer when every pid wedged pending
    per_pid_busy = {}
    for o in sorted(h.ops, key=lambda o: o.invoke_time):
        assert o.invoke_time < o.response_time
        assert 0 <= o.cmd < len(spec.CMDS)
        assert 0 <= o.arg < spec.CMDS[o.cmd].n_args
        if not o.is_pending:
            assert 0 <= o.resp < spec.CMDS[o.cmd].n_resps
        # per-pid sequential: next invoke after previous response, except
        # pending ops, which stay outstanding forever
        prev = per_pid_busy.get(o.pid)
        if prev is not None:
            assert not prev.is_pending
            assert o.invoke_time > prev.response_time
        per_pid_busy[o.pid] = o


def test_fuzz_host_backends_wide():
    """Many specs through the host backends (cheap, no device compiles)."""
    rep = fuzz_parity(n_specs=24, hists_per_spec=24, seed=1,
                      backends=("memo", "cpp"))
    assert rep.ok, rep.mismatches[:10]
    assert rep.linearizable > 0 and rep.violations > 0, (
        "fuzz corpus vacuous")


def test_fuzz_device_backend():
    """Fewer specs through the device kernel (per-spec compiles)."""
    rep = fuzz_parity(n_specs=3, hists_per_spec=24, seed=2,
                      backends=("device",))
    assert rep.ok, rep.mismatches[:10]
    assert rep.linearizable > 0 and rep.violations > 0


def test_fuzz_vector_specs_scalarized_device():
    """Small bounds product: the device rides the scalarize shadow; its
    decided verdicts must match the oracle on arbitrary vector specs."""
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.utils.fuzz import RandomVectorSpec

    assert JaxTPU(RandomVectorSpec(1))._shadow is not None  # 64 states
    rep = fuzz_parity(n_specs=2, hists_per_spec=20, seed=3,
                      backends=("memo", "device"),
                      vector_bounds=(4, 4, 4))
    assert rep.ok, rep.mismatches[:10]
    assert rep.linearizable > 0 and rep.violations > 0


def test_fuzz_vector_specs_sweep_path():
    """Bounds product over the cap: no shadow — the vmapped step-sweep
    kernel path is what gets fuzzed."""
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.utils.fuzz import RandomVectorSpec

    bounds = (7, 7, 7, 7, 7, 7, 7)  # 7^7 = 823,543 > MAX_PACKED_STATES
    assert JaxTPU(RandomVectorSpec(1, bounds=bounds))._shadow is None
    rep = fuzz_parity(n_specs=2, hists_per_spec=16, seed=4, n_ops=8,
                      backends=("memo", "device"), vector_bounds=bounds)
    assert rep.ok, rep.mismatches[:10]
    assert rep.linearizable > 0


def test_fuzz_spec_step_jax_safe_across_retraces():
    """Regression: caching jnp tables on the spec leaked a tracer from
    the first chunk compilation into the second (UnexpectedTracerError
    the moment a fuzz batch needed chunk escalation).  Force multiple
    chunk compiles and require clean decided-verdict parity."""
    import random as _random

    import numpy as np

    from qsm_tpu import WingGongCPU
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.utils.fuzz import RandomTableSpec, random_history

    spec = RandomTableSpec(seed=9)
    rng = _random.Random("retrace")
    hists = [random_history(spec, rng, 4, 10) for _ in range(16)]
    b = JaxTPU(spec)
    b.CHUNK_SCHEDULE = (4, 64, 4096)  # guarantee >= 2 chunk compiles
    want = WingGongCPU().check_histories(spec, hists)
    got = b.check_histories(spec, hists)  # crashed before the fix
    decided = got != 2
    np.testing.assert_array_equal(got[decided],
                                  np.asarray(want)[decided])
    assert b.rounds_run >= 2  # the escalation really happened


def test_fuzz_cli(capsys):
    from qsm_tpu.utils.cli import main

    rc = main(["fuzz", "--specs", "4", "--histories", "8",
               "--backends", "memo,cpp"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["ok"] and out["mismatches"] == []


@pytest.mark.slow
def test_fuzz_router_backend():
    """The auto-tpu router as a fuzz target: per-history segdc/plain
    routing (incl. native middle enumeration) must stay oracle-exact on
    random specs no in-tree model resembles."""
    from qsm_tpu.utils.fuzz import fuzz_parity

    rep = fuzz_parity(n_specs=3, hists_per_spec=12, seed=21,
                      backends=("auto",))
    assert rep.mismatches == []
    rep = fuzz_parity(n_specs=2, hists_per_spec=10, seed=22,
                      backends=("segdc",), vector_bounds=(3, 2, 2))
    assert rep.mismatches == []


@pytest.mark.slow
def test_fuzz_hybrid_backend():
    """Device-majority + host-tail as one backend: the fuzz target uses a
    tiny device budget so random specs push real traffic through the tail
    (ops/hybrid.py); every decided verdict must match the exact oracle."""
    rep = fuzz_parity(n_specs=3, hists_per_spec=24, seed=6,
                      backends=("hybrid",))
    assert rep.ok, rep.mismatches[:10]
    assert rep.linearizable > 0 and rep.violations > 0
    # the lane is only non-vacuous if the host tail really decided some
    # histories (same discipline as cpp_native_histories)
    assert rep.hybrid_tail_histories > 0
