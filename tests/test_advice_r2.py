"""Regression tests for the round-2 advisor findings (ADVICE.md).

One test per finding:

* scheduler reuse — ``n_delivered`` is per-run state, not instance-lifetime;
* probe env — ``probe_default_backend`` strips only the host-platform flag
  from ``XLA_FLAGS``, keeping operator chip-tuning flags;
* cpu-pinned refusal — ``_ensure_device_reachable`` refuses device backends
  in a cpu-pinned process instead of silently running the kernel on host;
* broken ``scalar_state_bound`` — an out-of-bound model state degrades the
  lane to BUDGET_EXCEEDED (oracle deferral) instead of a silently wrong
  verdict from a clamped step-table gather.
"""

import pytest

from qsm_tpu import Verdict, WingGongCPU, sequential_history
from qsm_tpu.models.cas import WRITE, CasSpec
from qsm_tpu.ops.jax_kernel import JaxTPU


def test_scheduler_reuse_resets_delivery_clock():
    """A FaultPlan crash_at counts deliveries; a second run() on a reused
    Scheduler must start counting from zero again (ADVICE: stale
    n_delivered made crashes fire immediately on reuse)."""
    from qsm_tpu.sched.scheduler import Recv, Scheduler, Send

    def ping(n):
        for _ in range(n):
            yield Send("echo", "hi")
            yield Recv()

    def echo():
        while True:
            msg = yield Recv()
            yield Send(msg.src, msg.payload)

    sched = Scheduler(seed=1)
    sched.spawn("client", ping(3))
    sched.spawn("echo", echo(), daemon=True)
    sched.run()
    first = sched.n_delivered
    assert first > 0
    # reuse the SAME scheduler instance for a fresh pair of processes
    sched.procs.clear()
    sched.spawn("client", ping(3))
    sched.spawn("echo", echo(), daemon=True)
    sched.run()
    assert sched.n_delivered == first  # counted from 0, not from `first`


def test_probe_env_keeps_operator_xla_flags(monkeypatch):
    """probe_default_backend must pass through operator XLA_FLAGS minus only
    the host-platform forcing flag (ADVICE: wholesale stripping made the
    probe validate a different XLA config than the real init uses)."""
    import subprocess

    from qsm_tpu.utils import device as device_mod

    captured = {}

    def fake_run(cmd, capture_output, text, timeout, env):
        captured["env"] = env
        raise subprocess.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr(device_mod.subprocess, "run", fake_run)
    monkeypatch.setenv(
        "XLA_FLAGS",
        "--xla_tpu_foo=1 --xla_force_host_platform_device_count=8 "
        "--xla_tpu_bar=2")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    p = device_mod.probe_default_backend(timeout_s=0.01)
    assert not p.ok
    env = captured["env"]
    assert "JAX_PLATFORMS" not in env
    assert env["XLA_FLAGS"] == "--xla_tpu_foo=1  --xla_tpu_bar=2"


def test_probe_env_drops_empty_xla_flags(monkeypatch):
    import subprocess

    from qsm_tpu.utils import device as device_mod

    captured = {}

    def fake_run(cmd, capture_output, text, timeout, env):
        captured["env"] = env
        raise subprocess.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr(device_mod.subprocess, "run", fake_run)
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    device_mod.probe_default_backend(timeout_s=0.01)
    assert "XLA_FLAGS" not in captured["env"]


def test_cli_refuses_device_backend_when_cpu_pinned():
    """This test process IS cpu-pinned (conftest forces the virtual CPU
    mesh), so the device-backend guard must refuse, not return silently
    (ADVICE: a silent return runs the lockstep kernel on host while looking
    like a TPU result)."""
    from qsm_tpu.utils.cli import _ensure_device_reachable

    with pytest.raises(SystemExit, match="pinned to the CPU platform"):
        _ensure_device_reachable()


class BrokenBoundCasSpec(CasSpec):
    """CAS spec whose declared scalar_state_bound is a lie: reachable
    states go up to n_values-1 but the bound claims 2."""

    def scalar_state_bound(self, n_ops):
        return 2


def test_broken_state_bound_defers_instead_of_wrong_verdict():
    spec = BrokenBoundCasSpec()
    # write(3) then read -> 0: under the TRUE spec this is a VIOLATION
    # (the read must see 3).  With bound=2 the old clamped gather read the
    # step-table row for state 1 instead of 3 and could answer wrongly;
    # now the out-of-bound lane must report BUDGET_EXCEEDED.
    h = sequential_history([
        (0, WRITE, 3, 0),
        (0, 0, 0, 0),  # read -> 0 (stale)
    ])
    v = JaxTPU(spec).check_histories(spec, [h])
    assert v[0] == int(Verdict.BUDGET_EXCEEDED)
    # the honest deferral path resolves it correctly via the oracle
    assert WingGongCPU().check_histories(spec, [h])[0] == int(
        Verdict.VIOLATION)


def test_correct_state_bound_unaffected():
    spec = CasSpec()
    h = sequential_history([
        (0, WRITE, 3, 0),
        (0, 0, 0, 3),  # read -> 3
    ])
    assert JaxTPU(spec).check_histories(spec, [h])[0] == int(
        Verdict.LINEARIZABLE)
