"""The trace plane's tier-1 gate (ISSUE 11 acceptance).

The load-bearing pins:

* a served request that fans out through pcomp sub-lanes across the
  worker pool yields, via the span log, ONE causal tree containing
  admission, every micro-batch (flush reason + worker id), every
  sub-lane, the recombine and the cache bank — and ``qsm-tpu trace``
  renders it;
* a SIGKILLed worker produces a flight-recorder dump whose last
  events include the doomed dispatch's trace id;
* the ``/metrics`` endpoint totals reconcile with ``stats()`` counters
  on the same run (they derive from the same books by construction);
* SHED responses carry the request's trace id (and the flight dump
  path when one fired);
* tracing off (the default) emits nothing and still answers with a
  trace id.
"""

from __future__ import annotations

import glob
import json
import os
import time
import urllib.request

import pytest

from qsm_tpu.obs import (FlightRecorder, MetricsRegistry, Observability,
                         Tracer, build_tree, load_dump, load_events,
                         parse_exposition, recent_events, render_tree)
from qsm_tpu.serve.client import CheckClient
from qsm_tpu.serve.server import CheckServer
from qsm_tpu.models.registry import MODELS
from qsm_tpu.utils.corpus import build_corpus


def _corpus(model, n, pids, ops, prefix):
    entry = MODELS[model]
    spec = entry.make_spec()
    return spec, build_corpus(
        spec, (entry.impls["atomic"], entry.impls["racy"]),
        n=n, n_pids=pids, max_ops=ops, seed_prefix=prefix)


# ---------------------------------------------------------------------------
# units: tracer / tree / metrics / flight
# ---------------------------------------------------------------------------

def test_tracer_emits_rotates_and_reloads(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tracer = Tracer(path=path, max_bytes=4096)
    for i in range(200):
        tracer.event("unit.tick", trace="t1", i=i)
    tracer.close()
    assert tracer.rotations >= 1
    assert os.path.exists(f"{path}.1")  # exactly one predecessor kept
    events = load_events(path, trace_id="t1")
    # rotation keeps a bounded WINDOW (live + one predecessor), never
    # unbounded disk; the newest events always survive
    assert 0 < len(events) <= 200
    assert events[-1]["attrs"]["i"] == 199
    # a torn tail (kill mid-write) is dropped, not fatal
    with open(path, "a") as f:
        f.write('{"name": "unit.torn", "trace": "t1"')
    assert load_events(path, trace_id="t1")[-1]["attrs"]["i"] == 199


def test_tracer_off_is_free_and_null_span_safe():
    tracer = Tracer()  # no sink
    assert not tracer.enabled
    assert tracer.event("x", trace="t") == ""
    with tracer.span("x", trace="t") as sp:
        sp.add(k=1)
        assert sp.id == ""
    assert tracer.events == 0


def test_span_context_manager_emits_on_exception(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tracer = Tracer(path=path)
    with pytest.raises(ValueError):
        with tracer.span("unit.fail", trace="t2"):
            raise ValueError("boom")
    tracer.close()
    ev = load_events(path, trace_id="t2")
    assert len(ev) == 1
    assert ev[0]["status"] == "error:ValueError"
    assert ev[0]["ms"] >= 0


def test_tree_reconstruction_and_orphans():
    events = [
        {"name": "request", "trace": "t", "span": "r", "parent": ""},
        {"name": "lane", "trace": "t", "span": "l", "parent": "r"},
        {"name": "batch", "trace": "t", "span": "b", "parent": "l"},
        # parent span never emitted (rotated away): still shown, as a
        # root — an incomplete tree must not lose events
        {"name": "orphan", "trace": "t", "span": "o", "parent": "gone"},
    ]
    roots = build_tree(events)
    assert [r["name"] for r in roots] == ["request", "orphan"]
    assert roots[0]["children"][0]["name"] == "lane"
    assert roots[0]["children"][0]["children"][0]["name"] == "batch"
    text = render_tree(roots)
    assert "request" in text and "`- batch" in text and "orphan" in text


def test_metrics_counter_gauge_histogram_and_exposition():
    reg = MetricsRegistry()
    reg.counter("unit_total", "help text").inc(3)
    reg.counter("unit_total").inc(2, kind="a")
    reg.gauge("unit_gauge").set(1.5)
    h = reg.histogram("unit_seconds")
    for v in (0.002, 0.002, 0.002, 0.4):
        h.observe(v)
    assert h.count() == 4
    assert 0.001 <= h.quantile(0.5) <= 0.005
    assert 0.25 <= h.quantile(0.99) <= 0.5
    reg.register_collector(
        lambda: [("unit_collected", "gauge", "", {}, 7.0)])
    text = reg.render()
    vals = parse_exposition(text)
    assert vals["unit_total"] == 3
    assert vals['unit_total{kind="a"}'] == 2
    assert vals["unit_gauge"] == 1.5
    assert vals["unit_seconds_count"] == 4
    assert vals["unit_collected"] == 7
    assert "# TYPE unit_seconds histogram" in text
    # identical name re-registration is idempotent; a type clash raises
    assert reg.counter("unit_total") is reg.counter("unit_total")
    with pytest.raises(TypeError):
        reg.gauge("unit_total")


def test_flight_ring_is_bounded_and_dump_roundtrips(tmp_path):
    fr = FlightRecorder(str(tmp_path), max_events=16,
                        min_interval_s=0.0)
    for i in range(100):
        fr.record({"name": "pool.tick", "trace": f"t{i}"})
    snap = fr.snapshot()
    assert snap["rings"]["pool"] == 16      # fixed-size ring
    assert snap["recorded"] == 100
    path = fr.dump("unit_test", extra={"k": 1})
    doc = load_dump(path)
    assert doc["reason"] == "unit_test" and doc["extra"] == {"k": 1}
    evs = recent_events(doc, "pool")
    assert len(evs) == 16
    assert evs[-1]["trace"] == "t99"        # the LAST events survive


def test_shed_storm_survives_rate_limit_shadow(tmp_path):
    """A storm tripping inside another dump's rate-limit window must
    NOT silently reset: the window re-arms on every further shed and
    the artifact lands once the limiter opens."""
    fr = FlightRecorder(str(tmp_path), min_interval_s=0.3,
                        storm_threshold=3, storm_window_s=60.0)
    assert fr.dump("unrelated") is not None     # opens the shadow
    assert [fr.note_shed() for _ in range(4)] == [None] * 4
    time.sleep(0.35)                            # limiter opens
    path = fr.note_shed()
    assert path is not None
    assert load_dump(path)["reason"] == "shed_storm"


def test_stopped_server_unregisters_its_metrics_collector(tmp_path):
    """A caller-supplied Observability outlives the server: after
    stop(), a reused registry must not double-emit (or pin) the dead
    server's series."""
    obs = Observability()
    s1 = CheckServer(obs=obs).start()
    s1.stop()
    s2 = CheckServer(obs=obs).start()
    try:
        _spec, hists = _corpus("cas", 1, 4, 10, "obs_reuse")
        client = CheckClient(f"127.0.0.1:{s2.port}")
        assert client.check("cas", hists, deadline_s=60)["ok"]
        client.close()
        names = [s[0] for s in obs.metrics.collect()
                 if s[0] == "qsm_serve_requests_total"]
        assert len(names) == 1                  # one live server's books
        assert obs.metrics.values()["qsm_serve_requests_total"] == 1
    finally:
        s2.stop()


def test_flight_dump_rate_limit_and_shed_storm(tmp_path):
    fr = FlightRecorder(str(tmp_path), min_interval_s=3600.0,
                        storm_threshold=5, storm_window_s=60.0)
    assert fr.dump("first") is not None
    assert fr.dump("second") is None        # rate-limited
    assert fr.dumps_suppressed == 1
    assert fr.dump("forced", force=True) is not None
    fr2 = FlightRecorder(str(tmp_path), min_interval_s=0.0,
                         storm_threshold=5, storm_window_s=60.0)
    paths = [fr2.note_shed() for _ in range(12)]
    fired = [p for p in paths if p]
    assert len(fired) >= 1                  # the storm tripped a dump
    assert paths[:4] == [None] * 4          # below threshold: no dump
    assert load_dump(fired[0])["reason"] == "shed_storm"


# ---------------------------------------------------------------------------
# e2e: the causal tree through pcomp sub-lanes and the worker pool
# ---------------------------------------------------------------------------

def test_trace_tree_pcomp_pool_end_to_end(tmp_path):
    """ISSUE 11 acceptance pin: a kv request fanning out through pcomp
    sub-lanes over a 2-worker pool yields ONE causal tree with
    admission, every micro-batch (flush reason + worker id), every
    sub-lane, every recombine, and the cache bank.  The pool is left
    COLD so the first dispatch holds its worker long enough that the
    second batch deterministically lands on the other worker."""
    log = str(tmp_path / "trace.jsonl")
    # max_lanes=4 < the ~8 sub-lanes: at least two micro-batches are
    # FORCED, so the cold-worker argument pins both workers
    srv = CheckServer(workers=2, max_lanes=4, trace_log=log,
                      flight_dir=str(tmp_path / "flight")).start()
    try:
        _spec, hists = _corpus("kv", 2, 8, 64, "obs_tree")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        res = client.check("kv", hists, deadline_s=120)
        assert res["ok"], res
        trace = res["trace"]
        client.close()
    finally:
        srv.stop()
    events = load_events(log, trace_id=trace)
    by_name: dict = {}
    for ev in events:
        by_name.setdefault(ev["name"], []).append(ev)
    # one request root, admission, a lane per history
    assert len(by_name["request"]) == 1
    assert len(by_name["admission.admit"]) == 1
    assert len(by_name["lane"]) == 2
    splits = by_name["pcomp.split"]
    subs = by_name["sublane"]
    assert len(splits) == 2                         # both hists split
    assert len(subs) == sum(s["attrs"]["keys"] for s in splits)
    # every sub-lane resolves through exactly one micro-batch — or a
    # sub-cache hit when two histories share a per-key sub-history —
    # and every batch stamp names its flush reason AND worker id
    batches = by_name["batch"]
    sub_hits = len(by_name.get("cache.hit", ()))
    assert len(batches) + sub_hits == len(subs)
    assert all(b["attrs"]["flush"] in
               ("full", "target", "interval", "deadline", "close")
               for b in batches)
    workers = {b["attrs"]["worker"] for b in batches}
    assert workers == {0, 1}, f"expected both pool workers: {workers}"
    assert len({b["attrs"]["batch"] for b in batches}) >= 2
    # the recombine and the cache bank
    assert len(by_name["pcomp.recombine"]) == 2
    assert len(by_name["cache.put"]) == len(batches)
    assert len(by_name["response"]) == 1
    # the events knit into ONE tree rooted at the request
    roots = build_tree(events)
    assert len(roots) == 1 and roots[0]["name"] == "request"
    rendered = render_tree(roots)
    for needle in ("admission.admit", "pcomp.split", "sublane",
                   "flush=", "worker=", "pcomp.recombine", "cache.put",
                   "response"):
        assert needle in rendered, f"missing {needle!r} in tree"


def test_trace_cli_reconstructs_tree(tmp_path, capsys):
    from qsm_tpu.utils.cli import main as cli_main

    log = str(tmp_path / "trace.jsonl")
    srv = CheckServer(trace_log=log).start()
    try:
        _spec, hists = _corpus("cas", 2, 4, 10, "obs_cli")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        res = client.check("cas", hists, deadline_s=60)
        assert res["ok"]
        client.close()
    finally:
        srv.stop()
    rc = cli_main(["trace", res["trace"], "--log", log])
    out = capsys.readouterr().out
    assert rc == 0
    assert "request" in out and "batch" in out and "response" in out
    # --json prints the raw event list
    rc = cli_main(["trace", res["trace"], "--log", log, "--json"])
    events = json.loads(capsys.readouterr().out)
    assert rc == 0 and all(e["trace"] == res["trace"] for e in events)
    # an unknown trace id exits 1 with a hint on stderr
    rc = cli_main(["trace", "feedbeef00000000", "--log", log])
    assert rc == 1


def test_client_supplied_trace_id_is_adopted(tmp_path):
    log = str(tmp_path / "trace.jsonl")
    srv = CheckServer(trace_log=log).start()
    try:
        _spec, hists = _corpus("cas", 1, 4, 10, "obs_adopt")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        res = client.check("cas", hists, trace="cafef00d12345678",
                           deadline_s=60)
        assert res["ok"] and res["trace"] == "cafef00d12345678"
        client.close()
    finally:
        srv.stop()
    assert load_events(log, trace_id="cafef00d12345678")


def test_tracing_off_default_still_answers_trace_id():
    srv = CheckServer().start()
    try:
        _spec, hists = _corpus("cas", 1, 4, 10, "obs_off")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        res = client.check("cas", hists, deadline_s=60)
        assert res["ok"]
        assert len(res["trace"]) == 16      # minted even with obs off
        st = client.stats()["stats"]
        assert st["obs"]["tracing"]["enabled"] is False
        assert st["obs"]["tracing"]["events"] == 0
        assert st["obs"]["flight"] is None
        client.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# e2e: flight recorder triggers
# ---------------------------------------------------------------------------

def test_sigkilled_worker_dumps_flight_with_doomed_trace(
        tmp_path, monkeypatch):
    """ISSUE 11 acceptance pin: kill:worker SIGKILLs the worker
    mid-batch; the supervisor sheds it, the flight recorder dumps, and
    the dump's last worker events carry the doomed dispatch's trace
    id.  The request itself still answers (re-dispatch/fallback)."""
    monkeypatch.setenv("QSM_TPU_FAULTS", "kill:worker@1")
    fdir = str(tmp_path / "flight")
    srv = CheckServer(workers=1, max_lanes=4, flight_dir=fdir,
                      pcomp=False).start()
    try:
        _spec, hists = _corpus("cas", 2, 4, 10, "obs_kill")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        res = client.check("cas", hists, deadline_s=60)
        assert res["ok"], res               # shed + re-dispatch, not lost
        client.close()
    finally:
        srv.stop()
    dumps = [p for p in glob.glob(os.path.join(fdir, "FLIGHT_*.json"))
             if "worker_crash" in p]
    assert dumps, "worker SIGKILL must fire a flight dump"
    doc = load_dump(sorted(dumps)[0])
    worker_evs = recent_events(doc, "worker")
    names = [e["name"] for e in worker_evs]
    assert "worker.dispatch" in names and "worker.shed" in names
    doomed = [t for e in worker_evs
              for t in (e.get("attrs") or {}).get("traces", [])]
    assert res["trace"] in doomed


def test_stop_dumps_flight_baseline(tmp_path):
    fdir = str(tmp_path / "flight")
    srv = CheckServer(flight_dir=fdir).start()
    srv.stop()
    dumps = glob.glob(os.path.join(fdir, "FLIGHT_*server_stop.json"))
    assert len(dumps) == 1                  # forced, never rate-limited


def test_shed_response_carries_trace_and_flight(tmp_path):
    """Satellite pin: a SHED answer is actionable — it names the
    request's trace id, and once a flight dump exists it names the
    artifact path too."""
    fdir = str(tmp_path / "flight")
    srv = CheckServer(queue_depth=1, flight_dir=fdir,
                      trace_log=str(tmp_path / "t.jsonl")).start()
    try:
        _spec, hists = _corpus("cas", 2, 4, 10, "obs_shed")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        res = client.check("cas", hists, deadline_s=30)  # 2 > depth 1
        assert res.get("shed") and res["reason"] == "queue full"
        assert len(res["trace"]) == 16
        assert "flight" not in res          # no dump fired yet: honest
        srv.obs.dump_flight("drill", force=True)
        res2 = client.check("cas", hists, deadline_s=30)
        assert res2.get("shed")
        assert res2["flight"] == srv.obs.flight_path()
        assert os.path.exists(res2["flight"])
        client.close()
    finally:
        srv.stop()
    # both sheds landed in the span log under their own trace ids
    evs = load_events(str(tmp_path / "t.jsonl"), trace_id=res["trace"])
    assert any(e["name"] == "admission.shed" for e in evs)


# ---------------------------------------------------------------------------
# e2e: metrics endpoint + reconciliation
# ---------------------------------------------------------------------------

def test_metrics_endpoint_reconciles_with_stats(tmp_path):
    """ISSUE 11 acceptance pin: the Prometheus exposition and the
    ``stats`` verb answer from the same books — totals are EQUAL on a
    quiesced server, not merely close."""
    srv = CheckServer(metrics_port=0,
                      trace_log=str(tmp_path / "t.jsonl")).start()
    try:
        _spec, hists = _corpus("cas", 4, 4, 10, "obs_recon")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        for _ in range(2):                  # second pass: cache hits
            assert client.check("cas", hists, deadline_s=60)["ok"]
        st = client.stats()["stats"]
        client.close()
        url = f"http://127.0.0.1:{srv.metrics_port}/metrics"
        vals = parse_exposition(
            urllib.request.urlopen(url).read().decode())
    finally:
        srv.stop()
    assert vals["qsm_serve_requests_total"] == st["requests"]
    assert vals["qsm_serve_histories_total"] == st["histories"]
    adm = st["admission"]
    assert vals["qsm_admission_admitted_lanes_total"] == \
        adm["admitted_lanes"]
    assert vals['qsm_admission_shed_total{reason="queue_full"}'] == \
        adm["shed_queue"]
    assert vals["qsm_batcher_batches_total"] == st["batcher"]["batches"]
    assert vals["qsm_batcher_lanes_total"] == st["batcher"]["lanes"]
    cache = st["cache"]
    assert vals["qsm_cache_hits_total"] == cache["hits"]
    assert vals["qsm_cache_misses_total"] == cache["misses"]
    assert cache["hits"] > 0                # the second pass hit
    assert vals["qsm_obs_span_events_total"] == \
        st["obs"]["tracing"]["events"] > 0
    # the request-latency histogram is labeled by verb (the SLO plane
    # reads per-verb windows); this run was check traffic only
    assert vals['qsm_serve_request_seconds_count{verb="check"}'] == \
        st["requests"]


def test_pool_dispatch_histogram_and_worker_metrics(tmp_path):
    srv = CheckServer(workers=1, metrics_port=0).start()
    try:
        _spec, hists = _corpus("cas", 2, 4, 10, "obs_poolm")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        assert client.check("cas", hists, deadline_s=60)["ok"]
        st = client.stats()["stats"]
        client.close()
        url = f"http://127.0.0.1:{srv.metrics_port}/metrics"
        vals = parse_exposition(
            urllib.request.urlopen(url).read().decode())
    finally:
        srv.stop()
    pool = st["pool"]
    assert vals["qsm_pool_workers_live"] == pool["live"] == 1
    assert vals["qsm_pool_dispatches_total"] == pool["dispatches"] >= 1
    assert vals['qsm_pool_dispatch_seconds_count{wid="0"}'] >= 1


def test_stats_watch_renders_and_cli_frames(tmp_path, capsys):
    from qsm_tpu.utils.cli import _render_stats_watch
    from qsm_tpu.utils.cli import main as cli_main

    srv = CheckServer(workers=0).start()
    try:
        _spec, hists = _corpus("cas", 2, 4, 10, "obs_watch")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        assert client.check("cas", hists, deadline_s=60)["ok"]
        st = client.stats()["stats"]
        client.close()
        frame = _render_stats_watch(st)
        assert "requests 1" in frame and "cache:" in frame
        assert "admission: in_flight" in frame
        rc = cli_main(["stats", "--serve", f"127.0.0.1:{srv.port}",
                       "--watch", "--watch-count", "2",
                       "--interval", "0.2"])
        out = capsys.readouterr().out
        assert rc == 0 and out.count("qsm-tpu serve") == 2
    finally:
        srv.stop()
    # --watch without --serve is a usage error, not a silent hang
    with pytest.raises(SystemExit):
        cli_main(["stats", "--watch"])


# ---------------------------------------------------------------------------
# the span<->stats bridge and the global sink
# ---------------------------------------------------------------------------

def test_batch_records_carry_obs_event_counts(tmp_path):
    """span->stats: a traced batch's compact search record says how
    many trace events the batch emitted (``obe``); stats->span: the
    serve.dispatch component event carries the compact record."""
    log = str(tmp_path / "trace.jsonl")
    srv = CheckServer(trace_log=log).start()
    try:
        _spec, hists = _corpus("cas", 2, 4, 10, "obs_bridge")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        res = client.check("cas", hists, deadline_s=60)
        assert res["ok"]
        client.close()
    finally:
        srv.stop()
    batches = [b for b in res["batches"] if b.get("search")]
    assert batches and all(b["search"].get("obe", 0) > 0
                           for b in batches)
    dispatch_evs = [e for e in load_events(log)
                    if e["name"] == "serve.dispatch"]
    assert dispatch_evs
    assert dispatch_evs[0]["attrs"]["search"]["nph"] >= 0


def test_failover_degrade_reports_into_global_sink(monkeypatch):
    """An engine-layer degradation (no obs handle anywhere near it)
    lands in the server's flight ring via the global sink."""
    from qsm_tpu import obs as obs_mod
    from qsm_tpu.models.registry import make
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.resilience.failover import FailoverBackend

    bundle = Observability(flight_dir="/nonexistent-never-dumped")
    obs_mod.set_global(bundle)
    try:
        spec, _sut = make("register", "atomic")

        from qsm_tpu.ops.backend import BackendUnavailable

        class _Dying:
            def check_histories(self, *_a):
                raise BackendUnavailable("chip gone")

        from qsm_tpu.resilience.policy import RetryPolicy

        fb = FailoverBackend(spec, _Dying(), fallback=WingGongCPU(),
                             policy=RetryPolicy(name="t", attempts=1,
                                                timeout_s=2.0))
        _spec2, hists = _corpus("register", 1, 2, 6, "obs_deg")
        fb.check_histories(spec, hists)
        snap = bundle.flight.snapshot()
        assert snap["rings"].get("failover") == 1
    finally:
        obs_mod.set_global(None)


def test_fault_hit_event_rides_global_sink(tmp_path, monkeypatch):
    """A fired fault-plane rule emits fault.hit (a dump trigger) and
    shows up in stats()['faults']."""
    monkeypatch.setenv("QSM_TPU_FAULTS", "raise:serve@1")
    fdir = str(tmp_path / "flight")
    srv = CheckServer(flight_dir=fdir).start()
    try:
        _spec, hists = _corpus("cas", 1, 4, 10, "obs_fault")
        client = CheckClient(f"127.0.0.1:{srv.port}")
        res = client.check("cas", hists, deadline_s=60)
        assert res["ok"]                    # degraded, not wrong
        st = client.stats()["stats"]
        client.close()
        assert st["faults"].get("serve", 0) >= 1
        assert st["serve_faults"] >= 1
    finally:
        srv.stop()
    dumps = glob.glob(os.path.join(fdir, "FLIGHT_*fault_plane.json"))
    assert dumps, "a fired fault rule must dump the flight ring"


def test_shrink_request_traces_frontier_rounds(tmp_path):
    """The shrink verb's tree: a root, shrink.round events (one per
    greedy frontier round), and batch events for candidate lanes."""
    from qsm_tpu.sched.runner import run_concurrent
    from qsm_tpu.models.registry import make

    spec, _ = make("cas", "atomic")
    # a failing history: seeded racy run until a violation shows
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.core.generator import generate_program

    oracle = WingGongCPU(memo=True)
    failing = None
    for seed in range(60):
        _s, sut = make("cas", "racy")
        prog = generate_program(spec, seed=seed, n_pids=4, max_ops=12)
        h = run_concurrent(sut, prog, seed=f"obs_shrink:{seed}")
        if int(oracle.check_histories(spec, [h])[0]) == 0:
            failing = h
            break
    assert failing is not None
    log = str(tmp_path / "trace.jsonl")
    srv = CheckServer(trace_log=log).start()
    try:
        client = CheckClient(f"127.0.0.1:{srv.port}")
        res = client.shrink("cas", failing, deadline_s=120)
        assert res["ok"] and res["verdict"] == "VIOLATION"
        client.close()
    finally:
        srv.stop()
    evs = load_events(log, trace_id=res["trace"])
    names = [e["name"] for e in evs]
    rounds = names.count("shrink.round")
    # one decide per memo-missing round, plus the input-history check;
    # fully-memoized rounds dispatch nothing (and emit nothing)
    assert 1 <= rounds <= res["rounds"] + 1
    assert "request" in names and "response" in names
    roots = build_tree(evs)
    assert len(roots) == 1
