"""`qsm-tpu check`: the checker as a standalone tool over EXTERNAL
traces (no scheduler involved) — the trace-validation use the OmniLink
paper frames (PAPERS.md).  Saved regression files are valid traces by
construction (same history encoding)."""

import json

from qsm_tpu.utils.cli import main


def _write(tmp_path, doc):
    p = tmp_path / "trace.json"
    p.write_text(json.dumps(doc))
    return str(p)


def test_check_linearizable_trace_with_witness(tmp_path, capsys):
    # register: write(3) completes, then a read sees 3
    path = _write(tmp_path, {
        "model": "register",
        "history": [[0, 1, 3, 0, 0, 1], [1, 0, 0, 3, 2, 3]]})
    rc = main(["check", "--trace", path, "--witness"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["verdict"] == "LINEARIZABLE"
    assert out["witness_verifies"] is True


def test_check_violating_trace(tmp_path, capsys):
    # stale read strictly after the write completed
    path = _write(tmp_path, {
        "model": "register",
        "history": [[0, 1, 3, 0, 0, 1], [1, 0, 0, 0, 2, 3]]})
    rc = main(["check", "--trace", path])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and out["verdict"] == "VIOLATION"


def test_check_pending_ops_and_model_override(tmp_path, capsys):
    # resp -1 == pending write; the read observing 1 forces completion
    path = _write(tmp_path, {
        "history": [[0, 1, 1, -1, 0, 1 << 30], [1, 0, 0, 1, 2, 3]]})
    rc = main(["check", "--trace", path, "--model", "register"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["verdict"] == "LINEARIZABLE"
    assert out["pending"] == 1


def test_check_accepts_saved_regression_files(tmp_path, capsys):
    # a regression file IS a trace: same history encoding + model field
    rc = main(["run", "--model", "cas", "--impl", "racy", "--trials",
               "80", "--seed", "5", "--save-regression",
               str(tmp_path / "cx.json")])
    assert rc == 1
    capsys.readouterr()
    rc = main(["check", "--trace", str(tmp_path / "cx.json")])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1 and out["verdict"] == "VIOLATION"
    assert out["model"] == "cas"


def test_check_batch_of_traces(tmp_path, capsys):
    """The plural 'histories' form: many external traces, one backend
    batch, per-trace verdicts."""
    path = _write(tmp_path, {
        "model": "register",
        "histories": [
            [[0, 1, 3, 0, 0, 1], [1, 0, 0, 3, 2, 3]],   # ok
            [[0, 1, 3, 0, 0, 1], [1, 0, 0, 0, 2, 3]],   # stale read
            [[0, 0, 0, 0, 0, 1]],                       # lone read ok
        ]})
    rc = main(["check", "--trace", path])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1
    assert out["verdicts"] == ["LINEARIZABLE", "VIOLATION",
                               "LINEARIZABLE"]
    assert out["violations"] == 1 and out["undecided"] == 0


def test_check_requires_model(tmp_path):
    import pytest

    path = _write(tmp_path, {"history": [[0, 0, 0, 0, 0, 1]]})
    with pytest.raises(SystemExit, match="no 'model'"):
        main(["check", "--trace", path])
