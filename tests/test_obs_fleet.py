"""Fleet-wide observability (ISSUE 15): cursor-paged span collection
(idempotent re-scrape, rotation survival, honest gaps), the collected
cross-process causal tree behind ``qsm-tpu trace <id> --addr ROUTER``
(client → router → nodes → workers, route hops and HA takeovers
included), metrics federation reconciling with per-node stats, the
SLO/health plane (grammar, burn rates, breach flight dumps, pinned
exit codes), and the standby-shed trace pin."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from qsm_tpu.fleet.membership import HashRing
from qsm_tpu.fleet.router import FleetRouter
from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.obs import (HEALTH_EXIT_CODES, SpanCollector, build_tree,
                         load_dump, load_events, parse_slo,
                         read_span_page, render_tree, trace_closure)
from qsm_tpu.obs.slo import SloEvaluator, worst_status
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.resilience.policy import preset
from qsm_tpu.serve.cache import fingerprint_key
from qsm_tpu.serve.client import CheckClient
from qsm_tpu.serve.protocol import VERDICT_NAMES
from qsm_tpu.serve.server import CheckServer
from qsm_tpu.utils.corpus import build_corpus

SPEC = CasSpec()


@pytest.fixture(scope="module")
def corpus():
    return build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=8,
                        n_pids=4, max_ops=8, seed_base=0,
                        seed_prefix="obs_fleet")


@pytest.fixture(scope="module")
def expected(corpus):
    oracle = WingGongCPU(memo=True)
    return [VERDICT_NAMES[int(v)]
            for v in oracle.check_histories(SPEC, corpus)]


def _write_log(path, events):
    with open(path, "a") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _ev(i, trace="T", parent=""):
    return {"ts": i, "name": "ev", "trace": trace, "span": f"s{i:04d}",
            "parent": parent}


# --- the obs.spans cursor (obs/collect.py) --------------------------------

def test_span_page_idempotent_rotation_and_gap(tmp_path):
    """The cursor contract: pages partition the log exactly (zero
    duplicates on re-scrape), a one-file rotation keeps the unread
    tail readable from the predecessor, a double rotation answers an
    honest ``gap``, and a torn tail is never half-consumed."""
    log = str(tmp_path / "t.jsonl")
    _write_log(log, [_ev(i) for i in range(10)])
    p1 = read_span_page(log, None, max_events=4)
    assert len(p1["events"]) == 4 and p1["more"] and not p1["gap"]
    p2 = read_span_page(log, p1["cursor"], max_events=100)
    assert len(p2["events"]) == 6 and not p2["more"]
    # idempotency: the re-scrape ships ZERO events
    p3 = read_span_page(log, p2["cursor"], max_events=100)
    assert p3["events"] == [] and not p3["gap"]
    # torn tail: an incomplete line is not consumed...
    with open(log, "a") as f:
        f.write('{"ts": 99, "name": "torn"')
    p4 = read_span_page(log, p3["cursor"], max_events=100)
    assert p4["events"] == []
    # ...and is served whole once completed
    with open(log, "a") as f:
        f.write(', "span": "s9999"}\n')
    p5 = read_span_page(log, p4["cursor"], max_events=100)
    assert [e["name"] for e in p5["events"]] == ["torn"]
    # rotation: live -> .1, fresh live; the cursor keeps draining .1
    os.replace(log, log + ".1")
    _write_log(log, [_ev(i) for i in range(20, 23)])
    p6 = read_span_page(log, p5["cursor"], max_events=100)
    p7 = read_span_page(log, p6["cursor"], max_events=100)
    got = [e["span"] for e in p6["events"] + p7["events"]]
    assert got == ["s0020", "s0021", "s0022"]
    assert not p6["gap"] and not p7["gap"]
    # double rotation: the cursor's file is gone — honest gap, resume
    # from the oldest surviving file (never a silent loss)
    os.replace(log, log + ".1")
    _write_log(log, [_ev(i) for i in range(30, 32)])
    stale = {"sig": "deadbeefdeadbeef", "off": 123}
    p8 = read_span_page(log, stale, max_events=100)
    assert p8["gap"]
    spans = [e["span"] for e in p8["events"]]
    while p8["more"]:
        p8 = read_span_page(log, p8["cursor"], max_events=100)
        spans += [e["span"] for e in p8["events"]]
    assert spans[-2:] == ["s0030", "s0031"]


def test_span_page_empty_live_cursor_never_reships(tmp_path):
    """A cursor minted while the live file had no identity yet (a
    scrape landing mid-rotation, before the first post-rotation
    write) positions at the live head — later pages must NOT restart
    from the predecessor and duplicate its events."""
    log = str(tmp_path / "t.jsonl")
    _write_log(log, [_ev(i) for i in range(4)])
    p1 = read_span_page(log, None, max_events=100)
    assert len(p1["events"]) == 4
    # rotation leaves an EMPTY live file (no first line yet)
    os.replace(log, log + ".1")
    open(log, "w").close()
    p2 = read_span_page(log, p1["cursor"], max_events=100)
    assert p2["events"] == [] and not p2["gap"]
    assert p2["cursor"]["sig"] == ""
    # the live file gains events: ONLY they ship — the predecessor's
    # 4 events were already consumed and must never re-ship
    _write_log(log, [_ev(i) for i in range(10, 12)])
    p3 = read_span_page(log, p2["cursor"], max_events=100)
    assert [e["span"] for e in p3["events"]] == ["s0010", "s0011"]
    assert not p3["gap"]
    p4 = read_span_page(log, p3["cursor"], max_events=100)
    assert p4["events"] == []


def test_collector_cursors_survive_restart(tmp_path):
    """The router-restart pin: per-node cursors persist, so a fresh
    collector over the same dir re-ships ZERO events."""
    log = str(tmp_path / "node.jsonl")
    _write_log(log, [_ev(i) for i in range(6)])

    def fetch(_nid, cursor, max_events):
        return {"ok": True, "enabled": True,
                **read_span_page(log, cursor, max_events)}

    cdir = str(tmp_path / "collect")
    col = SpanCollector(cdir)
    assert col.sweep(["n0"], fetch)["events"] == 6
    assert col.sweep(["n0"], fetch)["events"] == 0  # idempotent
    col.close()
    # a restarted collector resumes from the persisted cursor
    col2 = SpanCollector(cdir)
    assert col2.sweep(["n0"], fetch)["events"] == 0
    _write_log(log, [_ev(9)])
    assert col2.sweep(["n0"], fetch)["events"] == 1
    # collected events are node-stamped and land in ONE log
    events = load_events(col2.out_path)
    assert len(events) == 7
    assert all(e["node"] == "n0" for e in events)
    col2.close()


def test_collector_dead_node_costs_one_bounded_fetch(tmp_path):
    def fetch(_nid, _cursor, _max):
        raise ConnectionError("down")

    col = SpanCollector(str(tmp_path / "c"))
    res = col.sweep(["n0"], fetch)
    assert res["node_failures"] == 1 and res["events"] == 0
    col.close()


# --- cross-process collection through a live fleet ------------------------

def _fleet(tmp_path, corpus_dirname="collect", **router_kw):
    nodes = [CheckServer(node_id=f"n{i}",
                         trace_log=str(tmp_path / f"n{i}.jsonl"),
                         flush_s=0.005).start() for i in range(2)]
    router = FleetRouter(
        [(s.node_id, s.address) for s in nodes],
        policy=preset("fleet-route").with_(timeout_s=3.0),
        probe_policy=preset("fleet-probe").with_(timeout_s=1.0),
        heartbeat_s=0.2, anti_entropy_s=0.0,
        trace_log=str(tmp_path / "router.jsonl"),
        collect_dir=str(tmp_path / corpus_dirname),
        **router_kw).start()
    return router, nodes


def test_collected_tree_spans_router_and_both_nodes(tmp_path, corpus,
                                                    expected):
    """The basic fleet-native trace: ONE causal tree, the node's
    ``request`` root a CHILD of the router's ``node.dispatch`` edge
    (cross-process causality by edges, never wall clocks), and a
    re-sweep ships zero duplicates."""
    router, nodes = _fleet(tmp_path)
    try:
        with CheckClient(router.address, timeout_s=60.0) as c:
            res = c.check("cas", corpus)
            assert res["ok"] and res["verdicts"] == expected
            trace = res["trace"]
            assert router.collect_sweep()["events"] > 0
            assert router.collect_sweep()["events"] == 0  # idempotent
            te = c.trace_events(trace)
        events = te["events"]
        by_span = {e["span"]: e for e in events}
        reqs = [e for e in events if e["name"] == "request"]
        assert {e.get("node") for e in reqs} == {"n0", "n1"}
        for r in reqs:
            parent = by_span.get(r.get("parent"))
            assert parent is not None
            assert parent["name"] == "node.dispatch"
        # one connected tree: a single root holding both nodes' lanes
        roots = build_tree(events)
        assert len(roots) == 1 and roots[0]["name"] == "route.request"
        rendered = render_tree(roots)
        assert "node.dispatch" in rendered and "lane" in rendered
    finally:
        router.stop()
        for s in nodes:
            s.stop()


def test_federation_reconciles_with_per_node_stats(tmp_path, corpus):
    """ISSUE 15 acceptance: the router's federated ``/metrics`` and
    per-node ``stats()`` answer from the same books — per-node totals
    EQUAL on a quiesced fleet; a stopped node becomes a staleness
    gauge, and the scrape stays bounded (no hang)."""
    router, nodes = _fleet(tmp_path)
    try:
        with CheckClient(router.address, timeout_s=60.0) as c:
            assert c.check("cas", corpus)["ok"]
            m = c.metrics()
        samples = {}
        for name, _t, _h, labels, value in m["samples"]:
            if isinstance(labels, dict):
                key = (name, labels.get("node"),
                       tuple(sorted((k, v) for k, v in labels.items()
                                    if k != "node")))
                samples[key] = value
        per_node = router.node_stats()
        for nid in ("n0", "n1"):
            st = per_node[nid]
            assert "error" not in st
            assert samples[("qsm_serve_requests_total", nid, ())] \
                == st["requests"]
            assert samples[("qsm_serve_histories_total", nid, ())] \
                == st["histories"]
            assert samples[("qsm_cache_hits_total", nid, ())] \
                == st["cache"]["hits"]
            assert samples[("qsm_fleet_node_scrape_stale", nid, ())] \
                == 0.0
        # a dead node is a hole, not a hang: bounded scrape, stale=1.
        # (Drop the pooled links and wait out one LineChannel poll
        # slice: a just-stopped node answers for up to ~0.5 s.)
        nodes[1].stop()
        router.links["n1"].close_all()
        time.sleep(0.7)
        t0 = time.monotonic()
        fed = {(s[0], s[3].get("node")): s[4]
               for s in router._federated_samples()}
        assert time.monotonic() - t0 < 10.0
        assert fed[("qsm_fleet_node_scrape_stale", "n1")] == 1.0
        assert fed[("qsm_fleet_node_scrape_stale", "n0")] == 0.0
        assert ("qsm_serve_requests_total", "n1") not in fed
    finally:
        router.stop()
        for s in nodes:
            s.stop()


# --- the SLO / health plane -----------------------------------------------

def test_slo_grammar_parses_and_refuses():
    objs = parse_slo("check=250ms:p99,shed_rate<0.01")
    assert [(o.name, o.kind) for o in objs] == \
        [("check_p99_ms", "latency"), ("shed_rate", "shed_rate")]
    assert objs[0].target == pytest.approx(0.25)
    assert objs[0].quantile == pytest.approx(0.99)
    assert parse_slo("shrink=2s:p50")[0].target == pytest.approx(2.0)
    assert parse_slo("check=1ms:p999")[0].quantile == \
        pytest.approx(0.999)
    for bad in ("check=250ms", "bogus=1ms:p99", "shed_rate<2",
                "shed_rate<0", "", "check=1ms:p0",
                "check=250ms:p99,check=1ms:p99"):
        with pytest.raises(ValueError):
            parse_slo(bad)
    # a typo'd --slo refuses at server construction, loudly
    with pytest.raises(ValueError):
        CheckServer(slo="chekc=1ms:p99")


def test_slo_window_breach_and_recovery():
    """The evaluator over a synthetic histogram: under-target traffic
    is ok, slow traffic breaches (burn > 1), and `worst_status` folds
    fleet statuses with unknowns read as degraded."""
    from qsm_tpu.obs.metrics import Histogram

    hist = Histogram("t_seconds")
    counters = {"requests": 0, "sheds": 0}
    breaches = []
    ev = SloEvaluator(
        parse_slo("check=100ms:p50,shed_rate<0.5"),
        latency_hist=hist,
        requests_fn=lambda: counters["requests"],
        sheds_fn=lambda: counters["sheds"],
        window_s=30.0, min_tick_s=0.01,
        on_breach=breaches.append)
    doc = ev.evaluate()
    assert doc["status"] == "ok"        # no traffic, no breach
    for _ in range(10):
        hist.observe(0.01, verb="check")
        counters["requests"] += 1
    time.sleep(0.02)
    assert ev.evaluate()["status"] == "ok"
    for _ in range(50):
        hist.observe(1.0, verb="check")  # way past 100ms p50
        counters["requests"] += 1
    time.sleep(0.02)
    doc = ev.evaluate()
    assert doc["status"] == "breach"
    rows = {r["objective"]: r for r in doc["objectives"]}
    assert rows["check_p50_ms"]["burn_rate"] > 1.0
    assert breaches and breaches[0]["objective"] == "check_p50_ms"
    # the transition fires ONCE, not per evaluation
    assert ev.evaluate()["status"] == "breach"
    assert len(breaches) == 1
    assert worst_status(["ok", "degraded"]) == "degraded"
    assert worst_status(["ok", "unreachable"]) == "degraded"
    assert worst_status(["breach", "ok"]) == "breach"
    assert HEALTH_EXIT_CODES == {"ok": 0, "degraded": 1, "breach": 2}


def test_health_op_breach_flight_dump_and_cli_exit_codes(tmp_path,
                                                         corpus):
    """End to end: a server under an impossible latency objective
    answers ``health`` with breach, fires the slo_breach flight dump
    (the shed-storm heuristic as a configured objective), and the
    `qsm-tpu health` CLI maps statuses to pinned exit codes."""
    from qsm_tpu.utils.cli import main

    flight = str(tmp_path / "flight")
    srv = CheckServer(slo="check=1ms:p50", slo_window_s=30.0,
                      flight_dir=flight,
                      trace_log=str(tmp_path / "t.jsonl")).start()
    try:
        addr = f"127.0.0.1:{srv.port}"
        with CheckClient(addr) as c:
            assert c.health()["status"] == "ok"   # quiet server
            assert c.check("cas", corpus)["ok"]   # >> 1ms p50
            time.sleep(0.05)
            h = c.health()
        assert h["status"] == "breach"
        rows = {r["objective"]: r for r in h["slo"]["objectives"]}
        assert rows["check_p50_ms"]["burn_rate"] > 1.0
        dumps = [f for f in sorted(os.listdir(flight))
                 if "slo_breach" in f]
        assert dumps, os.listdir(flight)
        assert load_dump(os.path.join(flight, dumps[0]))["reason"] \
            == "slo_breach"
        # pinned exit codes: 2 = breach, 3 = unreachable
        assert main(["health", "--addr", addr]) == 2
    finally:
        srv.stop()
    assert main(["health", "--addr", "127.0.0.1:1"]) == 3
    # a healthy (objective-free) server answers 0
    srv2 = CheckServer().start()
    try:
        assert main(["health", "--addr",
                     f"127.0.0.1:{srv2.port}"]) == 0
    finally:
        srv2.stop()


def test_router_health_folds_node_statuses(tmp_path, corpus):
    router, nodes = _fleet(tmp_path, slo="check=10s:p99")
    try:
        with CheckClient(router.address, timeout_s=60.0) as c:
            assert c.check("cas", corpus[:2])["ok"]
            h = c.health()
        assert h["ok"] and h["status"] == "ok"
        assert set(h["fleet"]) == {"n0", "n1"}
        # a dead node degrades the fleet's health, bounded.  (Drop the
        # pooled links and wait out one LineChannel poll slice: a just-
        # stopped node's connection threads answer for up to ~0.5 s.)
        nodes[0].stop()
        router.links["n0"].close_all()
        time.sleep(0.7)
        doc = router.health_doc(timeout_s=2.0)
        assert doc["fleet"]["n0"]["status"] == "unreachable"
        assert doc["status"] == "degraded"
    finally:
        router.stop()
        for s in nodes:
            s.stop()


# --- trace --follow (live tail) -------------------------------------------

def test_trace_follow_prints_new_events(tmp_path, capsys):
    """The monitor-session debugging loop: --follow tails the span log
    and prints each NEW event of the trace as it lands, stopping after
    the idle bound."""
    from qsm_tpu.utils.cli import main

    log = str(tmp_path / "t.jsonl")
    _write_log(log, [_ev(0)])

    def feed():
        time.sleep(0.3)
        _write_log(log, [{"ts": 1, "name": "late.event", "trace": "T",
                          "span": "s_late", "parent": "s0000"}])

    t = threading.Thread(target=feed)
    t.start()
    rc = main(["trace", "T", "--log", log, "--follow",
               "--interval", "0.1", "--max-idle", "1.0"])
    t.join()
    out = capsys.readouterr().out
    assert rc == 0
    assert "+ late.event" in out
    # without --log/--addr the verb refuses loudly
    with pytest.raises(SystemExit):
        main(["trace", "T"])


# --- the standby-shed satellite (ISSUE 15) --------------------------------

def test_standby_shed_carries_trace_and_span(tmp_path, corpus):
    """A standby's ``router_standby`` SHED carries the request's trace
    id AND leaves a span in its log, so a client bouncing between
    ``--addr a,b`` during a takeover window is reconstructable."""
    nodes = [CheckServer(node_id="n0",
                         trace_log=str(tmp_path / "n0.jsonl"),
                         flush_s=0.005).start()]
    lease = str(tmp_path / "lease.json")
    kw = dict(policy=preset("fleet-route").with_(timeout_s=3.0),
              probe_policy=preset("fleet-probe").with_(timeout_s=1.0),
              heartbeat_s=0.2, anti_entropy_s=0.0,
              lease_ttl_s=0.5, ha_beat_s=0.0)
    ra = FleetRouter([(s.node_id, s.address) for s in nodes],
                     node_id="rA", lease_path=lease, **kw).start()
    rb_log = str(tmp_path / "rb.jsonl")
    rb = FleetRouter([(s.node_id, s.address) for s in nodes],
                     node_id="rB", lease_path=lease,
                     trace_log=rb_log, **kw).start()
    try:
        assert ra.ha_role == "active" and rb.ha_role == "standby"
        with CheckClient(rb.address, timeout_s=10.0) as c:
            res = c.check("cas", corpus[:1])
        assert res.get("shed") and res["reason"] == "router_standby"
        trace = res.get("trace")
        assert trace, "a standby SHED must carry the trace id"
        rb.obs.tracer.close()
        sheds = [e for e in load_events(rb_log, trace_id=trace)
                 if e.get("name") == "admission.shed"]
        assert sheds, "the refusal must leave a span"
        at = sheds[0].get("attrs") or {}
        assert at.get("reason") == "router_standby"
        assert at.get("role") == "standby"
    finally:
        ra.stop()
        rb.stop()
        for s in nodes:
            s.stop()


# --- THE acceptance soak: hop + takeover + both nodes, ONE tree -----------

def _spawn_node(nid: str, tmp_path, faults=None) -> tuple:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("QSM_TPU_FAULTS", None)
    if faults:
        env["QSM_TPU_FAULTS"] = faults
    unix = str(tmp_path / f"{nid}.sock")
    proc = subprocess.Popen(
        [sys.executable, "-m", "qsm_tpu", "serve", "--unix", unix,
         "--node-id", nid, "--workers", "1",
         "--trace-log", str(tmp_path / f"{nid}_trace.jsonl")],
        stdout=subprocess.PIPE, text=True, env=env)
    banner = json.loads(proc.stdout.readline())
    assert banner["serving"] == unix
    return proc, unix


def test_fleet_trace_renders_hop_takeover_and_both_nodes(tmp_path):
    """ISSUE 15 acceptance pin: one soak — a routed pcomp request that
    survives a mid-request node wedge AND an HA router takeover —
    then ``trace <id> --addr`` renders ONE causal tree containing the
    ``router.takeover`` edge, the ``route.hop`` off the lost node,
    and BOTH nodes' pcomp sub-lanes down to the pool worker."""
    from qsm_tpu.models.registry import MODELS

    entry = MODELS["kv"]
    spec = entry.make_spec()
    hists = build_corpus(spec,
                         (entry.impls["atomic"], entry.impls["racy"]),
                         n=6, n_pids=8, max_ops=24, seed_base=100,
                         seed_prefix="obs_fleet_kv")
    oracle = WingGongCPU(memo=True)
    want = [VERDICT_NAMES[int(v)]
            for v in oracle.check_histories(spec, hists)]
    # the ring is a pure function of the node ids: pick the victim
    # (the busiest node) BEFORE spawning, so only IT gets the wedge
    ring = HashRing(["n0", "n1"])
    owners = [ring.node_for(fingerprint_key(spec, h), {"n0", "n1"})
              for h in hists]
    victim = max(("n0", "n1"), key=owners.count)
    survivor = "n1" if victim == "n0" else "n0"
    assert owners.count(survivor) > 0, "need lanes on both nodes"
    procs = {}
    for nid in ("n0", "n1"):
        procs[nid] = _spawn_node(
            nid, tmp_path,
            faults="hang:worker" if nid == victim else None)
    lease = str(tmp_path / "lease.json")
    kw = dict(policy=preset("fleet-route").with_(timeout_s=2.0),
              probe_policy=preset("fleet-probe").with_(timeout_s=1.0),
              heartbeat_s=5.0, anti_entropy_s=0.0,
              lease_ttl_s=0.5, ha_beat_s=0.0)
    ra = FleetRouter([(nid, u) for nid, (_p, u) in procs.items()],
                     node_id="rA", lease_path=lease, **kw).start()
    rb = FleetRouter([(nid, u) for nid, (_p, u) in procs.items()],
                     node_id="rB", lease_path=lease,
                     trace_log=str(tmp_path / "rb_trace.jsonl"),
                     collect_dir=str(tmp_path / "collect"),
                     **kw).start()
    result = {}
    try:
        assert ra.ha_role == "active" and rb.ha_role == "standby"
        # "SIGKILL" rA: socket gone, beats stopped, lease NOT released
        # (a real SIGKILL cannot run the release path)
        ra.lease = None
        ra.stop()

        def drive():
            with CheckClient(f"{ra.address},{rb.address}",
                             timeout_s=60.0) as c:
                result.update(c.check("kv", hists, deadline_s=45.0))

        t = threading.Thread(target=drive)
        t.start()
        # rB promotes only after lease expiry + grace + node probe —
        # until then the client bounces off its router_standby SHEDs
        deadline = time.monotonic() + 10.0
        while rb.ha_role != "active" and time.monotonic() < deadline:
            time.sleep(0.1)
            rb.ha_beat()
        assert rb.ha_role == "active" and rb.takeovers == 1
        # collect while the victim is wedged mid-dispatch: its partial
        # sub-lane spans are scraped BEFORE it would die for real
        for _ in range(30):
            rb.collect_sweep()
            if not t.is_alive():
                break
            time.sleep(0.2)
        t.join(90.0)
        assert not t.is_alive()
        assert result.get("ok"), result
        assert result["verdicts"] == want
        trace = result["trace"]
        rb.collect_sweep()  # the post-completion tail
        with CheckClient(rb.address, timeout_s=30.0) as c:
            te = c.trace_events(trace)
        events = te["events"]
        names = {e["name"] for e in events}
        assert "router.takeover" in names
        hops = [e for e in events if e["name"] == "route.hop"]
        assert any((e.get("attrs") or {}).get("hop_from") == victim
                   for e in hops)
        subl = [e for e in events if e["name"] == "sublane"]
        assert {e.get("node") for e in subl} == {"n0", "n1"}, \
            "both nodes' pcomp sub-lanes must be in the tree"
        workers = {(e.get("attrs") or {}).get("worker")
                   for e in events if e["name"] == "batch"}
        assert 0 in workers or "0" in workers, workers
        # ONE tree: the takeover is the root, the request under it,
        # the hop and both nodes' subtrees under the request
        roots = build_tree(events)
        takeover_roots = [r for r in roots
                          if r["name"] == "router.takeover"]
        assert len(takeover_roots) == 1

        def walk(node, acc):
            acc.append(node)
            for ch in node["children"]:
                walk(ch, acc)
            return acc

        in_tree = walk(takeover_roots[0], [])
        tree_names = {e["name"] for e in in_tree}
        assert "route.request" in tree_names
        assert "route.hop" in tree_names
        assert {e.get("node") for e in in_tree
                if e["name"] == "sublane"} == {"n0", "n1"}
        # the standby-era bounce is in the event list too: the client
        # kept ONE trace across doors (client-minted id)
        assert any(e["name"] == "admission.shed"
                   and (e.get("attrs") or {}).get("reason")
                   == "router_standby" for e in events)
        # and the CLI renders it (exit 0 = events found)
        from qsm_tpu.utils.cli import main

        assert main(["trace", trace, "--addr", rb.address]) == 0
    finally:
        ra.stop()
        rb.stop()
        for proc, _unix in procs.values():
            if proc.poll() is None:
                proc.kill()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
