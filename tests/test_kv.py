"""Multi-key KV map (config #5, BASELINE.json:11): P-compositionality split
checks 16-pid/64-op histories key-by-key; pcomp verdicts must equal direct
whole-history verdicts wherever the direct search is feasible (PAPERS.md:5
soundness), and the racy stale-cache impl must be caught."""

import numpy as np
import pytest

from qsm_tpu import (PropertyConfig, Verdict, WingGongCPU, check_one,
                     generate_program, prop_concurrent, run_concurrent,
                     sequential_history)
from qsm_tpu.models.kv import GET, PUT, AtomicKvSUT, KvSpec, StaleCacheKvSUT
from qsm_tpu.ops.jax_kernel import JaxTPU
from qsm_tpu.ops.pcomp import PComp, split_history

SPEC = KvSpec(n_keys=4, n_values=4)


def test_step_jax_matches_py():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    step = jax.jit(SPEC.step_jax)
    for _ in range(200):
        state = [int(v) for v in rng.integers(0, SPEC.n_values, SPEC.n_keys)]
        cmd = int(rng.integers(0, 2))
        arg = int(rng.integers(0, SPEC.CMDS[cmd].n_args))
        resp = int(rng.integers(0, SPEC.CMDS[cmd].n_resps))
        py_s, py_ok = SPEC.step_py(state, cmd, arg, resp)
        jx_s, jx_ok = step(jnp.asarray(state, jnp.int32),
                           jnp.int32(cmd), jnp.int32(arg), jnp.int32(resp))
        assert list(map(int, jx_s)) == list(py_s)
        assert bool(jx_ok) == py_ok


def test_split_history_projects_to_register():
    h = sequential_history([
        (0, PUT, SPEC.put_arg(2, 3), 0),
        (1, GET, 2, 3),
        (1, GET, 0, 0),
    ])
    subs = split_history(SPEC, h)
    assert set(subs) == {0, 2}
    k2 = subs[2]
    assert [(o.cmd, o.arg, o.resp) for o in k2.ops] == [(1, 3, 0), (0, 0, 3)]
    # timestamps preserved: real-time order within the key is induced
    assert [o.invoke_time for o in k2.ops] == [0, 2]


def test_pcomp_agrees_with_direct_oracle():
    """Soundness spot-check: pcomp(WingGongCPU) == direct WingGongCPU on
    whole KV histories small enough to search directly."""
    spec = KvSpec(n_keys=2, n_values=4)  # concentrate ops per key
    direct = WingGongCPU()
    pcomp = PComp(spec)
    hists = []
    for seed in range(40):
        prog = generate_program(spec, seed=seed, n_pids=4, max_ops=12)
        for sut in (AtomicKvSUT(spec), StaleCacheKvSUT(spec)):
            hists.append(run_concurrent(sut, prog, seed=f"kv{seed}"))
    d = direct.check_histories(spec, hists)
    p = pcomp.check_histories(spec, hists)
    assert (d == p).all(), list(zip(d.tolist(), p.tolist()))
    assert (d == Verdict.VIOLATION).any(), "sample vacuous: no violations"


@pytest.mark.slow
def test_pcomp_device_parity_at_scale():
    """16 pids × up to 64 ops (the config-#5 scale): pcomp over the device
    kernel equals pcomp over the CPU oracle, after BUDGET_EXCEEDED verdicts
    are resolved the way the property layer resolves them (SURVEY.md §7
    hard-parts #5 — the device budget is bounded, never a guess)."""
    cpu = PComp(SPEC)
    dev = PComp(SPEC, lambda pspec: JaxTPU(pspec, budget=100_000))
    hists = []
    for seed in range(20):
        prog = generate_program(SPEC, seed=seed, n_pids=16, max_ops=64)
        for sut in (AtomicKvSUT(SPEC), StaleCacheKvSUT(SPEC)):
            hists.append(run_concurrent(sut, prog, seed=f"K{seed}"))
    c = cpu.check_histories(SPEC, hists)
    d = dev.check_histories(SPEC, hists)
    undecided = d == Verdict.BUDGET_EXCEEDED
    resolved = np.where(undecided, c, d)
    assert (c == resolved).all(), list(zip(c.tolist(), d.tolist()))
    # the budget must not be doing all the work: most verdicts decided on
    # device, both outcomes present
    assert undecided.mean() < 0.25, f"{undecided.sum()} of {len(hists)}"
    assert (d == Verdict.VIOLATION).any()
    assert (d == Verdict.LINEARIZABLE).any()


def test_atomic_kv_passes():
    cfg = PropertyConfig(n_trials=40, n_pids=16, max_ops=64, seed=13)
    res = prop_concurrent(SPEC, AtomicKvSUT(SPEC), cfg,
                          backend=PComp(SPEC), oracle=WingGongCPU())
    assert res.ok, res.counterexample


def test_stale_cache_kv_fails_and_shrinks():
    cfg = PropertyConfig(n_trials=40, n_pids=16, max_ops=64, seed=13)
    res = prop_concurrent(SPEC, StaleCacheKvSUT(SPEC), cfg,
                          backend=PComp(SPEC), oracle=WingGongCPU())
    assert not res.ok, "stale reads were never caught"
    cx = res.counterexample
    assert check_one(PComp(SPEC), SPEC, cx.history) == Verdict.VIOLATION
    # minimal counterexample must still mix a PUT and a GET
    cmds = {op.cmd for op in cx.program.ops}
    assert cmds == {GET, PUT}, cx.program


def test_pcomp_refuses_non_decomposable_spec():
    from qsm_tpu.models import CasSpec

    cas = CasSpec()
    h = sequential_history([(0, 0, 0, 0)])
    with pytest.raises(ValueError, match="partition_key"):
        split_history(cas, h)
