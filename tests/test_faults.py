"""Fault injection end-to-end (SURVEY.md §5 failure detection): histories
with dropped/duplicated messages and crashed pids flow through the full
generate→execute→check pipeline; a correct SUT stays linearizable (pending
ops complete/prune), and verdict parity holds on faulty histories."""

from qsm_tpu import (FaultPlan, PropertyConfig, Recv, Send, WingGongCPU,
                     generate_program, prop_concurrent, run_concurrent)
from qsm_tpu.models.register import AtomicRegisterSUT, RegisterSpec
from qsm_tpu.models.cas import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.ops.jax_kernel import JaxTPU
from qsm_tpu.utils.report import faults_from_doc, faults_to_doc

SPEC = RegisterSpec()


def test_prop_concurrent_atomic_register_under_message_loss():
    """Drops make ops pending, never wrong: the atomic register must still
    pass — a pending op may or may not have taken effect and the checker
    tries both (SURVEY.md §3.2 complete/prune)."""
    faults = FaultPlan(p_drop=0.15, protected=set())
    cfg = PropertyConfig(n_trials=60, n_pids=2, max_ops=10, seed=21,
                         faults=faults)
    res = prop_concurrent(SPEC, AtomicRegisterSUT(), cfg)
    assert res.ok, res.counterexample
    assert res.undecided == 0


class TaggedRegisterSUT:
    """Duplicate-tolerant register, the at-least-once RPC discipline on
    both ends: requests carry a per-pid sequence number; the server dedupes
    by (client, seq) — a late duplicate re-sends the cached response instead
    of re-applying the write — and the client discards responses whose tag
    doesn't match its outstanding request."""

    def setup(self, sched):
        self.store = {"v": 0}
        self.seq = {}
        applied = {}  # src -> (max applied seq, its cached response)

        def server():
            while True:
                msg = yield Recv()
                kind, arg, seq = msg.payload
                last_seq, last_resp = applied.get(msg.src, (0, None))
                if seq <= last_seq:
                    # stale duplicate (clients have one outstanding request,
                    # seqs strictly increase): do NOT re-apply; re-respond
                    yield Send(msg.src, (seq, last_resp))
                    continue
                if kind == "write":
                    self.store["v"] = arg
                    resp = 0
                else:
                    resp = self.store["v"]
                applied[msg.src] = (seq, resp)
                yield Send(msg.src, (seq, resp))

        sched.spawn("server", server(), daemon=True)

    def perform(self, pid, cmd, arg):
        from qsm_tpu.models.register import READ

        seq = self.seq[pid] = self.seq.get(pid, 0) + 1
        yield Send("server", ("read" if cmd == READ else "write", arg, seq))
        while True:
            msg = yield Recv()
            got_seq, result = msg.payload
            if got_seq == seq:
                return result  # stale duplicate responses are discarded


def test_duplication_breaks_untagged_protocol_and_tagging_fixes_it():
    """A duplicated request yields a second response that the naive client
    misattributes to its NEXT operation — a real protocol bug the checker
    must catch; the seq-tagged client is immune."""
    faults = FaultPlan(p_duplicate=0.25)
    cfg = PropertyConfig(n_trials=60, n_pids=2, max_ops=10, seed=22,
                         faults=faults)
    res = prop_concurrent(SPEC, AtomicRegisterSUT(), cfg)
    assert not res.ok, "response misattribution went undetected"
    res = prop_concurrent(SPEC, TaggedRegisterSUT(), cfg)
    assert res.ok, res.counterexample


def test_prop_concurrent_with_pid_crash():
    faults = FaultPlan(crash_at={"client:0": 2})
    cfg = PropertyConfig(n_trials=40, n_pids=2, max_ops=10, seed=23,
                         faults=faults)
    res = prop_concurrent(SPEC, AtomicRegisterSUT(), cfg)
    assert res.ok, res.counterexample


def test_racy_cas_still_caught_under_faults():
    """Faults must not mask real bugs."""
    spec = CasSpec()
    faults = FaultPlan(p_drop=0.05, protected=set())
    cfg = PropertyConfig(n_trials=80, n_pids=8, max_ops=32, seed=5,
                         faults=faults)
    res = prop_concurrent(spec, RacyCasSUT(spec), cfg)
    assert not res.ok


def test_backend_parity_on_faulty_histories():
    from conftest import assert_backend_parity

    spec = CasSpec()
    faults = FaultPlan(p_drop=0.1, protected=set())
    hists = []
    for seed in range(30):
        prog = generate_program(spec, seed=seed, n_pids=4, max_ops=10)
        for sut in (AtomicCasSUT(spec), RacyCasSUT(spec)):
            hists.append(run_concurrent(sut, prog, seed=f"f{seed}",
                                        faults=faults))
    assert any(h.n_pending for h in hists), "fault sample vacuous"
    assert_backend_parity(spec, hists, JaxTPU(spec),
                          expect_violations=False)


def test_fault_plan_doc_roundtrip():
    fp = FaultPlan(p_drop=0.1, p_duplicate=0.2,
                   partitions=[{"a", "b"}], crash_at={"client:0": 3},
                   protected={"server"})
    fp2 = faults_from_doc(faults_to_doc(fp))
    assert (fp2.p_drop, fp2.p_duplicate) == (0.1, 0.2)
    assert fp2.partitions == [{"a", "b"}]
    assert fp2.crash_at == {"client:0": 3}
    assert fp2.protected == {"server"}
    assert faults_from_doc(faults_to_doc(None)) is None


def test_delay_fault_determinism_and_progress():
    """Delays are seeded and replayable; with every message delayed the run
    still completes (an all-held pool delivers early rather than wedging)."""
    faults = FaultPlan(p_delay=1.0, delay_steps=4)
    prog = generate_program(SPEC, seed=3, n_pids=2, max_ops=8)
    h1 = run_concurrent(AtomicRegisterSUT(), prog, seed="d1", faults=faults)
    h2 = run_concurrent(AtomicRegisterSUT(), prog, seed="d1", faults=faults)
    assert h1.fingerprint() == h2.fingerprint()
    assert len(h1) == len(prog)  # every op completed


def test_delay_reorders_beyond_pool_reordering():
    """A delayed message must arrive later than messages sent AFTER it was
    already poolable — over enough seeds the delayed histories must differ
    from the fault-free ones for the same program."""
    prog = generate_program(SPEC, seed=9, n_pids=2, max_ops=10)
    plain = {run_concurrent(AtomicRegisterSUT(), prog,
                            seed=f"s{i}").fingerprint() for i in range(20)}
    delayed = {run_concurrent(
        AtomicRegisterSUT(), prog, seed=f"s{i}",
        faults=FaultPlan(p_delay=0.5, delay_steps=6)).fingerprint()
        for i in range(20)}
    assert delayed - plain, "delay produced no new interleavings"


def test_delay_induced_pending_flows_through_complete_prune():
    """A response delayed past the client's crash leaves a pending op; the
    checker must complete/prune it and the atomic SUT must stay
    linearizable (SURVEY.md §3.2 + §5 fault row)."""
    from qsm_tpu import Verdict, check_one

    faults = FaultPlan(p_delay=1.0, delay_steps=8,
                       crash_at={"client:0": 1})
    prog = generate_program(SPEC, seed=4, n_pids=2, max_ops=8)
    hs = [run_concurrent(AtomicRegisterSUT(), prog, seed=f"dc{i}",
                         faults=faults) for i in range(10)]
    assert any(h.n_pending for h in hs), "no delay-induced pending op"
    for h in hs:
        assert check_one(WingGongCPU(), SPEC, h) == Verdict.LINEARIZABLE
    # and the device backend agrees on the faulty sample
    from conftest import assert_backend_parity
    assert_backend_parity(SPEC, hs, JaxTPU(SPEC), expect_violations=False)


def test_fault_plan_delay_doc_roundtrip():
    fp = FaultPlan(p_delay=0.3, delay_steps=7)
    fp2 = faults_from_doc(faults_to_doc(fp))
    assert (fp2.p_delay, fp2.delay_steps) == (0.3, 7)
    # pre-round-2 docs lack the delay keys: defaults apply
    doc = faults_to_doc(FaultPlan(p_drop=0.1))
    del doc["p_delay"], doc["delay_steps"]
    fp3 = faults_from_doc(doc)
    assert (fp3.p_delay, fp3.delay_steps) == (0.0, 3)


def test_partition_cli_flag_and_wedge_semantics(capsys):
    """--partition drops boundary-crossing messages deterministically:
    the partitioned replicated register wedges (pending ops), stays
    checkable, and the printed replay hint round-trips the flag."""
    from qsm_tpu.utils.cli import main

    rc = main(["run", "--model", "register", "--impl", "replicated",
               "--trials", "20", "--partition", "replica:1",
               "--backend", "cpu"])
    out = capsys.readouterr().out
    # a full partition of one replica can't produce a violation (writes
    # wedge to pending, which the checker prunes) — the run passes
    assert rc == 0, out


def test_partition_flag_parses_to_plan():
    import argparse

    from qsm_tpu.utils.cli import _faults_from_args

    ns = argparse.Namespace(p_drop=0.0, p_duplicate=0.0, p_delay=0.0,
                            delay_steps=3, crash_at=[],
                            partition=["a,b", "c"])
    fp = _faults_from_args(ns)
    assert fp is not None and fp.partitions == [{"a", "b"}, {"c"}]
    assert fp.is_deterministic()


def test_partition_replay_hint_round_trips(capsys):
    """A violation found with a --partition flag must print a replay line
    carrying it (a pasted command without it replays a different fault
    plan).  The group names a process that never exchanges messages, so
    the plan is behaviorally inert and the racy register still fails —
    the assertion is about the HINT, not the partition's effect."""
    from qsm_tpu.utils.cli import main

    rc = main(["run", "--model", "register", "--impl", "racy",
               "--trials", "60", "--partition", "bystander",
               "--backend", "cpu"])
    out = capsys.readouterr().out
    assert rc == 1, out
    replay_line = [ln for ln in out.splitlines()
                   if ln.startswith("replay:")][0]
    assert "--partition bystander" in replay_line


def test_partition_explorable():
    """Partitions are deterministic, so explore accepts them; the
    partitioned tree is exhaustively walked."""
    from qsm_tpu.models.register import ReplicatedRegisterSUT
    from qsm_tpu.sched.systematic import explore_program

    prog = generate_program(SPEC, seed=2, n_pids=2, max_ops=3)
    res = explore_program(lambda: ReplicatedRegisterSUT(), prog, SPEC,
                          faults=FaultPlan(partitions=[{"replica:1"}]),
                          max_schedules=20_000)
    assert res.exhausted
