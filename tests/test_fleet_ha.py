"""Router HA + peer-to-peer anti-entropy + bounded catch-up (ISSUE 13):
the lease state machine (term monotonicity, one-way supersession),
split-brain refusal (exactly one of two routers serves; the stale one
SHEDs ``router_superseded``), client multi-address failover bit-identical
to a single router, gossip convergence with NO router alive, row-level
segment subsumption (a compacted segment whose rows a peer holds never
re-ships), and the capped fold-forward absorbed record."""

from __future__ import annotations

import json
import os
import time

import pytest

from qsm_tpu.fleet.gossip import GossipAgent
from qsm_tpu.fleet.lease import (FileLeaseStore, Lease, TCP_SCHEME,
                                 TcpLeaseStore)
from qsm_tpu.fleet.replog import SegmentedLog
from qsm_tpu.fleet.router import FleetRouter
from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
from qsm_tpu.obs import load_dump, load_events
from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
from qsm_tpu.resilience.policy import preset
from qsm_tpu.serve.cache import VerdictCache, fingerprint_key
from qsm_tpu.serve.client import CheckClient
from qsm_tpu.serve.protocol import VERDICT_NAMES
from qsm_tpu.serve.server import CheckServer

SPEC = CasSpec()
TTL = 0.5


@pytest.fixture(scope="module")
def corpus():
    from qsm_tpu.utils.corpus import build_corpus

    return build_corpus(SPEC, (AtomicCasSUT, RacyCasSUT), n=10,
                        n_pids=4, max_ops=10, seed_base=0,
                        seed_prefix="fleet_ha")


@pytest.fixture(scope="module")
def expected(corpus):
    oracle = WingGongCPU(memo=True)
    return [VERDICT_NAMES[int(v)]
            for v in oracle.check_histories(SPEC, corpus)]


def _nodes(tmp_path, n=2, seal_rows=8):
    return [CheckServer(node_id=f"n{i}",
                        replog_dir=str(tmp_path / f"replog{i}"),
                        replog_seal_rows=seal_rows,
                        flush_s=0.005).start() for i in range(n)]


def _router(nodes, node_id="router", lease_path=None, **kw):
    kw.setdefault("policy", preset("fleet-route").with_(timeout_s=3.0))
    kw.setdefault("probe_policy",
                  preset("fleet-probe").with_(timeout_s=1.0))
    kw.setdefault("heartbeat_s", 0.2)
    kw.setdefault("anti_entropy_s", 0.0)
    if lease_path is not None:
        kw.setdefault("lease_ttl_s", TTL)
        kw.setdefault("ha_beat_s", 0.0)  # tests drive beats by hand
    return FleetRouter([(s.node_id, s.address) for s in nodes],
                       node_id=node_id, lease_path=lease_path,
                       **kw).start()


# --- the lease itself ------------------------------------------------------

@pytest.fixture(params=["file", "tcp"])
def lease_store(request, tmp_path):
    """BOTH lease stores (ISSUE 18): the raw record path (file) and a
    lease-hosting node's ``tcp://`` address whose own FileLeaseStore
    backs the SAME record file.  Every term/expiry pin must hold
    identically over both — the TCP store is a transport, never a
    different arbitration."""
    path = str(tmp_path / "lease.json")
    if request.param == "file":
        yield path, path
    else:
        host = CheckServer(lease_path=path).start()
        try:
            yield TCP_SCHEME + host.address, path
        finally:
            host.stop()


def test_lease_terms_are_monotonic_and_one_way(lease_store):
    target, path = lease_store
    a = Lease(target, holder="rA", ttl_s=0.3)
    b = Lease(target, holder="rB", ttl_s=0.3)
    rec = a.acquire()
    assert rec["term"] == 1 and rec["holder"] == "rA"
    assert b.acquire() is None          # live foreign term: refused
    assert a.renew(1)["term"] == 1      # renew keeps the term
    assert a.acquire()["term"] == 1     # re-acquire of a live own
    #                                     record is a renew, not a bump
    time.sleep(0.35)
    assert a.renew(1) is None           # expired: one-way, never
    #                                     resurrected under term 1
    rec = b.acquire()
    assert rec["term"] == 2 and rec["holder"] == "rB"
    assert a.acquire() is None          # rA must now WIN a later term
    assert a.renew(1) is None
    time.sleep(0.35)
    assert a.acquire()["term"] == 3     # ...which it can, after expiry
    # a garbled record reads as expired, never crashes (written to the
    # BACKING file — over TCP that is the lease host's own record)
    with open(path, "w") as f:
        f.write("{torn")
    assert Lease.expired(b.read())
    assert b.acquire()["term"] == 1     # fresh history after the wipe


def test_lease_lock_contention_loses_the_beat_never_blocks(tmp_path):
    """The write-transaction lock is kernel-owned flock: a held lock
    makes a competing transaction LOSE its beat (non-blocking refusal,
    retried next beat), and releasing it — which a SIGKILLed holder
    does implicitly, fd teardown being kernel-side — restores
    acquirability with no stale state to break."""
    import fcntl

    path = str(tmp_path / "lease.json")
    a = Lease(path, holder="rA", ttl_s=0.3)
    # a competitor mid-transaction: flock held on the lock file
    fd = os.open(a._lock_path, os.O_CREAT | os.O_RDWR)
    fcntl.flock(fd, fcntl.LOCK_EX)
    assert a.acquire() is None      # lost the beat, did not block
    os.close(fd)                    # the holder dies: lock evaporates
    assert a.acquire()["term"] == 1


def test_tcp_lease_store_transport_loss_is_a_lost_beat():
    """A TcpLeaseStore whose host is unreachable loses every beat —
    None from each transaction, never an exception, never a block
    (the exact contract a lost flock beat has)."""
    dead = Lease("tcp://127.0.0.1:1", holder="rA", ttl_s=0.3)
    assert isinstance(dead.store, TcpLeaseStore)
    assert dead.acquire() is None
    assert dead.renew(1) is None
    assert dead.read() is None
    dead.release()                  # a no-op, not a crash
    assert dead.path == "tcp://127.0.0.1:1"


def test_make_store_dispatches_on_scheme(tmp_path):
    assert isinstance(Lease(str(tmp_path / "l.json"), holder="x").store,
                      FileLeaseStore)
    assert isinstance(Lease("tcp://h:1", holder="x").store,
                      TcpLeaseStore)
    # a pre-built store passes through (routers handed a shared store)
    st = FileLeaseStore(str(tmp_path / "l2.json"))
    assert Lease(st, holder="x").store is st


def test_lease_fault_site_demotes_never_serves_stale(tmp_path,
                                                     monkeypatch):
    """The ``lease`` fault site (satellite of ISSUE 18): an injected
    failure at renew is a LOST BEAT — the active demotes (one-way per
    term) instead of serving under a term it cannot prove live, the
    loss is counted, and the beat thread survives."""
    nodes = _nodes(tmp_path, n=1)
    lease = str(tmp_path / "lease.json")
    ra = _router(nodes, node_id="rA", lease_path=lease)
    try:
        assert ra.ha_role == "active" and ra._active_now()
        monkeypatch.setenv("QSM_TPU_FAULTS", "raise:lease")
        ra.ha_beat()
        assert ra.ha_role == "superseded"
        assert not ra._active_now()
        assert ra.lease_faults >= 1
        assert ra.stats()["lease"]["lease_faults"] >= 1
        from qsm_tpu.resilience.faults import fired_snapshot

        assert fired_snapshot().get("lease", 0) >= 1
        monkeypatch.delenv("QSM_TPU_FAULTS")
        # re-entry only by WINNING a later term (the record expires,
        # the gated path takes term 2)
        time.sleep(TTL + TTL * 0.5 + 0.1)
        ra.ha_beat()
        assert ra.ha_role == "active" and ra.term == 2
    finally:
        ra.stop()
        for s in nodes:
            s.stop()


# --- split brain -----------------------------------------------------------

def test_split_brain_exactly_one_router_serves(tmp_path, corpus,
                                               expected, lease_store):
    """THE split-brain pin: two routers, one lease — over BOTH stores.
    After a takeover the stale-term router answers SHED with a
    ``router_superseded`` block — never a verdict — while the new
    active serves under the bumped term."""
    nodes = _nodes(tmp_path, n=2)
    lease, _path = lease_store
    ra = _router(nodes, node_id="rA", lease_path=lease)
    rb = _router(nodes, node_id="rB", lease_path=lease)
    try:
        assert ra.ha_role == "active" and ra.term == 1
        assert rb.ha_role == "standby" and rb.term == 0
        with CheckClient(ra.address, timeout_s=30.0) as c:
            res = c.check("cas", corpus)
            assert res["verdicts"] == expected
            assert res["term"] == 1 and res["node"] == "rA"
        # the standby refuses while the active's term is live
        with CheckClient(rb.address, timeout_s=30.0) as c:
            res = c.check("cas", corpus[:1])
            assert res.get("shed") and res["reason"] == "router_standby"
            assert res["router"]["role"] == "standby"
        # rA wedges (its beats stop); the lease expires; rB's gated
        # promotion path takes term 2 after its own node health probe
        time.sleep(TTL + TTL * 0.5 + 0.1)
        rb.ha_beat()
        assert rb.ha_role == "active" and rb.term == 2
        assert rb.takeovers == 1
        # the stale-term router can never answer a verdict again:
        # its own expiry check refuses BEFORE it even observes term 2
        with CheckClient(ra.address, timeout_s=30.0) as c:
            res = c.check("cas", corpus)
            assert not res.get("ok") and res.get("shed")
            assert res["reason"] == "router_superseded"
            assert res["router"]["term"] == 1
            assert res["router"]["active_term"] == 2
            assert res["router"]["active_holder"] == "rB"
        ra.ha_beat()
        assert ra.ha_role == "superseded"
        # exactly one serves: the new active answers under term 2
        with CheckClient(rb.address, timeout_s=30.0) as c:
            res = c.check("cas", corpus)
            assert res["verdicts"] == expected
            assert res["term"] == 2 and res["node"] == "rB"
    finally:
        ra.stop()
        rb.stop()
        for s in nodes:
            s.stop()


def test_standby_promotion_requires_node_health(tmp_path):
    """A standby that cannot reach ANY fleet node must not take the
    term (a lease expiry observed from behind a partition is not a
    mandate to serve everything from its own ladder)."""
    dead = str(tmp_path / "nowhere.sock")
    lease = str(tmp_path / "lease.json")
    rb = FleetRouter([("n0", dead)], node_id="rB", lease_path=lease,
                     lease_ttl_s=TTL, ha_beat_s=0.0, heartbeat_s=30.0,
                     anti_entropy_s=0.0,
                     probe_policy=preset("fleet-probe").with_(
                         timeout_s=0.3)).start()
    try:
        beat = rb.ha_beat()
        assert rb.ha_role == "standby" and rb.term == 0
        assert beat.get("blocked") == "no reachable node"
    finally:
        rb.stop()


def test_takeover_emits_span_and_flight_dump(tmp_path, corpus):
    """The takeover acceptance artifacts: a ``router.takeover`` span
    carrying the superseded term (what ``qsm-tpu trace`` renders) and
    a flight dump with the ``router_takeover`` reason."""
    nodes = _nodes(tmp_path, n=1)
    lease = str(tmp_path / "lease.json")
    trace_log = str(tmp_path / "rb_trace.jsonl")
    flight_dir = str(tmp_path / "rb_flight")
    ra = _router(nodes, node_id="rA", lease_path=lease)
    rb = _router(nodes, node_id="rB", lease_path=lease,
                 trace_log=trace_log, flight_dir=flight_dir)
    try:
        assert ra.ha_role == "active"
        time.sleep(TTL + TTL * 0.5 + 0.1)
        rb.ha_beat()
        assert rb.ha_role == "active" and rb.term == 2
        rb.obs.tracer.close()
        events = [e for e in load_events(trace_log)
                  if e.get("name") == "router.takeover"]
        assert len(events) == 1
        at = events[0]["attrs"]
        assert at["term"] == 2 and at["superseded_term"] == 1
        assert at["superseded_holder"] == "rA"
        dumps = [f for f in sorted(os.listdir(flight_dir))
                 if "router_takeover" in f]
        assert dumps, os.listdir(flight_dir)
        dump = load_dump(os.path.join(flight_dir, dumps[0]))
        assert dump["reason"] == "router_takeover"
    finally:
        ra.stop()
        rb.stop()
        for s in nodes:
            s.stop()


def test_clean_shutdown_hands_the_term_over_immediately(tmp_path,
                                                        lease_store):
    """stop() on the active releases the lease as an expired TOMBSTONE
    (over BOTH stores): the standby's next beat promotes without
    waiting out the TTL, and the term still advances (monotonic across
    clean handovers — the same term must never come from two
    brains)."""
    nodes = _nodes(tmp_path, n=1)
    lease, _path = lease_store
    ra = _router(nodes, node_id="rA", lease_path=lease)
    rb = _router(nodes, node_id="rB", lease_path=lease)
    try:
        assert ra.ha_role == "active" and ra.term == 1
        ra.stop()
        rec = rb.lease.read()
        assert rec is not None and rec.get("released")  # not unlinked
        rb.ha_beat()
        assert rb.ha_role == "active" and rb.term == 2
    finally:
        ra.stop()
        rb.stop()
        for s in nodes:
            s.stop()


# --- client failover -------------------------------------------------------

def test_client_failover_bit_identical_to_single_router(tmp_path,
                                                        corpus,
                                                        expected):
    """``--addr a,b``: the client rides a router death mid-sequence
    onto the other address; every verdict is bit-identical to the
    single-router answer (idempotent ops, fingerprint-banked
    verdicts)."""
    nodes = _nodes(tmp_path, n=2)
    ra = _router(nodes, node_id="rA")
    rb = _router(nodes, node_id="rB")
    try:
        with CheckClient(f"{ra.address},{rb.address}",
                         timeout_s=30.0) as c:
            first = c.check("cas", corpus)
            assert first["verdicts"] == expected
            assert first["node"] == "rA"
            ra.stop()  # the door the client is connected to dies
            # let rA's connection reader notice the stop flag and
            # close (it polls every 0.5 s) — a half-stopped in-process
            # router answering one last buffered request is fine in
            # production but nondeterministic here (the PR 12 lesson)
            time.sleep(0.7)
            second = c.check("cas", corpus)
            assert second["verdicts"] == expected
            assert second["node"] == "rB"
            assert c.failovers >= 1
            # the answers are the single-router answers, bit-identical
            assert second["verdicts"] == first["verdicts"]
    finally:
        ra.stop()
        rb.stop()
        for s in nodes:
            s.stop()


def test_client_hops_off_standby_shed(tmp_path, corpus, expected):
    """A standby listed first is transparent: its ``router_standby``
    SHED makes the client hop to the active, not surface the SHED."""
    nodes = _nodes(tmp_path, n=1)
    lease = str(tmp_path / "lease.json")
    ra = _router(nodes, node_id="rA", lease_path=lease)
    rb = _router(nodes, node_id="rB", lease_path=lease)
    try:
        assert rb.ha_role == "standby"
        with CheckClient(f"{rb.address},{ra.address}",
                         timeout_s=30.0) as c:
            res = c.check("cas", corpus)
            assert res["ok"] and res["verdicts"] == expected
            assert res["node"] == "rA" and res["term"] == 1
            assert c.failovers >= 1
        assert rb.ha_sheds >= 1  # the standby did refuse, honestly
    finally:
        ra.stop()
        rb.stop()
        for s in nodes:
            s.stop()


def test_client_failover_is_bounded_under_total_partition(tmp_path,
                                                          monkeypatch,
                                                          corpus):
    """The ``router`` fault site: with EVERY client→router exchange
    partitioned, the client raises ConnectionError after its bounded
    attempts — never a wrong answer, never a spin."""
    nodes = _nodes(tmp_path, n=1)
    ra = _router(nodes, node_id="rA")
    try:
        with CheckClient(ra.address, timeout_s=10.0) as c:
            monkeypatch.setenv("QSM_TPU_FAULTS", "partition:router")
            with pytest.raises(ConnectionError):
                c.check("cas", corpus[:1])
            # the site really fired (drill accounting) — checked while
            # the env var is still set: fired_snapshot() answers {}
            # once the plane is off
            from qsm_tpu.resilience.faults import fired_snapshot

            assert fired_snapshot().get("router", 0) >= 1
            monkeypatch.delenv("QSM_TPU_FAULTS")
        with CheckClient(ra.address, timeout_s=10.0) as c:
            assert c.check("cas", corpus[:1])["ok"]
    finally:
        ra.stop()
        for s in nodes:
            s.stop()


def test_node_link_multi_address_failover(tmp_path):
    from qsm_tpu.fleet.router import NodeLink

    srv = CheckServer(node_id="n0").start()
    try:
        dead = str(tmp_path / "nowhere.sock")
        link = NodeLink("n0", f"{dead},{srv.address}")
        resp = link.request({"op": "stats"}, timeout_s=5.0)
        assert resp["ok"] and resp["node"] == "n0"
    finally:
        srv.stop()


# --- gossip: convergence with the router dead ------------------------------

def _wire_gossip(servers, fanout=None):
    for s in servers:
        peers = [(o.node_id, o.address) for o in servers if o is not s]
        s.gossip = GossipAgent(s.node_id, s.replog, s.cache,
                               peers=peers,
                               fanout=fanout or len(peers),
                               interval_s=0.0)  # beats driven by hand
    return servers


def test_gossip_converges_with_no_router_alive(tmp_path, corpus,
                                               expected):
    """The de-hubbing pin: traffic banked on its owner nodes converges
    to EVERY node's replog through node-to-node gossip alone — the
    router is stopped before the first beat — within a bounded number
    of beats (full fan-out: <= 2 rounds)."""
    nodes = _nodes(tmp_path, n=3, seal_rows=1)
    router = _router(nodes, node_id="rA")
    with CheckClient(router.address, timeout_s=60.0) as c:
        res = c.check("cas", corpus)
        assert res["verdicts"] == expected
    router.stop()  # the router is DEAD for everything that follows
    try:
        for s in nodes:
            s.cache.flush()
        _wire_gossip(nodes)
        for _round in range(2):  # the pinned convergence bound
            for s in nodes:
                s.gossip.sweep()
        digests = [s.replog.digests() for s in nodes]
        assert digests[0] == digests[1] == digests[2]
        assert digests[0], "convergence must be of a non-empty set"
        # every node can now answer the whole corpus from its bank
        for s in nodes:
            for h, want in zip(corpus, expected):
                e = s.cache.get(fingerprint_key(SPEC, h))
                assert e is not None
                assert VERDICT_NAMES[e.verdict] == want
        # a further beat moves nothing (quiescent)
        for s in nodes:
            r = s.gossip.sweep()
            assert r["pulled"] == r["pushed"] == 0
    finally:
        for s in nodes:
            s.stop()


def test_gossip_peer_fault_is_excluded_and_bounded(tmp_path):
    """A dead peer costs one bounded connect failure per beat and is
    excluded for the rest of that sweep — the beat completes and the
    live peer still converges."""
    nodes = _nodes(tmp_path, n=2, seal_rows=1)
    try:
        nodes[0].cache.put_many([(f"k{i}", 1, None) for i in range(4)])
        for s in nodes:
            peers = [(o.node_id, o.address) for o in nodes if o is not s]
            peers.append(("ghost", str(tmp_path / "nowhere.sock")))
            s.gossip = GossipAgent(
                s.node_id, s.replog, s.cache, peers=peers, fanout=2,
                interval_s=0.0,
                policy=preset("gossip").with_(timeout_s=1.0))
        r = nodes[1].gossip.sweep()
        assert r["peers"] == 2           # both contacted, one dead
        assert nodes[1].gossip.peer_faults == 1
        assert nodes[0].replog.digests() == nodes[1].replog.digests()
    finally:
        for s in nodes:
            s.stop()


def test_gossip_peers_op_wires_a_running_node(tmp_path):
    """The ``gossip.peers`` op (what ``qsm-tpu fleet`` drives):
    configures a running node's peer set + interval, idempotently;
    refused without a replog."""
    import socket as _socket

    from qsm_tpu.serve.protocol import LineChannel, connect, send_doc

    s0 = CheckServer(node_id="n0",
                     replog_dir=str(tmp_path / "r0")).start()
    s1 = CheckServer(node_id="n1").start()  # no replog
    try:
        sock = connect(s0.address, timeout_s=5.0)
        try:
            send_doc(sock, {"op": "gossip.peers",
                            "peers": [["n1", s1.address]],
                            "interval_s": 0.0})
            resp = json.loads(LineChannel(sock).read_line(timeout_s=5.0))
        finally:
            sock.close()
        assert resp["ok"] and resp["peers"] == ["n1"]
        assert s0.gossip is not None
        assert s0.stats()["gossip"]["peers"] == ["n1"]
        sock = connect(s1.address, timeout_s=5.0)
        try:
            send_doc(sock, {"op": "gossip.peers", "peers": []})
            resp = json.loads(LineChannel(sock).read_line(timeout_s=5.0))
        finally:
            sock.close()
        assert not resp["ok"] and "replog" in resp["error"]
    finally:
        s0.stop()
        s1.stop()


# --- bounded catch-up: row-level subsumption -------------------------------

def test_subsumed_segment_never_reshipped_after_compaction(tmp_path):
    """THE subsumption pin: a compacted segment (new identity, old
    rows) whose rows a peer already holds is marked subsumed on the
    peer — zero row lines cross the wire, the name never re-offers,
    and the record survives a restart."""
    a = CheckServer(node_id="a", replog_dir=str(tmp_path / "ra"),
                    replog_seal_rows=1).start()
    b = CheckServer(node_id="b", replog_dir=str(tmp_path / "rb"),
                    replog_seal_rows=1).start()
    try:
        a.cache.put_many([(f"k{i}", i % 2, None) for i in range(12)])
        _wire_gossip([a, b])
        b.gossip.sweep()  # b replicates everything a holds
        assert a.replog.digests() == b.replog.digests()
        # compaction mints a NEW identity for rows b already holds
        a.replog.compact(a.cache._live_lines())
        r = b.gossip.sweep()
        assert r["subsumed"] >= 1, r
        assert r["pulled"] == 0 and r["rows"] == 0, r
        snap = b.replog.snapshot()
        assert snap["subsumed_segments"] >= 1
        assert snap["subsumptions"] >= 1
        assert b.replog.missing(a.replog.digests()) == []
        # adopting a subsumed segment later is a no-op (idempotent)
        (name,) = [n for n in a.replog.digests()
                   if n in b.replog.covered()]
        fp, lines = a.replog.read_segment(name)
        assert b.replog.adopt(name, fp, lines) == []
        # the record is durable: a restarted replog still covers it
        b2 = SegmentedLog(str(tmp_path / "rb"), node_id="b",
                          seal_rows=1)
        assert name in b2.covered()
        assert b2.missing(a.replog.digests()) == []
    finally:
        a.stop()
        b.stop()


def test_router_sweep_subsumes_instead_of_shipping(tmp_path, corpus,
                                                   expected):
    """The router-driven anti-entropy path takes the same shortcut:
    after compaction on one node, the sweep records subsumption on the
    peer instead of re-shipping the rows."""
    nodes = _nodes(tmp_path, n=2, seal_rows=1)
    router = _router(nodes, node_id="rA")
    try:
        with CheckClient(router.address, timeout_s=60.0) as c:
            assert c.check("cas", corpus)["verdicts"] == expected
        for s in nodes:
            s.cache.flush()
        for _ in range(8):
            if router.anti_entropy_sweep()["segments_shipped"] == 0:
                break
        assert nodes[0].replog.digests() == nodes[1].replog.digests()
        nodes[0].replog.compact(nodes[0].cache._live_lines())
        res = router.anti_entropy_sweep()
        assert res["segments_subsumed"] >= 1, res
        assert res["segments_shipped"] == 0, res
        assert nodes[1].replog.missing(
            nodes[0].replog.digests()) == []
        assert router.stats()["anti_entropy"]["segments_subsumed"] >= 1
    finally:
        router.stop()
        for s in nodes:
            s.stop()


def test_absorbed_record_is_capped_with_fold_forward(tmp_path):
    """The PR 12 REMAINING fix: 100 compactions leave the absorbed
    record O(cap) on disk — oldest names fold forward (dropped from
    the record, still covered by the live set via subsumption) and
    the persisted next_seq keeps names collision-free forever."""
    cap = 8
    log = SegmentedLog(str(tmp_path / "n"), node_id="n", seal_rows=1,
                       absorbed_cap=cap)
    cache = VerdictCache(max_entries=4096, store=log)
    sizes = []
    for i in range(100):
        cache.put(f"k{i}", 1, None)
        cache.flush()
        log.compact(cache._live_lines())
        assert len(log.absorbed()) <= cap
        sizes.append(os.path.getsize(
            os.path.join(str(tmp_path / "n"), "absorbed.json")))
    # O(cap): the record's disk footprint stops growing once capped
    assert max(sizes[cap + 2:]) <= sizes[cap + 1] * 2
    assert len(log.absorbed()) == cap
    # fold-forward kept the NEWEST names
    seqs = sorted(int(n.split("-")[2]) for n in log.absorbed())
    assert seqs[0] >= 100 - cap
    # next_seq survives the forgetting: a restart never reuses a seq
    log2 = SegmentedLog(str(tmp_path / "n"), node_id="n", seal_rows=1,
                        absorbed_cap=cap)
    assert log2._next_seq == log._next_seq
    assert log2._next_seq > 100
    # the subsumed record is capped by the same bound
    for i in range(2 * cap):
        fp = "%012x" % i
        log2.note_subsumed(f"seg-x-{i:06d}-{fp}.jsonl", fp)
    assert len(log2.subsumed()) <= cap


# --- the stats surface -----------------------------------------------------

def test_stats_fleet_renders_lease_table(tmp_path, corpus):
    from qsm_tpu.utils.cli import _render_stats_fleet

    nodes = _nodes(tmp_path, n=1)
    lease = str(tmp_path / "lease.json")
    ra = _router(nodes, node_id="rA", lease_path=lease)
    rb = _router(nodes, node_id="rB", lease_path=lease)
    try:
        text = _render_stats_fleet(ra.stats())
        assert "rA [ACTIVE] term 1" in text
        assert "expires_in" in text
        text = _render_stats_fleet(rb.stats())
        assert "rB [STANDBY] term 0" in text
        assert "active: rA term 1" in text
        # leaseless router renders the off line (no HA standby)
        r2 = _router(nodes, node_id="solo")
        try:
            assert "lease: off" in _render_stats_fleet(r2.stats())
        finally:
            r2.stop()
    finally:
        ra.stop()
        rb.stop()
        for s in nodes:
            s.stop()
