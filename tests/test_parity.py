"""Cross-backend parity: ``JaxTPU(h) == WingGongCPU(h)`` for every history
the generator/scheduler ever produces, plus golden hand-written cases
(SURVEY.md §4: 'a cross-backend parity suite ... property-tested').

Runs on the virtual CPU mesh in CI (conftest forces JAX_PLATFORMS=cpu);
the same code path runs on the real chip in bench.py.
"""

import numpy as np
import pytest

from qsm_tpu import (History, Op, Verdict, WingGongCPU, generate_program,
                     overlapping_history, run_concurrent, sequential_history)
from qsm_tpu.ops.jax_kernel import JaxTPU
from qsm_tpu.models.register import (READ, WRITE, AtomicRegisterSUT,
                                     RacyCachedRegisterSUT,
                                     ReplicatedRegisterSUT, RegisterSpec)

SPEC = RegisterSpec(n_values=5)
ORACLE = WingGongCPU()


@pytest.fixture(scope="module")
def tpu():
    return JaxTPU(SPEC)


GOLDEN = [
    History([]),
    sequential_history([(0, WRITE, 3, 0), (0, READ, 0, 3)]),
    sequential_history([(0, WRITE, 3, 0), (1, READ, 0, 0)]),  # stale
    overlapping_history([(0, WRITE, 3, 0, 0, 5), (1, READ, 0, 0, 1, 2)]),
    overlapping_history([(0, WRITE, 3, 0, 0, 5), (1, READ, 0, 3, 1, 2)]),
    overlapping_history([(0, WRITE, 3, 0, 0, 5), (1, READ, 0, 2, 1, 2)]),
    # new/old inversion
    overlapping_history([(0, WRITE, 3, 0, 0, 7), (1, READ, 0, 3, 1, 2),
                         (1, READ, 0, 0, 3, 4)]),
    # pending write completed-or-pruned
    History([Op(0, WRITE, 1, -1, 0, 1 << 30),
             Op(1, READ, 0, 1, 2, 3)]),
    History([Op(0, WRITE, 1, -1, 0, 1 << 30),
             Op(1, READ, 0, 4, 2, 3)]),
]


def test_golden_parity(tpu):
    cpu = ORACLE.check_histories(SPEC, GOLDEN)
    dev = tpu.check_histories(SPEC, GOLDEN)
    assert list(cpu) == list(dev), (list(cpu), list(dev))
    # and the expected verdicts themselves
    assert list(cpu) == [1, 1, 0, 1, 1, 0, 0, 1, 0]


@pytest.mark.parametrize("sut_cls,n_pids,max_ops", [
    (AtomicRegisterSUT, 2, 12),
    (AtomicRegisterSUT, 4, 20),
    (RacyCachedRegisterSUT, 2, 12),
    (RacyCachedRegisterSUT, 3, 16),
    (ReplicatedRegisterSUT, 2, 12),
    (ReplicatedRegisterSUT, 4, 20),
])
def test_scheduler_history_parity(tpu, sut_cls, n_pids, max_ops):
    hists = []
    for seed in range(60):  # seeds 44/53 give ReplicatedRegister violations
        prog = generate_program(SPEC, seed=seed, n_pids=n_pids,
                                max_ops=max_ops)
        hists.append(run_concurrent(sut_cls(), prog, seed=f"p{seed}"))
    from conftest import assert_backend_parity

    cpu = assert_backend_parity(
        SPEC, hists, tpu, oracle=ORACLE,
        expect_violations=sut_cls is not AtomicRegisterSUT)
    if sut_cls is AtomicRegisterSUT:
        assert (cpu == Verdict.LINEARIZABLE).all()


def test_batch_padding_consistency(tpu):
    """Verdicts must not depend on batch size / padding position."""
    hists = GOLDEN[1:4]
    singles = [int(tpu.check_histories(SPEC, [h])[0]) for h in hists]
    batched = list(tpu.check_histories(SPEC, hists))
    assert singles == batched


def test_budget_exceeded_resolved_not_guessed():
    # rescue disabled: an exhausted budget must surface as BUDGET_EXCEEDED,
    # never a guessed verdict
    tiny = JaxTPU(SPEC, budget=3, rescue_budget=0, mid_budget=0)
    h = sequential_history([(0, WRITE, i % 5, 0) for i in range(10)])
    v = tiny.check_histories(SPEC, [h])[0]
    assert v == Verdict.BUDGET_EXCEEDED
    # with the rescue pass enabled (default), the same backend decides it
    rescued = JaxTPU(SPEC, budget=3)
    assert rescued.check_histories(SPEC, [h])[0] == Verdict.LINEARIZABLE
    assert rescued.rescued == 1


def test_large_batch_parity(tpu):
    """Regression for the JAX 0.9.0 vmapped-bool-scatter bug: batches padded
    to >=1024 must give the same verdicts as tiny batches (the kernel now
    uses mask arithmetic, no scatters)."""
    h = History([Op(0, READ, 0, -1, 3, 1 << 30), Op(0, WRITE, 0, 0, 3, 11),
                 Op(1, READ, 0, 1, 5, 11), Op(1, READ, 0, 0, 7, 9)])
    assert int(ORACLE.check_histories(SPEC, [h])[0]) == Verdict.VIOLATION
    out = tpu.check_histories(SPEC, [h] * 200)  # expands to >1024 rows
    assert (np.asarray(out) == Verdict.VIOLATION).all()


def test_sharded_batch_parity():
    """JaxTPU with a batch-axis NamedSharding over the 8-device mesh must
    give bit-identical verdicts to the unsharded backend (SURVEY.md §5 comm
    backend: batch-axis sharding over ICI)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]), ("batch",))
    sharded = JaxTPU(SPEC, sharding=NamedSharding(mesh, P("batch")))
    hists = []
    for seed in range(32):
        prog = generate_program(SPEC, seed=seed, n_pids=3, max_ops=12)
        hists.append(run_concurrent(RacyCachedRegisterSUT(), prog,
                                    seed=f"sh{seed}"))
    from conftest import assert_backend_parity
    assert_backend_parity(SPEC, hists, sharded, oracle=ORACLE)


def test_pending_expansion_overflow_defers():
    few = JaxTPU(SPEC, max_expansions=2)
    h = History([Op(0, WRITE, 1, -1, 0, 1 << 30),
                 Op(1, WRITE, 2, -1, 1, 1 << 30),
                 Op(0, READ, 0, 0, 2, 3)])
    # 2 pending ops -> (1+1)*(1+1) = 4 > 2 expansions (write has 1 resp)
    v = few.check_histories(SPEC, [h])[0]
    assert v == Verdict.BUDGET_EXCEEDED
