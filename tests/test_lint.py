"""The qsmlint tier-1 gate (ISSUE 1 acceptance): the in-tree corpus —
all eight registry model families and all five lineariser engine
modules — must lint clean (no non-whitelisted error findings), and each
seeded-bug fixture (parity-broken spec, retracing kernel, nondeterministic
scheduler stub) must be flagged with the correct rule_id.  A lint whose
true positives rot is a green light with the bulb removed."""

from __future__ import annotations

import json
import time

import pytest

import qsm_tpu.analysis.fixtures as fixtures
from qsm_tpu.analysis import (ERROR, FAMILIES, Finding, Whitelist,
                              run_lint)
from qsm_tpu.analysis.engine import (DEFAULT_DEVQ_FILES,
                                     DEFAULT_FLEET_FILES,
                                     DEFAULT_GEN_FILES,
                                     DEFAULT_MESH_FILES,
                                     DEFAULT_MONITOR_FILES,
                                     DEFAULT_OBS_FILES,
                                     DEFAULT_OPS_FILES,
                                     DEFAULT_POOL_FILES,
                                     DEFAULT_PROTOCOL_FILES,
                                     DEFAULT_RACE_FILES,
                                     DEFAULT_RESILIENCE_FILES,
                                     DEFAULT_SCHED_FILES,
                                     DEFAULT_SERVE_FILES,
                                     _retrace_corpora)
from qsm_tpu.analysis.kernel_passes import (VMEM_BUDGET_BYTES,
                                            check_retracing,
                                            check_step_dtypes,
                                            pallas_vmem_bytes)
from qsm_tpu.analysis.sched_passes import check_sched_file
from qsm_tpu.analysis.spec_passes import check_spec
from qsm_tpu.models.registry import MODELS


@pytest.fixture(scope="module")
def report():
    t0 = time.perf_counter()
    rep = run_lint()
    rep.wall = time.perf_counter() - t0
    return rep


def test_in_tree_corpus_is_clean(report):
    """All eight families + the five engine modules + the scheduler
    plane + the device/tool modules: zero non-whitelisted error
    findings."""
    assert sorted(MODELS) == report.models  # really covered everything
    assert len(DEFAULT_OPS_FILES) == 5      # the five lineariser engines
    assert len(DEFAULT_SCHED_FILES) == 4
    # every engine module is also resilience-scanned, plus the device
    # plumbing and the artifact tools (bench.py, tools/)
    assert len(DEFAULT_RESILIENCE_FILES) >= 12
    assert "resilience" in report.passes
    # the serving plane (family e): every connection-accepting /
    # lane-buffering module (the pool supervisor and worker recv loops
    # included) plus the serve bench tool — and, since r12, the fleet
    # tier's router/membership/replog (+ the r13 lease/gossip modules)
    # and its soak bench
    assert len(DEFAULT_SERVE_FILES) == 16
    assert "serve" in report.passes
    # the worker-lifecycle plane (family f): spawn/supervise/bench
    assert len(DEFAULT_POOL_FILES) == 3
    assert "pool" in report.passes
    # the whole-program race plane (family g): serve + resilience +
    # tools, analyzed as one closed program (the shrink plane and the
    # fleet tier included)
    assert len(DEFAULT_RACE_FILES) >= 21
    assert "race" in report.passes
    # the shrink plane's frontier-bound family (h)
    assert "shrink" in report.passes
    # the trace-plane discipline family (i): span close + metric
    # cardinality over obs/ + serve/ + resilience/
    assert len(DEFAULT_OBS_FILES) >= 17
    assert "obs" in report.passes
    # the fleet re-dispatch + lease family (j): router/membership/
    # replog + the r13 lease/gossip modules + the soak bench
    assert len(DEFAULT_FLEET_FILES) == 8
    assert "fleet" in report.passes
    # the monitor-session bounds family (k): monitor/ + ingest/ + the
    # monitor bench driver (ISSUE 14)
    assert len(DEFAULT_MONITOR_FILES) == 8
    assert "monitor" in report.passes
    # the wire-contract family (l): the socket-protocol planes plus the
    # committed PROTOCOL.json artifact (ISSUE 16)
    assert len(DEFAULT_PROTOCOL_FILES) == 13
    assert "protocol" in report.passes
    # the generation-campaign bounds family (m): gen/ + the gen bench
    # driver (ISSUE 17)
    assert len(DEFAULT_GEN_FILES) == 5
    assert "gen" in report.passes
    # the mesh-dispatch family (n): the substrate + its sharded
    # consumers + the mesh bench driver (ISSUE 19)
    assert len(DEFAULT_MESH_FILES) == 6
    assert "mesh" in report.passes
    # the device-work-queue family (o): the queue/drain plane + the
    # window and bench drivers (ISSUE 20)
    assert len(DEFAULT_DEVQ_FILES) == 4
    assert "devq" in report.passes
    # a–o all registered and all ran in the default lane
    assert sorted(FAMILIES) == list("abcdefghijklmno")
    assert report.families == list("abcdefghijklmno")
    assert report.ok, "\n".join(
        f"{f.rule_id} {f.location}: {f.message}" for f in report.errors)


def test_lint_is_window_cheap(report):
    """The acceptance bound is <120 s on CPU; the analyzer must stay far
    inside it or the watcher's pre-seize gate becomes its own window
    burner."""
    assert report.wall < 120.0


def test_whitelist_entries_are_all_live(report):
    """Every .qsmlint entry must still match a real finding — dead
    entries are expired claims that hide future regressions at the same
    location."""
    used_rules = {f.rule_id for f in report.whitelisted}
    from qsm_tpu.analysis import default_whitelist_path

    wl = Whitelist.load(default_whitelist_path())
    for rule, _prefix in wl.entries:
        assert rule in used_rules, \
            f"whitelist entry {rule} matches nothing; remove it"


# --- the seeded-bug fixtures: every pass family proves it still fires ----

def test_parity_broken_spec_is_caught():
    findings = check_spec(fixtures.ParityBrokenCasSpec(),
                          "fixture:parity_broken_cas")
    errs = {f.rule_id for f in findings if f.severity == ERROR}
    assert "QSM-SPEC-PARITY" in errs


def test_retracing_kernel_is_caught():
    spec = MODELS["cas"].make_spec()
    backend = fixtures.RetracingJaxTPU(
        spec, budget=2_000, mid_budget=0, rescue_budget=0,
        rescue_slots=64)
    backend.CHUNK_SCHEDULE = (512,)
    backend.DOUBLE_BUFFER = False
    findings = check_retracing(spec, backend,
                               _retrace_corpora(MODELS["cas"], spec),
                               "fixture:retracing_kernel")
    assert {f.rule_id for f in findings} == {"QSM-KERN-RETRACE"}


def test_nondeterministic_scheduler_stub_is_caught():
    findings = check_sched_file(fixtures.__file__)
    rules = {f.rule_id for f in findings}
    assert {"QSM-DET-SET-ITER", "QSM-DET-RANDOM", "QSM-DET-TIME",
            "QSM-DET-ID"} <= rules


def test_unseeded_random_construction_is_flagged(tmp_path):
    """The Random-constructor exemption is for SEEDED construction
    only: `random.Random()` draws from OS entropy — the same
    unreplayable nondeterminism the rule exists to forbid."""
    p = tmp_path / "stub.py"
    p.write_text("import random\n"
                 "class S:\n"
                 "    def __init__(self, seed):\n"
                 "        self.rng = random.Random(seed)   # ok: seeded\n"
                 "        self.bad = random.Random()       # entropy\n")
    findings = check_sched_file(str(p))
    assert [f.rule_id for f in findings] == ["QSM-DET-RANDOM"]
    assert "UNSEEDED" in findings[0].message


def test_unbounded_device_probe_is_caught():
    """The resilience pass's bulb check: the bare jax.devices(), the
    timeoutless subprocess wait and the probe-timeout literal each fire
    their rule; the watchdog-bounded twin in the same fixture class must
    NOT be flagged (a pass that cries wolf on the sanctioned form gets
    whitelisted into uselessness)."""
    from qsm_tpu.analysis.resilience_passes import check_resilience_file

    findings = check_resilience_file(fixtures.__file__)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule_id, []).append(f)
    assert len(by_rule.pop("QSM-RES-DEVICES")) == 1   # bounded twin clean
    assert len(by_rule.pop("QSM-RES-SUBPROC")) == 1
    lit = by_rule.pop("QSM-RES-TIMEOUT-LITERAL")
    assert len(lit) == 1 and lit[0].severity == "warning"
    assert not by_rule  # nothing else fires on the fixture module


def test_unbounded_serve_loop_is_caught():
    """The serve pass's bulb check (family e): the while-True accept
    loop with no deadline/shutdown check and the unbounded admission
    queue each fire their rule exactly once; the stop-flag-gated and
    settimeout-polled twins in the same fixture class must NOT be
    flagged."""
    from qsm_tpu.analysis.serve_passes import check_serve_file

    findings = check_serve_file(fixtures.__file__)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule_id, []).append(f)
    accept = by_rule.pop("QSM-SERVE-ACCEPT")
    assert len(accept) == 1 and accept[0].severity == ERROR
    assert "serve_forever_unbounded" in accept[0].location
    unbounded = by_rule.pop("QSM-SERVE-UNBOUNDED")
    assert len(unbounded) == 1
    assert "serve_forever_unbounded" in unbounded[0].location
    assert not by_rule  # nothing else fires on the fixture module


def test_unclosed_span_and_unbounded_metric_are_caught():
    """The obs pass's bulb check (family i): the hand-entered span
    fires QSM-OBS-SPAN exactly once, and the fingerprint-minted metric
    name + concatenated label value fire QSM-OBS-CARDINALITY exactly
    twice; the with-statement / delegating-return span twins and the
    constant-name / str(wid)-labeled metric twins must NOT be
    flagged."""
    from qsm_tpu.analysis.obs_passes import check_obs_file

    findings = check_obs_file(fixtures.__file__)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule_id, []).append(f)
    span = by_rule.pop("QSM-OBS-SPAN")
    assert len(span) == 1 and span[0].severity == ERROR
    assert "UnclosedSpanStub" not in span[0].location  # function-scoped
    assert ":work:" in span[0].location
    card = by_rule.pop("QSM-OBS-CARDINALITY")
    assert len(card) == 2
    assert {f.severity for f in card} == {ERROR}
    assert not by_rule  # nothing else fires on the fixture module


def test_obs_live_tree_is_clean():
    """The obs plane itself, the serving stack and the resilience
    layers all keep the span-close and bounded-cardinality
    disciplines (the sanctioned forms the rules carve out: with-
    statement spans, delegating returns, str()-cast bounded labels)."""
    from qsm_tpu.analysis.obs_passes import check_obs_file
    from qsm_tpu.analysis.engine import REPO_ROOT
    import os

    findings = []
    for rel in DEFAULT_OBS_FILES:
        findings += check_obs_file(os.path.join(REPO_ROOT, rel),
                                   root=REPO_ROOT)
    assert findings == []


def test_fleet_redispatch_is_caught():
    """The fleet pass's bulb check (family j): the while-True
    re-dispatch loop (no attempt budget) and the bounded loop that
    never excludes the failed node each fire QSM-FLEET-REDISPATCH
    exactly once; the tried-set + exclude= twin must NOT be flagged."""
    from qsm_tpu.analysis.fleet_passes import check_fleet_file

    findings = check_fleet_file(fixtures.__file__)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule_id, []).append(f)
    hits = by_rule.pop("QSM-FLEET-REDISPATCH")
    assert len(hits) == 2
    assert {f.severity for f in hits} == {ERROR}
    # the two seeded forms, in source order: unbounded first (the
    # while-True stub), non-excluding second; the sanctioned
    # BoundedRedispatchRouterStub (tried.add + exclude=) stays clean
    assert "no bounded attempt budget" in hits[0].message
    assert "never excludes the failed node" in hits[1].message
    by_rule.pop("QSM-FLEET-LEASE")    # pinned by its own bulb test
    by_rule.pop("QSM-FLEET-HANDOFF")  # pinned by its own bulb test
    assert not by_rule  # nothing else fires on the fixture module


def test_fleet_lease_is_caught():
    """The lease pass's bulb check (family j, ISSUE 13): the
    while-True promote loop and the term/expiry-blind acquire each
    fire QSM-FLEET-LEASE exactly once; the beat-driven twin that
    reads the record, consults expired()/term and acquires at most
    once per beat must NOT be flagged."""
    from qsm_tpu.analysis.fleet_passes import check_fleet_file

    findings = [f for f in check_fleet_file(fixtures.__file__)
                if f.rule_id == "QSM-FLEET-LEASE"]
    assert len(findings) == 2
    assert {f.severity for f in findings} == {ERROR}
    assert "promote_forever" in findings[0].location
    assert "unbounded standby-promote loop" in findings[0].message
    assert "promote_blind" in findings[1].location
    assert "never consults lease term/expiry" in findings[1].message
    # the sanctioned LeasedTakeoverRouterStub stays clean
    assert not any("LeasedTakeoverRouterStub" in f.location
                   or "beat" in f.location for f in findings)


def test_fleet_handoff_is_caught():
    """The handoff pass's bulb check (family j, ISSUE 18): the join
    that never seeds the newcomer's replog and the leave that never
    migrates the retiree's routed sessions each fire
    QSM-FLEET-HANDOFF exactly once; the sweep-on-join +
    invalidate-on-leave twin must NOT be flagged."""
    from qsm_tpu.analysis.fleet_passes import check_fleet_file

    findings = [f for f in check_fleet_file(fixtures.__file__)
                if f.rule_id == "QSM-FLEET-HANDOFF"]
    assert len(findings) == 2
    assert {f.severity for f in findings} == {ERROR}
    assert "join_cold" in findings[0].location
    assert "without replog handoff" in findings[0].message
    assert "leave_sticky" in findings[1].location
    assert "without session migration" in findings[1].message
    # the sanctioned RebalancingRouterStub stays clean
    assert not any("RebalancingRouterStub" in f.location
                   or ":join:" in f.location or ":leave:" in f.location
                   for f in findings)


def test_fleet_live_tree_is_clean():
    """The fleet tier itself keeps the discipline its pass gates:
    bounded attempts from the fleet-route preset + tried-set
    exclusion (fleet/router.py _dispatch_group is the model)."""
    import os

    from qsm_tpu.analysis.engine import REPO_ROOT
    from qsm_tpu.analysis.fleet_passes import check_fleet_file

    findings = []
    for rel in DEFAULT_FLEET_FILES:
        findings += check_fleet_file(os.path.join(REPO_ROOT, rel),
                                     root=REPO_ROOT)
    assert findings == []


def test_monitor_unbounded_buffer_is_caught():
    """The monitor pass's bulb check (family k, ISSUE 14): the session
    stub whose event buffer AND window grow with no cap comparison or
    eviction fires QSM-MON-UNBOUNDED once per unbounded attribute; the
    capped/evicting twin (session.py max_events shape + frontier.py
    decided-prefix reassignment) must NOT be flagged."""
    from qsm_tpu.analysis.monitor_passes import check_monitor_file

    # scope to the session stubs: the family-m seed-pool fixture in the
    # same file legitimately trips this scan too (its own test covers it)
    findings = [f for f in check_monitor_file(fixtures.__file__)
                if f.rule_id == "QSM-MON-UNBOUNDED"
                and "SessionBufferStub" in f.location]
    assert len(findings) == 2  # self.events and self.window
    assert {f.severity for f in findings} == {ERROR}
    assert all("UnboundedSessionBufferStub" in f.location
               for f in findings)
    assert any("self.events" in f.message for f in findings)
    assert any("self.window" in f.message for f in findings)
    assert not any("BoundedSessionBufferStub" in f.location
                   for f in findings)


def test_monitor_live_tree_is_clean():
    """The monitor plane itself keeps the discipline its pass gates:
    capped event logs (session.py), capped frontier state sets and
    decided-prefix window eviction (frontier.py), bounded ingest."""
    import os

    from qsm_tpu.analysis.engine import REPO_ROOT
    from qsm_tpu.analysis.monitor_passes import check_monitor_file

    findings = []
    for rel in DEFAULT_MONITOR_FILES:
        findings += check_monitor_file(os.path.join(REPO_ROOT, rel),
                                       root=REPO_ROOT)
    assert findings == []


def test_gen_unbounded_pool_is_caught():
    """The gen pass's bulb check (family m, ISSUE 17): the seed-pool
    stub whose corpus AND flip log grow once per round with no cap
    comparison or eviction fires QSM-GEN-UNBOUNDED once per unbounded
    attribute; the capacity-evicted / tail-windowed twin (the steer.py
    SeedPool.add + kept-flips shapes) must NOT be flagged."""
    from qsm_tpu.analysis.gen_passes import check_gen_file

    findings = [f for f in check_gen_file(fixtures.__file__)
                if f.rule_id == "QSM-GEN-UNBOUNDED"
                and "SeedPoolStub" in f.location]
    assert len(findings) == 2  # self.seeds and self.flips
    assert {f.severity for f in findings} == {ERROR}
    assert all("UnboundedSeedPoolStub" in f.location
               for f in findings)
    assert any("self.seeds" in f.message for f in findings)
    assert any("self.flips" in f.message for f in findings)
    assert not any("BoundedSeedPoolStub" in f.location
                   for f in check_gen_file(fixtures.__file__))


def test_gen_delegated_growth_is_not_flagged():
    """Family m's refinement over family k's scan: ``self.pool.add(…)``
    where ``pool`` is another object (``SeedPool()``) is delegation —
    the delegate, in the scan set itself, carries the bound — so only
    attributes the class owns as raw container literals are hunted."""
    import textwrap

    from qsm_tpu.analysis.gen_passes import check_gen_file

    src = textwrap.dedent("""
        class Campaign:
            def __init__(self):
                self.pool = SeedPool()
            def round(self, entry):
                self.pool.add(entry)
    """)
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py") as f:
        f.write(src)
        f.flush()
        assert check_gen_file(f.name) == []


def test_gen_live_tree_is_clean():
    """The generation plane itself keeps the discipline its pass gates:
    capacity-evicted seed pool (steer.py), tail-windowed kept flips,
    capped wrongness provenance (fleet.py)."""
    import os

    from qsm_tpu.analysis.engine import REPO_ROOT
    from qsm_tpu.analysis.gen_passes import check_gen_file

    findings = []
    for rel in DEFAULT_GEN_FILES:
        findings += check_gen_file(os.path.join(REPO_ROOT, rel),
                                   root=REPO_ROOT)
    assert findings == []


def test_mesh_hardcode_is_caught():
    """The mesh pass's bulb check (family n, ISSUE 19): the hardcoded
    stub fires QSM-MESH-HARDCODE for BOTH shapes — indexing the device
    enumeration and a literal count in a mesh constructor — while the
    shape-polymorphic twin (threaded count, len() over the enumeration)
    stays clean."""
    from qsm_tpu.analysis.mesh_passes import check_mesh_file

    findings = [f for f in check_mesh_file(fixtures.__file__)
                if f.rule_id == "QSM-MESH-HARDCODE"
                and "MeshStub" in f.location]
    assert len(findings) == 2
    assert {f.severity for f in findings} == {ERROR}
    assert all("HardcodedMeshStub" in f.location for f in findings)
    assert any("pin_first_device" in f.location for f in findings)
    assert any("build_fixed_mesh" in f.location for f in findings)
    assert not any("ShapePolymorphicMeshStub" in f.location
                   for f in check_mesh_file(fixtures.__file__))


def test_mesh_transfer_is_caught():
    """QSM-MESH-TRANSFER fires on the function that BOTH applies a
    sharding and pulls to host; the split twin (the jax_kernel.py
    _shard_carry / _compact_carry_host shape) stays clean."""
    from qsm_tpu.analysis.mesh_passes import check_mesh_file

    findings = [f for f in check_mesh_file(fixtures.__file__)
                if f.rule_id == "QSM-MESH-TRANSFER"]
    assert len(findings) == 1
    assert "TransferringDispatchStub.shard_then_pull" in \
        findings[0].location
    assert findings[0].severity == ERROR
    assert not any("DeviceResidentDispatchStub" in f.location
                   for f in check_mesh_file(fixtures.__file__))


def test_mesh_scope_is_the_function_not_the_module():
    """A module that device_puts in one function and np.asarray's in
    another must NOT co-occur into a finding — the rule's scope is the
    function, because gather-then-reshard THROUGH a helper is exactly
    the sanctioned compaction shape."""
    import tempfile
    import textwrap

    from qsm_tpu.analysis.mesh_passes import check_mesh_file

    src = textwrap.dedent("""
        import jax
        import numpy as np

        def shard(arrs, sharding):
            return [jax.device_put(a, sharding) for a in arrs]

        def gather(shards):
            return [np.asarray(s) for s in shards]
    """)
    with tempfile.NamedTemporaryFile("w", suffix=".py") as f:
        f.write(src)
        f.flush()
        assert check_mesh_file(f.name) == []


def test_mesh_live_tree_is_clean():
    """The substrate keeps its own discipline: no literal device count
    outside a threaded parameter, no host pull inside a sharding-
    applying function, across qsm_tpu/mesh/ and every sharded consumer
    (jax_kernel's _shard_carry / _compact_carry_host split included)."""
    import os

    from qsm_tpu.analysis.engine import REPO_ROOT
    from qsm_tpu.analysis.mesh_passes import check_mesh_file

    findings = []
    for rel in DEFAULT_MESH_FILES:
        findings += check_mesh_file(os.path.join(REPO_ROOT, rel),
                                    root=REPO_ROOT)
    assert findings == []


def test_devq_unbounded_queue_is_caught():
    """The devq pass's bulb check (family o, ISSUE 20): the queue stub
    whose pending map AND done-tombstone log grow with no cap
    comparison or eviction fires QSM-DEVQ-UNBOUNDED once per unbounded
    attribute; the capped/pruning twin (queue.py _evict_over_cap +
    tail-window tombstone trim shapes) must NOT be flagged."""
    from qsm_tpu.analysis.devq_passes import check_devq_file

    # scope to the devq stubs: families k/m's unbounded fixtures in the
    # same file legitimately trip this shared scan too (their own tests
    # cover them)
    findings = [f for f in check_devq_file(fixtures.__file__)
                if f.rule_id == "QSM-DEVQ-UNBOUNDED"
                and "DevqStub" in f.location]
    assert len(findings) == 2  # self.pending and self.done
    assert {f.severity for f in findings} == {ERROR}
    assert all("UnboundedDevqStub" in f.location for f in findings)
    assert any("self.pending" in f.message for f in findings)
    assert any("self.done" in f.message for f in findings)
    assert not any("BoundedDevqStub" in f.location for f in findings)


def test_devq_deadline_blind_drain_is_caught():
    """Family o's second rule (QSM-DEVQ-DRAIN): the drain stub whose
    while-loop never consults the window deadline fires; the
    deadline-gated twin (the DrainScheduler.drain `remaining` shape)
    must NOT be flagged.  (The family-g counter fixtures' `_drain`
    threads in the same file trip the name heuristic too — scoped out,
    their own tests cover them.)"""
    from qsm_tpu.analysis.devq_passes import check_devq_file

    blind = [f for f in check_devq_file(fixtures.__file__)
             if f.rule_id == "QSM-DEVQ-DRAIN"
             and "drain_queue" in f.location]
    assert len(blind) == 1  # DeadlineBlindDrainStub.drain_queue only:
    # the gated twin's drain_queue consults `remaining` and stays clean
    assert blind[0].severity == ERROR
    assert "deadline" in blind[0].message


def test_devq_live_tree_is_clean():
    """The devq plane keeps its own discipline: capped pending map +
    tombstone trim (queue.py), every drain while-loop consulting the
    remaining window time (drain.py, tools/window_drain.py)."""
    import os

    from qsm_tpu.analysis.devq_passes import check_devq_file
    from qsm_tpu.analysis.engine import REPO_ROOT

    findings = []
    for rel in DEFAULT_DEVQ_FILES:
        p = os.path.join(REPO_ROOT, rel)
        if os.path.exists(p):
            findings += check_devq_file(p, root=REPO_ROOT)
    assert findings == []


def test_protocol_fixture_matrix():
    """The protocol pass's bulb check (family l, ISSUE 16): the
    miswired pair fires QSM-PROTO-UNHANDLED (undispatched ``mis.ghost``
    at the send site AND as a declared-but-handlerless op) and
    QSM-PROTO-FIELDS (the never-written ``echo_payload`` read); the
    ``send_doc``-bypassing handler fires QSM-PROTO-EGRESS; the
    except-continue loop re-sending the mutating ``retry.reset`` fires
    QSM-PROTO-RETRY-IDEMPOTENT.  The sanctioned twins (wired pair,
    ``_send``-routed handler, retried-but-idempotent ``retry.get``)
    stay clean."""
    from qsm_tpu.analysis.protocol_passes import check_protocol_project

    findings = check_protocol_project([fixtures.__file__])
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule_id, []).append(f)
    unhandled = by_rule.pop("QSM-PROTO-UNHANDLED")
    assert len(unhandled) == 2
    assert {f.severity for f in unhandled} == {ERROR}
    assert any("MiswiredProtocolClientStub.ghost" in f.location
               for f in unhandled)
    assert all("mis.ghost" in f.message for f in unhandled)
    fields = by_rule.pop("QSM-PROTO-FIELDS")
    assert len(fields) == 1 and fields[0].severity == ERROR
    assert "MiswiredProtocolClientStub.ping" in fields[0].location
    assert "echo_payload" in fields[0].message
    egress = by_rule.pop("QSM-PROTO-EGRESS")
    assert len(egress) == 1 and egress[0].severity == ERROR
    assert "UnstampedEgressStub._handle" in egress[0].location
    retry = by_rule.pop("QSM-PROTO-RETRY-IDEMPOTENT")
    assert len(retry) == 1 and retry[0].severity == ERROR
    assert "RetriedMutationClientStub.reset" in retry[0].location
    assert "retry.reset" in retry[0].message
    assert not by_rule  # nothing else fires on the fixture module
    clean = ("WiredProtocol", "StampedEgress", "IdempotentRetry")
    assert not any(c in f.location for c in clean for f in findings)


def test_protocol_live_tree_is_clean():
    """The socket planes keep the contract their pass gates: every op
    dispatched and called, responses through the one ``_send``, retried
    ops all declared idempotent, the committed PROTOCOL.json current."""
    import os

    from qsm_tpu.analysis.engine import REPO_ROOT
    from qsm_tpu.analysis.protocol_passes import check_protocol_project

    paths = [os.path.join(REPO_ROOT, rel)
             for rel in DEFAULT_PROTOCOL_FILES]
    assert check_protocol_project(paths, root=REPO_ROOT) == []


def test_protocol_json_is_deterministic_and_covering():
    """The contract artifact is byte-stable (sorted keys, no
    timestamps — two extractions, one with the file list reversed,
    render identically) and total: every op declared in
    serve/protocol.py appears with at least one handler and one
    caller."""
    import os

    from qsm_tpu.analysis.engine import REPO_ROOT
    from qsm_tpu.analysis.protocol_model import (ProtocolModel,
                                                 render_protocol_json)
    from qsm_tpu.serve.protocol import OPS

    paths = [os.path.join(REPO_ROOT, rel)
             for rel in DEFAULT_PROTOCOL_FILES if rel.endswith(".py")]
    one = render_protocol_json(ProtocolModel(paths, root=REPO_ROOT))
    two = render_protocol_json(
        ProtocolModel(list(reversed(paths)), root=REPO_ROOT))
    assert one == two
    doc = json.loads(one)
    assert sorted(doc["ops"]) == sorted(OPS)
    for op in OPS:
        assert doc["ops"][op]["handlers"], f"{op}: no handler"
        assert doc["ops"][op]["callers"], f"{op}: no caller"


def test_protocol_drift_gate(tmp_path):
    """The pre-refactor safety net: a protocol edit that does not
    regenerate PROTOCOL.json fails the gate (QSM-PROTO-DRIFT), and the
    committed artifact matches a fresh extraction today."""
    import os

    from qsm_tpu.analysis.engine import REPO_ROOT
    from qsm_tpu.analysis.protocol_passes import check_protocol_project

    paths = [os.path.join(REPO_ROOT, rel)
             for rel in DEFAULT_PROTOCOL_FILES]
    committed = os.path.join(REPO_ROOT, "PROTOCOL.json")
    stale = tmp_path / "PROTOCOL.json"
    stale.write_text(open(committed).read().replace(
        '"artifact": "PROTOCOL"', '"artifact": "STALE"'))
    findings = check_protocol_project(paths, root=REPO_ROOT,
                                      protocol_path=str(stale))
    assert [f.rule_id for f in findings] == ["QSM-PROTO-DRIFT"]
    assert findings[0].severity == ERROR
    # and the real committed artifact is current (== fresh extraction)
    assert check_protocol_project(paths, root=REPO_ROOT,
                                  protocol_path=committed) == []


def test_lint_report_carries_protocol_summary(report):
    """``qsm-tpu lint --json`` exposes the contract trend block —
    bench_report.py rows key off these counts."""
    assert report.protocol is not None
    assert report.protocol["ops"] == 27
    assert report.protocol["handled_ops"] == report.protocol["ops"]
    assert report.protocol["called_ops"] == report.protocol["ops"]
    # shutdown is the one deliberately non-idempotent op, and it must
    # never appear on a retrying path
    assert report.protocol["idempotent_ops"] == 26
    assert "shutdown" not in report.protocol["retried_ops"]


def test_unreaped_worker_pool_is_caught():
    """The pool pass's bulb check (family f): the reapless Popen and
    the backoffless while-True respawn loop each fire their rule
    exactly once; the terminate→bounded-wait→kill twin and the
    stop-gated backoff loop must NOT be flagged."""
    from qsm_tpu.analysis.pool_passes import check_pool_file

    findings = check_pool_file(fixtures.__file__)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule_id, []).append(f)
    reap = by_rule.pop("QSM-POOL-REAP")
    assert len(reap) == 1 and reap[0].severity == ERROR
    assert "spawn_unreaped" in reap[0].location
    respawn = by_rule.pop("QSM-POOL-RESPAWN")
    assert len(respawn) == 1 and respawn[0].severity == ERROR
    assert "respawn_forever" in respawn[0].location
    assert not by_rule  # nothing else fires on the fixture module


def test_bounded_pool_idioms_are_clean(tmp_path):
    """True-negative pin: the pool plane's own idioms — spawn with a
    bounded reap in the same class, a stop-gated respawn loop with
    backoff, a for-bounded retry — must not be flagged."""
    from qsm_tpu.analysis.pool_passes import check_pool_file

    p = tmp_path / "stub.py"
    p.write_text(
        "import subprocess, sys, time\n"
        "class Pool:\n"
        "    def spawn(self):\n"
        "        p = subprocess.Popen([sys.executable, '-c', 'pass'])\n"
        "        p.terminate()\n"
        "        p.wait(timeout=2.0)\n"
        "        return p\n"
        "    def retry_bounded(self):\n"
        "        for _ in range(3):\n"
        "            p = subprocess.Popen([sys.executable, '-c', 'x'])\n"
        "            p.wait(timeout=1.0)\n")
    assert check_pool_file(str(p)) == []


def test_module_scope_unreaped_spawn_is_caught(tmp_path):
    """A module-level spawn (the bench-tool shape) needs a bounded reap
    at module scope too — a class' reap elsewhere says nothing about
    it."""
    from qsm_tpu.analysis.pool_passes import check_pool_file

    p = tmp_path / "stub.py"
    p.write_text(
        "import subprocess, sys\n"
        "class Unrelated:\n"
        "    def reap(self, p):\n"
        "        p.wait(timeout=1.0)\n"
        "proc = subprocess.Popen([sys.executable, '-c', 'pass'])\n")
    findings = check_pool_file(str(p))
    assert [f.rule_id for f in findings] == ["QSM-POOL-REAP"]
    assert "<module>" in findings[0].location


def test_bounded_serve_idioms_are_clean(tmp_path):
    """True-negative pin: the serving plane's own idioms — stop-flag
    loop tests, settimeout-bounded polls, maxsize'd queues — must not
    be flagged (a pass that cries wolf on the sanctioned forms gets
    whitelisted into uselessness)."""
    from qsm_tpu.analysis.serve_passes import check_serve_file

    p = tmp_path / "stub.py"
    p.write_text(
        "import queue\n"
        "class S:\n"
        "    def loop(self, sock):\n"
        "        q = queue.Queue(maxsize=8)\n"
        "        sock.settimeout(0.2)\n"
        "        while True:\n"
        "            try:\n"
        "                q.put(sock.accept(), block=False)\n"
        "            except OSError:\n"
        "                continue\n"
        "    def gated(self, sock, stop):\n"
        "        while not stop.is_set():\n"
        "            sock.recv(4096)\n")
    assert check_serve_file(str(p)) == []


def test_queue_maxsize_zero_is_flagged_as_unbounded(tmp_path):
    """The stdlib spells 'infinite' as Queue(maxsize=0) (negatives
    too): an explicit-zero bound is exactly the unbounded hazard, not a
    bound — the pass must not wave it through."""
    from qsm_tpu.analysis.serve_passes import check_serve_file

    p = tmp_path / "stub.py"
    p.write_text("import queue\n"
                 "a = queue.Queue(maxsize=0)\n"
                 "b = queue.Queue(0)\n"
                 "c = queue.Queue(maxsize=-1)\n"
                 "d = queue.Queue(maxsize=8)   # ok: a real bound\n")
    findings = check_serve_file(str(p))
    assert [f.rule_id for f in findings] == ["QSM-SERVE-UNBOUNDED"] * 3


def test_subprocess_with_timeout_is_clean(tmp_path):
    """True-negative pin: the repo's own bounded-subprocess idiom
    (probe/compile calls always pass timeout=) must not be flagged."""
    from qsm_tpu.analysis.resilience_passes import check_resilience_file

    p = tmp_path / "stub.py"
    p.write_text("import subprocess, sys\n"
                 "def probe(t):\n"
                 "    return subprocess.run([sys.executable, '-c', "
                 "'pass'], capture_output=True, timeout=t)\n")
    assert check_resilience_file(str(p)) == []


def test_dtype_pass_flags_float_state():
    class FloatStateCas(fixtures.ParityBrokenCasSpec):
        def step_jax(self, state, cmd, arg, resp):
            import jax.numpy as jnp

            ns, ok = super().step_jax(state, cmd, arg, resp)
            return ns.astype(jnp.float32), ok  # seeded promotion

    findings = check_step_dtypes(FloatStateCas(), "fixture:float_state")
    assert any(f.rule_id == "QSM-KERN-DTYPE" and f.severity == ERROR
               for f in findings)


def test_vmem_estimator_brackets_the_envelope():
    """The static estimator agrees with the kernel's own ceiling
    (MAX_PALLAS_STATES fits) and rejects what that ceiling exists to
    exclude (the S=1280 scalarized queue/stack shadows)."""
    from qsm_tpu.ops.pallas_kernel import (MAX_PALLAS_OPS,
                                           MAX_PALLAS_STATES, PallasTPU)

    fits = pallas_vmem_bytes(MAX_PALLAS_OPS, MAX_PALLAS_STATES,
                             PallasTPU.LANES,
                             PallasTPU.PALLAS_CACHE_SLOTS)
    blows = pallas_vmem_bytes(MAX_PALLAS_OPS, 1280, PallasTPU.LANES,
                              PallasTPU.PALLAS_CACHE_SLOTS)
    assert fits <= VMEM_BUDGET_BYTES < blows


# --- family (g): the interprocedural race analyzer ------------------------

@pytest.fixture(scope="module")
def race_findings():
    from qsm_tpu.analysis.race_passes import check_race_project

    return check_race_project([fixtures.__file__])


def test_race_fixture_matrix(race_findings):
    """The family-(g) bulb check: each seeded stub — AB/BA lock cycle,
    unguarded counter, unjoined thread, leaked pipe — fires its rule
    EXACTLY once on the fixtures module, and the sanctioned twins
    (ordered locks, guarded counter, stop-gated joined thread,
    try/finally-closed pipe) stay clean."""
    by = {}
    for f in race_findings:
        by.setdefault(f.rule_id, []).append(f)
    order = by.pop("QSM-RACE-ORDER")
    assert len(order) == 1 and order[0].severity == ERROR
    assert "DeadlockingLockPairStub" in order[0].location
    assert "lock_a" in order[0].message and "lock_b" in order[0].message
    unguarded = by.pop("QSM-RACE-UNGUARDED")
    assert len(unguarded) == 1 and unguarded[0].severity == ERROR
    assert "UnguardedCounterStub._drain" in unguarded[0].location
    assert "_lock" in unguarded[0].message  # names the guard lock
    life = by.pop("QSM-THREAD-LIFECYCLE")
    assert len(life) == 1 and life[0].severity == ERROR
    assert "UnjoinedThreadStub.start" in life[0].location
    leak = by.pop("QSM-RES-LEAK")
    assert len(leak) == 1 and leak[0].severity == ERROR
    assert "LeakedPipeStub.open_unclosed" in leak[0].location
    assert not by  # nothing else fires on the fixture module


def test_race_interprocedural_discipline(tmp_path):
    """The whole point of the call-graph substrate: a write guarded
    only via its CALLER's lock must not be flagged (entry_held
    propagation), an AB/BA cycle assembled across two functions must
    be (transitive acquires), and an ``acquire()``/``release()`` pair
    bounds the guarded region exactly."""
    from qsm_tpu.analysis.race_passes import check_race_project

    p = tmp_path / "stub.py"
    p.write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._stop = threading.Event()\n"
        "        self.n = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n"
        "    def _loop(self):\n"
        "        while not self._stop.is_set():\n"
        "            with self._lock:\n"
        "                self._bump()\n"
        "    def _bump(self):\n"
        "        self.n += 1      # guarded via the caller's lock\n"
        "    def other(self):\n"
        "        with self._lock:\n"
        "            self.n -= 1\n"
        "class D:\n"
        "    def __init__(self):\n"
        "        self.a = threading.Lock()\n"
        "        self.b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self.a:\n"
        "            self._takeb()   # cycle half via a call\n"
        "    def _takeb(self):\n"
        "        with self.b:\n"
        "            pass\n"
        "    def two(self):\n"
        "        with self.b:\n"
        "            with self.a:\n"
        "                pass\n"
        "class E:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._stop = threading.Event()\n"
        "        self.v = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._go).start()\n"
        "    def _go(self):\n"
        "        while not self._stop.is_set():\n"
        "            self._lock.acquire()\n"
        "            try:\n"
        "                self.v += 1   # inside the pair: guarded\n"
        "            finally:\n"
        "                self._lock.release()\n"
        "            self.v = 0        # past the release: unguarded\n")
    findings = check_race_project([str(p)])
    rules = sorted(f.rule_id for f in findings)
    assert rules == ["QSM-RACE-ORDER", "QSM-RACE-UNGUARDED"]
    unguarded = next(f for f in findings
                     if f.rule_id == "QSM-RACE-UNGUARDED")
    assert "E._go" in unguarded.location  # C._bump stayed clean


def test_race_three_lock_cycle_reports_real_edges(tmp_path):
    """Regression: a 3-lock cycle whose alphabetical node order is NOT
    an edge path (la->lc, lc->lb, lb->la) must produce one ORDER
    finding whose reported path follows real edges — the first cut
    crashed (KeyError) on exactly this shape, which the CLI would have
    laundered into 'analyzer trouble' and the watcher waved through."""
    from qsm_tpu.analysis.race_passes import check_race_project

    p = tmp_path / "stub.py"
    p.write_text(
        "import threading\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self.la = threading.Lock()\n"
        "        self.lb = threading.Lock()\n"
        "        self.lc = threading.Lock()\n"
        "    def p1(self):\n"
        "        with self.la:\n"
        "            with self.lc:\n"
        "                pass\n"
        "    def p2(self):\n"
        "        with self.lc:\n"
        "            with self.lb:\n"
        "                pass\n"
        "    def p3(self):\n"
        "        with self.lb:\n"
        "            with self.la:\n"
        "                pass\n")
    findings = check_race_project([str(p)])
    assert [f.rule_id for f in findings] == ["QSM-RACE-ORDER"]
    msg = findings[0].message
    assert "T.la" in msg and "T.lb" in msg and "T.lc" in msg
    # every reported hop is a real acquisition site, never a guess
    assert "T.la -> T.lb at" not in msg  # the non-edge pair


def test_race_bare_annotation_is_not_a_write(tmp_path):
    """Regression: ``self.x: int`` (no value) writes nothing and must
    not fire QSM-RACE-UNGUARDED next to lock-guarded real writes."""
    from qsm_tpu.analysis.race_passes import check_race_project

    p = tmp_path / "stub.py"
    p.write_text(
        "import threading\n"
        "from typing import Optional\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._stop = threading.Event()\n"
        "        self.n = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n"
        "    def _loop(self):\n"
        "        self.n: int            # annotation, not a write\n"
        "        while not self._stop.is_set():\n"
        "            with self._lock:\n"
        "                self.n += 1\n")
    assert check_race_project([str(p)]) == []


def test_race_annotated_acquisition_close_is_clean(tmp_path):
    """Regression: an fd/socket bound via an ANNOTATED assignment and
    closed must not fire QSM-RES-LEAK (AnnAssign binds a name exactly
    like Assign); the same acquisition without a close still fires."""
    from qsm_tpu.analysis.race_passes import check_race_project

    p = tmp_path / "stub.py"
    p.write_text(
        "import socket\n"
        "def fine():\n"
        "    s: socket.socket = socket.socket()\n"
        "    s.close()\n"
        "def leaky():\n"
        "    s: socket.socket = socket.socket()\n"
        "    return 'nope'\n")
    findings = check_race_project([str(p)])
    assert [f.rule_id for f in findings] == ["QSM-RES-LEAK"]
    assert "leaky" in findings[0].location


def test_race_live_tree_is_clean(race_findings):
    """The end-to-end deliverable of ISSUE 7: the analyzer runs over
    the live serving stack and every finding it surfaced there was
    FIXED in this PR (pool slot-backoff writes under the pool lock,
    stop() marking handles dead under the lock, the accept thread
    joined with a bound) — so the race family must now come back
    empty (or whitelisted) on the real tree."""
    from qsm_tpu.analysis.engine import REPO_ROOT
    from qsm_tpu.analysis.race_passes import check_race_project

    import os

    paths = [os.path.join(REPO_ROOT, rel) for rel in DEFAULT_RACE_FILES]
    findings = check_race_project(paths, root=REPO_ROOT)
    wl = Whitelist.load(os.path.join(REPO_ROOT, ".qsmlint"))
    real = [f for f in findings if not wl.allows(f)]
    assert real == [], "\n".join(
        f"{f.rule_id} {f.location}: {f.message}" for f in real)


# --- satellites: families / --changed / cache / SARIF ----------------------

def test_family_registry_is_declarative():
    """Every family declares id + runner; the engine holds no
    hard-coded pass list (ISSUE 7 satellite): selecting any registered
    id runs exactly that family."""
    for fid, fam in FAMILIES.items():
        assert fam.fid == fid
        assert (fam.per_file is None) != (fam.whole is None)
    rep = run_lint(models=["cas"], retrace=False, families=["g"],
                   cache=False)
    assert rep.families == ["g"]
    assert list(rep.passes) == ["race"]
    with pytest.raises(ValueError):
        run_lint(models=["cas"], retrace=False, families=["z"])


def test_changed_scope_skips_untouched_families(tmp_path):
    """--changed narrows per-file families to git-touched modules and
    skips whole-set families whose scan set and triggers are
    untouched; an unanswerable ref falls back to the full tree with
    git_ok stamped false."""
    rep = run_lint(models=["cas"], retrace=False,
                   families=["c", "g"], changed="HEAD", cache=False,
                   file_overrides={"c": (), "g": ()})
    assert rep.changed is not None and rep.changed["ref"] == "HEAD"
    assert rep.changed["git_ok"] is True
    # empty scan sets + no triggers touched -> both families vacuous
    assert rep.findings == []
    bogus = run_lint(models=["cas"], retrace=False, families=["c"],
                     changed="no-such-ref-xyzzy", cache=False)
    assert bogus.changed["git_ok"] is False  # full-tree fallback


def test_result_cache_hits_and_invalidates(tmp_path):
    """Per-file findings are cached by content digest: an unchanged
    tree is all hits, an edited file re-lints, and the hit counts ride
    the --json report (ISSUE 7 satellite)."""
    src = tmp_path / "mod.py"
    src.write_text("import queue\nq = queue.Queue()\n")
    cache_path = str(tmp_path / "cache.json")
    kw = dict(models=["cas"], retrace=False, families=["e"],
              cache=cache_path, file_overrides={"e": (str(src),)})
    cold = run_lint(**kw)
    assert [f.rule_id for f in cold.findings] == ["QSM-SERVE-UNBOUNDED"]
    assert cold.cache == {"path": cache_path, "hits": 0, "misses": 1}
    warm = run_lint(**kw)
    assert [f.rule_id for f in warm.findings] == ["QSM-SERVE-UNBOUNDED"]
    assert warm.cache["hits"] == 1 and warm.cache["misses"] == 0
    doc = json.loads(warm.to_json())
    assert doc["cache"]["hits"] == 1  # stamped in the archive form
    src.write_text("import queue\nq = queue.Queue(maxsize=8)\n")
    fixed = run_lint(**kw)
    assert fixed.cache["misses"] == 1  # content change = cache miss
    assert fixed.findings == []
    # the superseded digest's row was pruned, not kept forever: one
    # live key per (family, file), or the cache grows per edit
    with open(cache_path) as f:
        entries = json.load(f)["entries"]
    assert len([k for k in entries if str(src) in k]) == 1


def test_changed_trigger_relints_per_file_family(tmp_path):
    """Regression: under --changed, editing a per-file family's OWN
    pass source re-lints its whole scan set (a rule change must be
    exercised); with neither files nor triggers touched the family is
    skipped."""
    from qsm_tpu.analysis.engine import FAMILIES, _LintRun, _run_family

    src = tmp_path / "mod.py"
    src.write_text("import queue\nq = queue.Queue()\n")
    fam = FAMILIES["e"]
    ctx = _LintRun(["cas"], False, 0)
    overrides = {"e": (str(src),)}
    hit = _run_family(fam, ctx, {"qsm_tpu/analysis/serve_passes.py"},
                      None, overrides)
    assert [f.rule_id for f in hit] == ["QSM-SERVE-UNBOUNDED"]
    assert _run_family(fam, ctx, set(), None, overrides) == []


def test_full_tree_lint_is_fast_with_warm_cache(report):
    """ISSUE 7 acceptance: the full-tree run stays under 10 s on the
    bench host WITH THE CACHE WARM.  The module fixture's run_lint()
    warmed it; this run times a genuinely warm full tree (the
    uncacheable retrace probe included — the honest end-to-end
    bound) and proves it actually hit."""
    t0 = time.perf_counter()
    warm = run_lint()
    wall = time.perf_counter() - t0
    assert warm.cache is not None and warm.cache["hits"] > 0
    assert wall < 10.0, f"warm full lint took {wall:.1f}s"


def test_sarif_golden_file():
    """The SARIF rendering is pinned byte-for-byte: deterministic
    output (sorted keys, no timestamps) against the committed golden
    document, whitelisted findings riding as suppressed results."""
    from qsm_tpu.analysis import render_sarif

    findings = [
        Finding("error", "QSM-RACE-ORDER",
                "qsm_tpu/serve/pool.py:WorkerPool._shed:340",
                "lock-order cycle WorkerHandle.lock -> WorkerPool._lock"
                " -> WorkerHandle.lock: two threads interleaving these "
                "paths deadlock",
                "pick ONE acquisition order for these locks"),
        Finding("error", "QSM-PROTO-RETRY-IDEMPOTENT",
                "qsm_tpu/serve/client.py:CheckClient.shutdown:223",
                "op 'shutdown' rides a retrying call path (via "
                "CheckClient._round_trip) but is not in the declared "
                "idempotent set",
                "make the op replay-safe and add it to IDEMPOTENT_OPS "
                "in serve/protocol.py, or move it off the retry path"),
        Finding("warning", "QSM-DET-TIME", "qsm_tpu/sched/pool.py:123",
                "wall-clock read in the scheduler plane"),
        Finding("info", "QSM-SPEC-PARITY", "model:kv",
                "parity sampled (8356 tuples), not exhaustive"),
    ]
    whitelisted = [
        Finding("error", "QSM-RES-DEVICES",
                "qsm_tpu/utils/device.py:probe_or_force_cpu:41",
                "bare jax.devices() outside a watchdog",
                "bound it or whitelist with a reviewed note"),
    ]
    rendered = render_sarif(findings, whitelisted,
                            meta={"version": "r16"}) + "\n"
    import os

    golden = os.path.join(os.path.dirname(__file__), "data",
                          "golden_lint.sarif")
    with open(golden) as f:
        assert f.read() == rendered
    doc = json.loads(rendered)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "qsmlint"
    # file findings carry uri+line; model findings a bare uri; the
    # whitelisted one is suppressed, not dropped
    results = run["results"]
    lines = [r["locations"][0]["physicalLocation"].get(
        "region", {}).get("startLine") for r in results]
    assert 340 in lines and 223 in lines
    assert [r for r in results if r.get("suppressions")]


def test_cli_lint_family_changed_sarif(tmp_path, capsys):
    """CLI plumbing for the new flags: --family selects by id (unknown
    ids exit 2, the usage contract), --sarif archives the document,
    --changed stamps its scope into --json."""
    from qsm_tpu.utils.cli import main

    sarif_path = tmp_path / "lint.sarif"
    rc = main(["lint", "--json", "--models", "cas", "--family", "g",
               "--no-cache", "--sarif", str(sarif_path)])
    doc = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and doc["families"] == ["g"]
    sarif = json.loads(sarif_path.read_text())
    assert sarif["runs"][0]["tool"]["driver"]["name"] == "qsmlint"
    assert main(["lint", "--family", "nope"]) == 2
    assert "unknown pass families" in capsys.readouterr().err
    rc = main(["lint", "--json", "--models", "cas", "--family", "c",
               "--no-cache", "--changed"])
    doc = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and doc["changed"]["ref"] == "HEAD"


# --- whitelist and CLI plumbing -------------------------------------------

def test_whitelist_filters_exact_rule_and_prefix():
    wl = Whitelist([("QSM-DET-TIME", "qsm_tpu/sched/pool.py")])
    hit = Finding("warning", "QSM-DET-TIME",
                  "qsm_tpu/sched/pool.py:123", "m")
    other_rule = Finding("error", "QSM-DET-RANDOM",
                         "qsm_tpu/sched/pool.py:123", "m")
    other_loc = Finding("warning", "QSM-DET-TIME",
                        "qsm_tpu/sched/scheduler.py:5", "m")
    assert wl.allows(hit)
    assert not wl.allows(other_rule)
    assert not wl.allows(other_loc)
    assert Whitelist([("QSM-DET-TIME", "*")]).allows(other_loc)


def test_cli_lint_json_and_exit_codes(tmp_path, capsys):
    """`python -m qsm_tpu lint --json` is the probe_watcher/CI archive
    form: one JSON document, exit 0 on a clean corpus, findings carried
    in full."""
    from qsm_tpu.utils.cli import main

    out_path = tmp_path / "lint.json"
    rc = main(["lint", "--json", "--models", "cas", "--no-retrace",
               "--out", str(out_path)])
    doc = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and doc["ok"] is True
    assert doc["tool"] == "qsmlint" and doc["errors"] == 0
    assert doc["models"] == ["cas"]
    # the --out archive is the same document
    assert json.loads(out_path.read_text())["ok"] is True


def test_cli_lint_usage_errors_exit_2_not_1(capsys, tmp_path):
    """Exit-code contract: 1 is reserved for REAL FINDINGS (the watcher
    refuses window seizes on it); usage mistakes exit 2."""
    from qsm_tpu.utils.cli import main

    assert main(["lint", "--models", "nope"]) == 2
    assert "unknown model" in capsys.readouterr().err
    assert main(["lint", "--whitelist", str(tmp_path / "absent")]) == 2


def test_cli_lint_analyzer_crash_exits_3_not_1(monkeypatch):
    """Analyzer trouble must exit 3 so probe_watcher waves it through
    instead of refusing every healed window of the round."""
    import qsm_tpu.analysis as analysis
    from qsm_tpu.utils.cli import main

    monkeypatch.setattr(analysis, "run_lint",
                        lambda **kw: (_ for _ in ()).throw(
                            RuntimeError("boom")))
    assert main(["lint", "--no-retrace", "--models", "cas"]) == 3


def test_report_json_shape(report):
    doc = json.loads(report.to_json())
    assert set(doc) >= {"tool", "errors", "warnings", "findings",
                        "whitelisted", "ok", "seconds", "passes",
                        "models"}
    for f in doc["findings"] + doc["whitelisted"]:
        assert set(f) == {"severity", "rule_id", "location", "message",
                          "fix_hint"}
