"""The qsmlint tier-1 gate (ISSUE 1 acceptance): the in-tree corpus —
all eight registry model families and all five lineariser engine
modules — must lint clean (no non-whitelisted error findings), and each
seeded-bug fixture (parity-broken spec, retracing kernel, nondeterministic
scheduler stub) must be flagged with the correct rule_id.  A lint whose
true positives rot is a green light with the bulb removed."""

from __future__ import annotations

import json
import time

import pytest

import qsm_tpu.analysis.fixtures as fixtures
from qsm_tpu.analysis import (ERROR, Finding, Whitelist, run_lint)
from qsm_tpu.analysis.engine import (DEFAULT_OPS_FILES,
                                     DEFAULT_POOL_FILES,
                                     DEFAULT_RESILIENCE_FILES,
                                     DEFAULT_SCHED_FILES,
                                     DEFAULT_SERVE_FILES,
                                     _retrace_corpora)
from qsm_tpu.analysis.kernel_passes import (VMEM_BUDGET_BYTES,
                                            check_retracing,
                                            check_step_dtypes,
                                            pallas_vmem_bytes)
from qsm_tpu.analysis.sched_passes import check_sched_file
from qsm_tpu.analysis.spec_passes import check_spec
from qsm_tpu.models.registry import MODELS


@pytest.fixture(scope="module")
def report():
    t0 = time.perf_counter()
    rep = run_lint()
    rep.wall = time.perf_counter() - t0
    return rep


def test_in_tree_corpus_is_clean(report):
    """All eight families + the five engine modules + the scheduler
    plane + the device/tool modules: zero non-whitelisted error
    findings."""
    assert sorted(MODELS) == report.models  # really covered everything
    assert len(DEFAULT_OPS_FILES) == 5      # the five lineariser engines
    assert len(DEFAULT_SCHED_FILES) == 4
    # every engine module is also resilience-scanned, plus the device
    # plumbing and the artifact tools (bench.py, tools/)
    assert len(DEFAULT_RESILIENCE_FILES) >= 12
    assert "resilience" in report.passes
    # the serving plane (family e): every connection-accepting /
    # lane-buffering module (the pool supervisor and worker recv loops
    # included) plus the serve bench tool
    assert len(DEFAULT_SERVE_FILES) == 10
    assert "serve" in report.passes
    # the worker-lifecycle plane (family f): spawn/supervise/bench
    assert len(DEFAULT_POOL_FILES) == 3
    assert "pool" in report.passes
    assert report.ok, "\n".join(
        f"{f.rule_id} {f.location}: {f.message}" for f in report.errors)


def test_lint_is_window_cheap(report):
    """The acceptance bound is <120 s on CPU; the analyzer must stay far
    inside it or the watcher's pre-seize gate becomes its own window
    burner."""
    assert report.wall < 120.0


def test_whitelist_entries_are_all_live(report):
    """Every .qsmlint entry must still match a real finding — dead
    entries are expired claims that hide future regressions at the same
    location."""
    used_rules = {f.rule_id for f in report.whitelisted}
    from qsm_tpu.analysis import default_whitelist_path

    wl = Whitelist.load(default_whitelist_path())
    for rule, _prefix in wl.entries:
        assert rule in used_rules, \
            f"whitelist entry {rule} matches nothing; remove it"


# --- the seeded-bug fixtures: every pass family proves it still fires ----

def test_parity_broken_spec_is_caught():
    findings = check_spec(fixtures.ParityBrokenCasSpec(),
                          "fixture:parity_broken_cas")
    errs = {f.rule_id for f in findings if f.severity == ERROR}
    assert "QSM-SPEC-PARITY" in errs


def test_retracing_kernel_is_caught():
    spec = MODELS["cas"].make_spec()
    backend = fixtures.RetracingJaxTPU(
        spec, budget=2_000, mid_budget=0, rescue_budget=0,
        rescue_slots=64)
    backend.CHUNK_SCHEDULE = (512,)
    backend.DOUBLE_BUFFER = False
    findings = check_retracing(spec, backend,
                               _retrace_corpora(MODELS["cas"], spec),
                               "fixture:retracing_kernel")
    assert {f.rule_id for f in findings} == {"QSM-KERN-RETRACE"}


def test_nondeterministic_scheduler_stub_is_caught():
    findings = check_sched_file(fixtures.__file__)
    rules = {f.rule_id for f in findings}
    assert {"QSM-DET-SET-ITER", "QSM-DET-RANDOM", "QSM-DET-TIME",
            "QSM-DET-ID"} <= rules


def test_unseeded_random_construction_is_flagged(tmp_path):
    """The Random-constructor exemption is for SEEDED construction
    only: `random.Random()` draws from OS entropy — the same
    unreplayable nondeterminism the rule exists to forbid."""
    p = tmp_path / "stub.py"
    p.write_text("import random\n"
                 "class S:\n"
                 "    def __init__(self, seed):\n"
                 "        self.rng = random.Random(seed)   # ok: seeded\n"
                 "        self.bad = random.Random()       # entropy\n")
    findings = check_sched_file(str(p))
    assert [f.rule_id for f in findings] == ["QSM-DET-RANDOM"]
    assert "UNSEEDED" in findings[0].message


def test_unbounded_device_probe_is_caught():
    """The resilience pass's bulb check: the bare jax.devices(), the
    timeoutless subprocess wait and the probe-timeout literal each fire
    their rule; the watchdog-bounded twin in the same fixture class must
    NOT be flagged (a pass that cries wolf on the sanctioned form gets
    whitelisted into uselessness)."""
    from qsm_tpu.analysis.resilience_passes import check_resilience_file

    findings = check_resilience_file(fixtures.__file__)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule_id, []).append(f)
    assert len(by_rule.pop("QSM-RES-DEVICES")) == 1   # bounded twin clean
    assert len(by_rule.pop("QSM-RES-SUBPROC")) == 1
    lit = by_rule.pop("QSM-RES-TIMEOUT-LITERAL")
    assert len(lit) == 1 and lit[0].severity == "warning"
    assert not by_rule  # nothing else fires on the fixture module


def test_unbounded_serve_loop_is_caught():
    """The serve pass's bulb check (family e): the while-True accept
    loop with no deadline/shutdown check and the unbounded admission
    queue each fire their rule exactly once; the stop-flag-gated and
    settimeout-polled twins in the same fixture class must NOT be
    flagged."""
    from qsm_tpu.analysis.serve_passes import check_serve_file

    findings = check_serve_file(fixtures.__file__)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule_id, []).append(f)
    accept = by_rule.pop("QSM-SERVE-ACCEPT")
    assert len(accept) == 1 and accept[0].severity == ERROR
    assert "serve_forever_unbounded" in accept[0].location
    unbounded = by_rule.pop("QSM-SERVE-UNBOUNDED")
    assert len(unbounded) == 1
    assert "serve_forever_unbounded" in unbounded[0].location
    assert not by_rule  # nothing else fires on the fixture module


def test_unreaped_worker_pool_is_caught():
    """The pool pass's bulb check (family f): the reapless Popen and
    the backoffless while-True respawn loop each fire their rule
    exactly once; the terminate→bounded-wait→kill twin and the
    stop-gated backoff loop must NOT be flagged."""
    from qsm_tpu.analysis.pool_passes import check_pool_file

    findings = check_pool_file(fixtures.__file__)
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule_id, []).append(f)
    reap = by_rule.pop("QSM-POOL-REAP")
    assert len(reap) == 1 and reap[0].severity == ERROR
    assert "spawn_unreaped" in reap[0].location
    respawn = by_rule.pop("QSM-POOL-RESPAWN")
    assert len(respawn) == 1 and respawn[0].severity == ERROR
    assert "respawn_forever" in respawn[0].location
    assert not by_rule  # nothing else fires on the fixture module


def test_bounded_pool_idioms_are_clean(tmp_path):
    """True-negative pin: the pool plane's own idioms — spawn with a
    bounded reap in the same class, a stop-gated respawn loop with
    backoff, a for-bounded retry — must not be flagged."""
    from qsm_tpu.analysis.pool_passes import check_pool_file

    p = tmp_path / "stub.py"
    p.write_text(
        "import subprocess, sys, time\n"
        "class Pool:\n"
        "    def spawn(self):\n"
        "        p = subprocess.Popen([sys.executable, '-c', 'pass'])\n"
        "        p.terminate()\n"
        "        p.wait(timeout=2.0)\n"
        "        return p\n"
        "    def retry_bounded(self):\n"
        "        for _ in range(3):\n"
        "            p = subprocess.Popen([sys.executable, '-c', 'x'])\n"
        "            p.wait(timeout=1.0)\n")
    assert check_pool_file(str(p)) == []


def test_module_scope_unreaped_spawn_is_caught(tmp_path):
    """A module-level spawn (the bench-tool shape) needs a bounded reap
    at module scope too — a class' reap elsewhere says nothing about
    it."""
    from qsm_tpu.analysis.pool_passes import check_pool_file

    p = tmp_path / "stub.py"
    p.write_text(
        "import subprocess, sys\n"
        "class Unrelated:\n"
        "    def reap(self, p):\n"
        "        p.wait(timeout=1.0)\n"
        "proc = subprocess.Popen([sys.executable, '-c', 'pass'])\n")
    findings = check_pool_file(str(p))
    assert [f.rule_id for f in findings] == ["QSM-POOL-REAP"]
    assert "<module>" in findings[0].location


def test_bounded_serve_idioms_are_clean(tmp_path):
    """True-negative pin: the serving plane's own idioms — stop-flag
    loop tests, settimeout-bounded polls, maxsize'd queues — must not
    be flagged (a pass that cries wolf on the sanctioned forms gets
    whitelisted into uselessness)."""
    from qsm_tpu.analysis.serve_passes import check_serve_file

    p = tmp_path / "stub.py"
    p.write_text(
        "import queue\n"
        "class S:\n"
        "    def loop(self, sock):\n"
        "        q = queue.Queue(maxsize=8)\n"
        "        sock.settimeout(0.2)\n"
        "        while True:\n"
        "            try:\n"
        "                q.put(sock.accept(), block=False)\n"
        "            except OSError:\n"
        "                continue\n"
        "    def gated(self, sock, stop):\n"
        "        while not stop.is_set():\n"
        "            sock.recv(4096)\n")
    assert check_serve_file(str(p)) == []


def test_queue_maxsize_zero_is_flagged_as_unbounded(tmp_path):
    """The stdlib spells 'infinite' as Queue(maxsize=0) (negatives
    too): an explicit-zero bound is exactly the unbounded hazard, not a
    bound — the pass must not wave it through."""
    from qsm_tpu.analysis.serve_passes import check_serve_file

    p = tmp_path / "stub.py"
    p.write_text("import queue\n"
                 "a = queue.Queue(maxsize=0)\n"
                 "b = queue.Queue(0)\n"
                 "c = queue.Queue(maxsize=-1)\n"
                 "d = queue.Queue(maxsize=8)   # ok: a real bound\n")
    findings = check_serve_file(str(p))
    assert [f.rule_id for f in findings] == ["QSM-SERVE-UNBOUNDED"] * 3


def test_subprocess_with_timeout_is_clean(tmp_path):
    """True-negative pin: the repo's own bounded-subprocess idiom
    (probe/compile calls always pass timeout=) must not be flagged."""
    from qsm_tpu.analysis.resilience_passes import check_resilience_file

    p = tmp_path / "stub.py"
    p.write_text("import subprocess, sys\n"
                 "def probe(t):\n"
                 "    return subprocess.run([sys.executable, '-c', "
                 "'pass'], capture_output=True, timeout=t)\n")
    assert check_resilience_file(str(p)) == []


def test_dtype_pass_flags_float_state():
    class FloatStateCas(fixtures.ParityBrokenCasSpec):
        def step_jax(self, state, cmd, arg, resp):
            import jax.numpy as jnp

            ns, ok = super().step_jax(state, cmd, arg, resp)
            return ns.astype(jnp.float32), ok  # seeded promotion

    findings = check_step_dtypes(FloatStateCas(), "fixture:float_state")
    assert any(f.rule_id == "QSM-KERN-DTYPE" and f.severity == ERROR
               for f in findings)


def test_vmem_estimator_brackets_the_envelope():
    """The static estimator agrees with the kernel's own ceiling
    (MAX_PALLAS_STATES fits) and rejects what that ceiling exists to
    exclude (the S=1280 scalarized queue/stack shadows)."""
    from qsm_tpu.ops.pallas_kernel import (MAX_PALLAS_OPS,
                                           MAX_PALLAS_STATES, PallasTPU)

    fits = pallas_vmem_bytes(MAX_PALLAS_OPS, MAX_PALLAS_STATES,
                             PallasTPU.LANES,
                             PallasTPU.PALLAS_CACHE_SLOTS)
    blows = pallas_vmem_bytes(MAX_PALLAS_OPS, 1280, PallasTPU.LANES,
                              PallasTPU.PALLAS_CACHE_SLOTS)
    assert fits <= VMEM_BUDGET_BYTES < blows


# --- whitelist and CLI plumbing -------------------------------------------

def test_whitelist_filters_exact_rule_and_prefix():
    wl = Whitelist([("QSM-DET-TIME", "qsm_tpu/sched/pool.py")])
    hit = Finding("warning", "QSM-DET-TIME",
                  "qsm_tpu/sched/pool.py:123", "m")
    other_rule = Finding("error", "QSM-DET-RANDOM",
                         "qsm_tpu/sched/pool.py:123", "m")
    other_loc = Finding("warning", "QSM-DET-TIME",
                        "qsm_tpu/sched/scheduler.py:5", "m")
    assert wl.allows(hit)
    assert not wl.allows(other_rule)
    assert not wl.allows(other_loc)
    assert Whitelist([("QSM-DET-TIME", "*")]).allows(other_loc)


def test_cli_lint_json_and_exit_codes(tmp_path, capsys):
    """`python -m qsm_tpu lint --json` is the probe_watcher/CI archive
    form: one JSON document, exit 0 on a clean corpus, findings carried
    in full."""
    from qsm_tpu.utils.cli import main

    out_path = tmp_path / "lint.json"
    rc = main(["lint", "--json", "--models", "cas", "--no-retrace",
               "--out", str(out_path)])
    doc = json.loads(capsys.readouterr().out.strip())
    assert rc == 0 and doc["ok"] is True
    assert doc["tool"] == "qsmlint" and doc["errors"] == 0
    assert doc["models"] == ["cas"]
    # the --out archive is the same document
    assert json.loads(out_path.read_text())["ok"] is True


def test_cli_lint_usage_errors_exit_2_not_1(capsys, tmp_path):
    """Exit-code contract: 1 is reserved for REAL FINDINGS (the watcher
    refuses window seizes on it); usage mistakes exit 2."""
    from qsm_tpu.utils.cli import main

    assert main(["lint", "--models", "nope"]) == 2
    assert "unknown model" in capsys.readouterr().err
    assert main(["lint", "--whitelist", str(tmp_path / "absent")]) == 2


def test_cli_lint_analyzer_crash_exits_3_not_1(monkeypatch):
    """Analyzer trouble must exit 3 so probe_watcher waves it through
    instead of refusing every healed window of the round."""
    import qsm_tpu.analysis as analysis
    from qsm_tpu.utils.cli import main

    monkeypatch.setattr(analysis, "run_lint",
                        lambda **kw: (_ for _ in ()).throw(
                            RuntimeError("boom")))
    assert main(["lint", "--no-retrace", "--models", "cas"]) == 3


def test_report_json_shape(report):
    doc = json.loads(report.to_json())
    assert set(doc) >= {"tool", "errors", "warnings", "findings",
                        "whitelisted", "ok", "seconds", "passes",
                        "models"}
    for f in doc["findings"] + doc["whitelisted"]:
        assert set(f) == {"severity", "rule_id", "location", "message",
                          "fix_hint"}
