"""Window-seize logic of tools/probe_watcher.py — the machinery that
banked the round's only real-TPU evidence.  Everything here runs with
monkeypatched subprocess/probe layers: no chip, no sleeps, no bench
runs; what is tested is the DECISION logic (what gets chased, what gets
kept, what may never be clobbered)."""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import time

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture()
def w(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "watcher_under_test", os.path.join(REPO, "tools",
                                           "probe_watcher.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # sandbox every path the module touches
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    monkeypatch.setattr(mod, "LOG", str(tmp_path / "probe_log.jsonl"))
    monkeypatch.setattr(mod, "WINDOW_ARTIFACT",
                        str(tmp_path / "BENCH_TPU_WINDOW.json"))
    mod.COMMITTED_COPIES = {
        str(tmp_path / "BENCH_TPU_WINDOW.json"):
            str(tmp_path / "BENCH_TPU_r05.json"),
        str(tmp_path / "BENCH_SCALE_TPU_WINDOW.json"):
            str(tmp_path / "BENCH_SCALE_TPU_r05.json"),
    }
    monkeypatch.setattr(mod, "CAPTURES_LOG",
                        str(tmp_path / "BENCH_TPU_CAPTURES_r05.jsonl"))
    monkeypatch.setattr(mod, "LINT_ARTIFACT",
                        str(tmp_path / "LINT_r05.json"))
    # the pre-seize lint gate runs a real analysis subprocess; stub it
    # open here (its own decision logic is tested separately below)
    monkeypatch.setattr(mod, "_preflight_lint", lambda *a, **k: True)
    return mod


def _events(mod):
    try:
        with open(mod.LOG) as f:
            return [json.loads(ln) for ln in f if ln.strip()]
    except OSError:
        return []


def test_tool_rows_excludes_skipped_markers(w, tmp_path):
    p = tmp_path / "art.json"
    rows = [{"artifact": "x", "device_fallback": None},
            {"batch": 4096, "rate_h_per_s": 1.0},
            {"batch": 16384, "skipped": "time box exhausted"},
            {"variant": "oneshot", "rate_h_per_s": 2.0}]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert w._tool_rows(str(p)) == 2  # header and skipped don't count
    assert w._tool_rows(str(tmp_path / "missing.json")) == 0


def test_seize_all_banked_is_silent(w, tmp_path, monkeypatch):
    """With every artifact banked — and the headline's stamped settings
    matching what the banked scan decided — a healthy probe cycle must
    neither log event spam nor launch any subprocess (the round-4 review
    found the pre-fix watcher appending ~5 fake-success lines per
    cycle)."""
    (tmp_path / "BENCH_TPU_WINDOW.json").write_text(
        json.dumps({"extras": {"device_batch": 4096, "unroll": 8}}))
    # FULL-row artifacts: completeness is row-count-based now (a
    # header-only bank from a timed-out window gets chased resumably)
    (tmp_path / "BENCH_CONFIGS_TPU_WINDOW.json").write_text(
        "\n".join(["{}"] + [json.dumps({"cell": f"m{i}", "rate": 1.0})
                            for i in range(w.CONFIGS_MIN_ROWS)]) + "\n")
    (tmp_path / "BENCH_E2E_TPU_WINDOW.json").write_text(
        "\n".join(["{}"] + [json.dumps({"cell": f"r{i}", "ok": True})
                            for i in range(w.E2E_MIN_ROWS)]) + "\n")
    scale = [{"h": 1, "device_fallback": None}] + [
        {"batch": b, "rate_h_per_s": 1.0, "wrong": 0}
        for b in (4096, 16384, 65536, 262144)] + [
        {"batch": 4096, "variant": "unroll1", "rate_h_per_s": 1.0,
         "wrong": 0},
        {"batch": 4096, "variant": "pallas", "rate_h_per_s": 1.0,
         "wrong": 0},
        {"variant": "budget2k", "rate_h_per_s": 1.0, "wrong": 0}]
    (tmp_path / "BENCH_SCALE_TPU_WINDOW.json").write_text(
        "\n".join(json.dumps(r) for r in scale) + "\n")
    pdir = tmp_path / "profiles" / "r05_tpu" / "plugins"
    pdir.mkdir(parents=True)
    (pdir / "t.xplane.pb").write_bytes(b"x")
    (tmp_path / "BENCH_SWEEP_r05.json").write_text(
        json.dumps({"device_fallback": None}))

    def boom(*a, **k):
        raise AssertionError("no subprocess may run when all is banked")

    monkeypatch.setattr(w.subprocess, "run", boom)
    assert w._seize_window(600.0) is True
    assert _events(w) == []


def test_fresh_headline_still_chases_missing_upgrades(w, tmp_path,
                                                      monkeypatch):
    """A <3h-old headline must NOT suppress missing configs/e2e/scale —
    the round-4 window banked the headline and closed before the
    upgrades; a same-round reopen must chase them."""
    (tmp_path / "BENCH_TPU_WINDOW.json").write_text(
        json.dumps({"extras": {"device_batch": 4096, "unroll": 8}}))
    chased = []
    monkeypatch.setattr(
        w, "_run_tool",
        lambda script, out, timeout, label, min_rows=0, extra_args=(), resumable=False:
            chased.append(label))
    monkeypatch.setattr(
        w, "_run_window_bench",
        lambda *a, **k: chased.append(a[2]) or True)
    w._seize_window(600.0)
    assert "window_configs" in chased
    assert "window_e2e" in chased
    assert "window_scale" in chased
    # the scan outranks everything: round-4's windows died headline-first
    assert chased[0] == "window_scale"
    # headline bench was NOT re-run (fresh, settings current since no
    # banked scan contradicts them), only logged as kept
    assert "window_bench_headline" not in chased
    assert any(e.get("event") == "window_bench_headline"
               and "kept" in e.get("detail", "")
               for e in _events(w))


def test_stale_headline_is_rebenched(w, tmp_path, monkeypatch):
    art = tmp_path / "BENCH_TPU_WINDOW.json"
    art.write_text(json.dumps({"extras": {"device_batch": 4096,
                                          "unroll": 8}}))
    old = time.time() - 4 * 3600
    os.utime(art, (old, old))
    ran = []
    monkeypatch.setattr(
        w, "_run_tool",
        lambda script, out, timeout, label, min_rows=0, extra_args=(), resumable=False:
            ran.append(label))
    monkeypatch.setattr(
        w, "_run_window_bench",
        lambda *a, **k: ran.append(a[2]) or True)
    w._seize_window(600.0)
    # scan first (the decision), then the stale headline re-bench
    assert ran[0] == "window_scale"
    assert "window_bench_headline" in ran


def test_scale_decision_triggers_headline_rescale(w, tmp_path,
                                                  monkeypatch):
    """When the banked scan's decision (width OR unroll) differs from the
    settings the banked headline ran with, the headline is re-benched in
    the same window even though it is fresh."""
    (tmp_path / "BENCH_TPU_WINDOW.json").write_text(
        json.dumps({"extras": {"device_batch": 4096, "unroll": 8}}))
    scale = [{"artifact": "bench_scale", "device_fallback": None},
             {"batch": 4096, "rate_h_per_s": 100.0, "wrong": 0},
             {"batch": 65536, "rate_h_per_s": 900.0, "wrong": 0}]
    (tmp_path / "BENCH_SCALE_TPU_WINDOW.json").write_text(
        "\n".join(json.dumps(r) for r in scale) + "\n")
    ran = []
    monkeypatch.setattr(
        w, "_run_tool",
        lambda script, out, timeout, label, min_rows=0, extra_args=(), resumable=False:
            ran.append(label))
    monkeypatch.setattr(
        w, "_run_window_bench",
        lambda *a, **k: ran.append(a[2]) or True)
    w._seize_window(600.0)
    assert "window_bench_headline" in ran  # 65536 ≠ banked 4096


def test_scale_unroll_decision_triggers_headline_rescale(w, tmp_path,
                                                         monkeypatch):
    """The scan deciding unroll1 invalidates a headline that ran
    unroll8 — the exact regression the round-4 windows could not
    attribute."""
    (tmp_path / "BENCH_TPU_WINDOW.json").write_text(
        json.dumps({"extras": {"device_batch": 4096, "unroll": 8}}))
    scale = [{"artifact": "bench_scale", "device_fallback": None},
             {"batch": 4096, "rate_h_per_s": 60.0, "wrong": 0},
             {"batch": 4096, "variant": "unroll1",
              "rate_h_per_s": 105.0, "wrong": 0}]
    (tmp_path / "BENCH_SCALE_TPU_WINDOW.json").write_text(
        "\n".join(json.dumps(r) for r in scale) + "\n")
    ran = []
    monkeypatch.setattr(
        w, "_run_tool",
        lambda script, out, timeout, label, min_rows=0, extra_args=(), resumable=False:
            ran.append(label))
    monkeypatch.setattr(
        w, "_run_window_bench",
        lambda *a, **k: ran.append(a[2]) or True)
    w._seize_window(600.0)
    assert "window_bench_headline" in ran  # scan says unroll1 wins


def test_run_tool_timeout_promotion_is_monotonic(w, tmp_path,
                                                 monkeypatch):
    """A timed-out scan's partial tmp is promoted ONLY when it holds more
    measured rows than the existing bank (round-4 review: a header-only
    partial must never clobber banked device rows)."""
    out = tmp_path / "BENCH_SCALE_TPU_WINDOW.json"
    rows = [{"artifact": "s", "device_fallback": None},
            {"batch": 4096, "rate_h_per_s": 1.0},
            {"batch": 16384, "rate_h_per_s": 2.0}]
    out.write_text("\n".join(json.dumps(r) for r in rows) + "\n")

    monkeypatch.setattr(
        w, "probe_default_backend",
        lambda *a, **kw: type("P", (), {"is_device": True, "detail": "tpu"})())

    def fake_run(cmd, **kw):
        # the tool writes a header-only tmp, then "hangs" past timeout
        tmp = cmd[cmd.index("--out") + 1]
        with open(tmp, "w") as f:
            f.write(json.dumps({"artifact": "s", "device_fallback": None})
                    + "\n")
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 1))

    monkeypatch.setattr(w.subprocess, "run", fake_run)
    w._run_tool("bench_scale.py", str(out), 1.0, "window_scale",
                min_rows=5)
    kept = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert len(kept) == 3  # the 2-row bank survived the header-only tmp
    ev = [e for e in _events(w) if e.get("event") == "window_scale"]
    assert ev and ev[-1]["ok"] is False


def test_run_tool_timeout_promotes_bigger_partial(w, tmp_path,
                                                  monkeypatch):
    out = tmp_path / "BENCH_SCALE_TPU_WINDOW.json"

    monkeypatch.setattr(
        w, "probe_default_backend",
        lambda *a, **kw: type("P", (), {"is_device": True, "detail": "tpu"})())

    def fake_run(cmd, **kw):
        tmp = cmd[cmd.index("--out") + 1]
        rows = [{"artifact": "s", "device_fallback": None},
                {"batch": 4096, "rate_h_per_s": 1.0}]
        with open(tmp, "w") as f:
            f.write("\n".join(json.dumps(r) for r in rows) + "\n")
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 1))

    monkeypatch.setattr(w.subprocess, "run", fake_run)
    w._run_tool("bench_scale.py", str(out), 1.0, "window_scale",
                min_rows=5)
    kept = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert len(kept) == 2  # promoted: 1 measured row > 0 banked
    # and the committed twin was banked too
    assert (tmp_path / "BENCH_SCALE_TPU_r05.json").exists()


@pytest.fixture()
def w_lint(tmp_path, monkeypatch):
    """Watcher module with the REAL _preflight_lint (subprocess patched
    per-test) — the `w` fixture stubs the gate open."""
    spec = importlib.util.spec_from_file_location(
        "watcher_lint_under_test", os.path.join(REPO, "tools",
                                                "probe_watcher.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    monkeypatch.setattr(m, "REPO", str(tmp_path))
    monkeypatch.setattr(m, "LOG", str(tmp_path / "probe_log.jsonl"))
    monkeypatch.setattr(m, "LINT_ARTIFACT", str(tmp_path / "LINT.json"))
    return m


def _fake_lint_run(rc):
    def run(cmd, **kw):
        class R:
            returncode = rc
            stdout = '{"tool": "qsmlint"}'
            stderr = ""
        return R()
    return run


def test_lint_gate_refuses_seize_on_error_findings(w_lint, monkeypatch):
    """rc 1 (non-whitelisted error findings) must block the seize — a
    statically-broken kernel/spec may not spend a healing window."""
    monkeypatch.setattr(w_lint.subprocess, "run", _fake_lint_run(1))
    assert w_lint._preflight_lint() is False
    ev = [e for e in _events(w_lint) if e.get("event") == "window_lint"]
    assert ev and ev[-1]["ok"] is False
    # cached: a second call must not re-run the subprocess
    monkeypatch.setattr(w_lint.subprocess, "run",
                        lambda *a, **k: (_ for _ in ()).throw(
                            AssertionError("must be cached")))
    assert w_lint._preflight_lint() is False


def test_lint_gate_waves_through_analyzer_trouble(w_lint, monkeypatch):
    """Analyzer crashes (rc != 0/1) must NOT cost the round its windows:
    seize allowed, warning logged."""
    monkeypatch.setattr(w_lint.subprocess, "run", _fake_lint_run(2))
    assert w_lint._preflight_lint() is True
    ev = [e for e in _events(w_lint) if e.get("event") == "window_lint"]
    assert ev and "waved through" in ev[-1]["detail"]


def test_lint_gate_does_not_cache_transient_trouble(w_lint, monkeypatch):
    """A timeout (pegged machine) is waved through but NOT cached —
    caching ok=True under the fingerprint would silently disarm the
    gate for these sources for the rest of the round."""
    def timeout_run(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 1))

    monkeypatch.setattr(w_lint.subprocess, "run", timeout_run)
    assert w_lint._preflight_lint() is True  # waved through
    # next call re-runs (not cached) and sees the real verdict
    monkeypatch.setattr(w_lint.subprocess, "run", _fake_lint_run(1))
    assert w_lint._preflight_lint() is False


def test_lint_gate_cache_clears_when_sources_change(w_lint, tmp_path,
                                                    monkeypatch):
    """The cached verdict is keyed on a source fingerprint: a refusal
    cached before a fix must clear once the sources change — otherwise
    every later window of the round is refused on a stale verdict."""
    src = tmp_path / "qsm_tpu"
    src.mkdir()
    f = src / "mod.py"
    f.write_text("x = 1\n")
    monkeypatch.setattr(w_lint.subprocess, "run", _fake_lint_run(1))
    assert w_lint._preflight_lint() is False
    # same sources: cached refusal, no re-run
    monkeypatch.setattr(w_lint.subprocess, "run", _fake_lint_run(0))
    assert w_lint._preflight_lint() is False
    # "fix lands": mtime moves, fingerprint changes, gate re-lints
    os.utime(f, (time.time() + 10, time.time() + 10))
    assert w_lint._preflight_lint() is True
    # whitelisting a finding touches ONLY .qsmlint — that too must
    # clear the cache (the documented accept-a-finding workflow)
    monkeypatch.setattr(w_lint.subprocess, "run", _fake_lint_run(1))
    assert w_lint._preflight_lint() is True  # still cached
    (tmp_path / ".qsmlint").write_text("# reviewed\n")
    os.utime(tmp_path / ".qsmlint",
             (time.time() + 20, time.time() + 20))
    assert w_lint._preflight_lint() is False  # re-linted


def test_lint_gate_clean_allows_seize(w_lint, monkeypatch):
    monkeypatch.setattr(w_lint.subprocess, "run", _fake_lint_run(0))
    assert w_lint._preflight_lint() is True
    ev = [e for e in _events(w_lint) if e.get("event") == "window_lint"]
    assert ev and ev[-1]["ok"] is True and ev[-1]["detail"] == "clean"


def test_scale_completeness_is_content_based(w, tmp_path):
    """A pre-ladder-growth artifact (complete for the OLD widths) must
    read as incomplete so the new widest row gets chased — a row-count
    gate went stale exactly this way in round 4."""
    p = tmp_path / "BENCH_SCALE_TPU_WINDOW.json"
    rows = [{"artifact": "s", "device_fallback": None}] + [
        {"batch": b, "rate_h_per_s": 1.0, "wrong": 0}
        for b in (4096, 16384, 65536)] + [
        {"variant": "unroll1", "rate_h_per_s": 1.0},
        {"variant": "pallas", "error": "Mosaic lowering failed"},
        {"variant": "budget2k", "rate_h_per_s": 1.0}]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert w._scale_complete(str(p)) is False  # 262144 missing

    rows.insert(5, {"batch": 262144,
                    "error": "RESOURCE_EXHAUSTED"})  # an answer, not a gap
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert w._scale_complete(str(p)) is True

    # CPU-fallback header is never complete
    rows[0]["device_fallback"] = "cpu"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert w._scale_complete(str(p)) is False


def test_scale_complete_distrusts_truncated_artifact(w, tmp_path):
    """A scan killed mid-write under a pre-journal scheme leaves half a
    JSON line at the tail; completeness must read False — a window that
    re-runs a complete-looking-but-corrupt scan loses minutes, a window
    that trusts one loses the whole diagnostic set."""
    p = tmp_path / "BENCH_SCALE_TPU_WINDOW.json"
    rows = [{"artifact": "s", "device_fallback": None}] + [
        {"batch": b, "rate_h_per_s": 1.0, "wrong": 0}
        for b in (4096, 16384, 65536, 262144)] + [
        {"variant": "unroll1", "rate_h_per_s": 1.0},
        {"variant": "pallas", "rate_h_per_s": 1.0},
        {"variant": "budget2k", "rate_h_per_s": 1.0}]
    whole = "\n".join(json.dumps(r) for r in rows) + "\n"
    p.write_text(whole)
    assert w._scale_complete(str(p)) is True  # the uncut control

    p.write_text(whole + '{"variant": "budget2k", "rate_h_')  # mid-write
    assert w._scale_complete(str(p)) is False

    p.write_text("")  # zero-byte artifact (killed before the header)
    assert w._scale_complete(str(p)) is False
    assert w._scale_complete(str(tmp_path / "absent.json")) is False


def test_tool_rows_counts_only_parseable_measured_rows(w, tmp_path):
    """_tool_rows against a mid-write tail: the garbled line is not a
    row, the intact measured rows before it still count (promotion and
    min_rows gating both ride this number), and a header-only or
    missing artifact counts zero."""
    p = tmp_path / "art.json"
    p.write_text(
        json.dumps({"artifact": "x", "device_fallback": None}) + "\n"
        + json.dumps({"batch": 4096, "rate_h_per_s": 1.0}) + "\n"
        + json.dumps({"batch": 16384, "skipped": "time box"}) + "\n"
        + '{"batch": 65536, "rate_h')  # killed mid-write
    assert w._tool_rows(str(p)) == 1

    p.write_text(json.dumps({"artifact": "x"}) + "\n")
    assert w._tool_rows(str(p)) == 0  # header only
    assert w._tool_rows(str(tmp_path / "absent.json")) == 0


def test_run_tool_resume_seeds_tmp_and_passes_resume_flag(w, tmp_path,
                                                          monkeypatch):
    """The resumable path end to end: the banked partial is copied to
    the tool's tmp output, --resume rides the command line, and the
    finished scan (more rows than the bank) is promoted."""
    out = tmp_path / "BENCH_SCALE_TPU_WINDOW.json"
    bank = [{"artifact": "s", "device_fallback": None},
            {"cell": "b4096", "batch": 4096, "rate_h_per_s": 1.0}]
    out.write_text("\n".join(json.dumps(r) for r in bank) + "\n")

    monkeypatch.setattr(
        w, "probe_default_backend",
        lambda *a, **kw: type("P", (), {"is_device": True,
                                        "detail": "tpu"})())
    seen = {}

    def fake_run(cmd, **kw):
        tmp = cmd[cmd.index("--out") + 1]
        seen["resume"] = "--resume" in cmd
        # the tool saw the seeded bank (CellJournal would adopt it)...
        seen["seeded_rows"] = len(open(tmp).read().splitlines())
        # ...and finishes the scan
        rows = bank + [{"cell": "b16384", "batch": 16384,
                        "rate_h_per_s": 2.0}]
        with open(tmp, "w") as f:
            f.write("\n".join(json.dumps(r) for r in rows) + "\n")
        return type("R", (), {"returncode": 0, "stdout": "", "stderr": ""})()

    monkeypatch.setattr(w.subprocess, "run", fake_run)
    w._run_tool("bench_scale.py", str(out), 60.0, "window_scale",
                min_rows=2, resumable=True)
    assert seen == {"resume": True, "seeded_rows": 2}
    kept = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert len(kept) == 3  # promoted: the finished scan
    ev = [e for e in _events(w) if e.get("event") == "window_scale"]
    assert ev and ev[-1]["ok"] is True


def test_partial_e2e_and_configs_banks_are_chased_resumably(
        w, tmp_path, monkeypatch):
    """A header-only (or few-row) artifact promoted from a timed-out
    window is NOT complete: the next window must re-run the tool with
    --resume semantics so the banked cells are adopted and only the
    missing ones are measured."""
    (tmp_path / "BENCH_TPU_WINDOW.json").write_text(
        json.dumps({"extras": {"device_batch": 4096, "unroll": 8}}))
    (tmp_path / "BENCH_E2E_TPU_WINDOW.json").write_text(
        "{}\n" + json.dumps({"cell": "memo:atomic:tb1", "ok": True})
        + "\n")
    (tmp_path / "BENCH_CONFIGS_TPU_WINDOW.json").write_text("{}\n")
    calls = []
    monkeypatch.setattr(
        w, "_run_tool",
        lambda script, out, timeout, label, min_rows=0, extra_args=(),
        resumable=False: calls.append((label, min_rows, resumable)))
    monkeypatch.setattr(w, "_run_window_bench", lambda *a, **k: True)
    w._seize_window(600.0)
    assert ("window_e2e", w.E2E_MIN_ROWS, True) in calls
    assert ("window_configs", w.CONFIGS_MIN_ROWS, True) in calls


def test_probe_log_compaction_keeps_device_and_event_rows(
        w, tmp_path, monkeypatch):
    """The watcher-invoked compactor (tools/soak_prune.py
    --compact-probe-log): device-hit rows and event rows survive
    forever, failures keep only a bounded tail, the rewrite is atomic,
    and the compaction logs its own event row."""
    rows = [json.dumps({"ok": False, "is_device": False, "ts": i,
                        "detail": "wedged"}) for i in range(30)]
    rows.insert(5, json.dumps({"ok": True, "is_device": True,
                               "platform": "tpu", "ts": 1000}))
    rows.insert(12, json.dumps({"event": "window_lint", "ok": True}))
    log = tmp_path / "probe_log.jsonl"
    log.write_text("\n".join(rows) + "\n")
    monkeypatch.setattr(w, "_PROBE_LOG_SIZE_FLOOR", 0)
    monkeypatch.setattr(w, "PROBE_LOG_COMPACT_ROWS", 10)
    monkeypatch.setattr(w, "PROBE_LOG_KEEP_FAILURES", 4)
    w._maybe_compact_probe_log()
    kept = [json.loads(ln) for ln in log.read_text().splitlines()
            if ln.strip()]
    assert sum(1 for r in kept if r.get("is_device")) == 1
    assert any(r.get("event") == "window_lint" for r in kept)
    # the compactor's own log line landed after the rewrite
    compacts = [r for r in kept if r.get("event") == "probe_log_compact"]
    assert len(compacts) == 1 and compacts[0]["ok"] is True
    assert compacts[0]["rows_before"] == 32
    failures = [r for r in kept
                if not r.get("is_device") and "event" not in r]
    assert len(failures) == 4
    assert [r["ts"] for r in failures] == [26, 27, 28, 29]  # the tail


def test_probe_log_compaction_is_a_noop_below_threshold(
        w, tmp_path, monkeypatch):
    log = tmp_path / "probe_log.jsonl"
    log.write_text(json.dumps({"ok": False, "is_device": False}) + "\n")
    before = log.read_text()
    monkeypatch.setattr(w, "_PROBE_LOG_SIZE_FLOOR", 0)
    monkeypatch.setattr(w, "PROBE_LOG_COMPACT_ROWS", 10)
    calls = []
    monkeypatch.setattr(w.subprocess, "run",
                        lambda *a, **k: calls.append(a))
    w._maybe_compact_probe_log()
    assert log.read_text() == before and not calls
