# qsm_tpu CI/tooling entry points.
#
# `lint-gate` is the static-analysis gate: it runs every registered
# qsmlint pass family (a–o, docs/ANALYSIS.md) over the full tree,
# archives the JSON findings document to LINT_r20.json (the artifact
# probe_watcher also refreshes before every window seize) and FAILS
# (exit 1) on any non-whitelisted error-severity finding — including
# QSM-PROTO-DRIFT when the committed PROTOCOL.json no longer matches a
# fresh extraction (`make protocol` regenerates it).  The on-disk
# result cache (.qsmlint-cache.json) keeps a warm full-tree run in the
# low seconds; CI lanes that want diff-scoped speed use `lint-changed`.

PYTHON ?= python
# keep in lockstep with tools/probe_watcher.py LINT_ROUND (the watcher
# archives the same document before every window seize)
LINT_ARTIFACT ?= LINT_r20.json

# P-compositionality bench (tools/bench_pcomp.py): host-only — no TPU
# window needed — on CellJournal --resume rails; refreshes the
# committed BENCH_PCOMP artifact (kv 64/256/1024 decomposed vs whole,
# oracle-verified, stitched witnesses replayed)
PCOMP_ARTIFACT ?= BENCH_PCOMP_r09.json

# Batched-shrink bench (tools/bench_shrink.py): host-only, CellJournal
# --resume rails; refreshes the committed BENCH_SHRINK artifact
# (frontier-at-once vs one-at-a-time on racy kv/cas 64-op failing
# corpora: engine-call ratio, audited 1-minimality, serve-verb parity)
SHRINK_ARTIFACT ?= BENCH_SHRINK_r10.json

# Obs-overhead bench (tools/bench_obs.py): host-only, CellJournal
# --resume rails; refreshes the committed BENCH_OBS artifact (serve
# path with obs absent / tracing off / tracing on — the ≤5%
# tracing-off gate of docs/OBSERVABILITY.md — plus the r15 fleet
# cells: span collection on/off through a 2-node router and the
# federated /metrics scrape latency)
OBS_ARTIFACT ?= BENCH_OBS_r15.json

# Fleet soak (tools/bench_fleet.py): host-only, CellJournal --resume
# rails; refreshes the committed BENCH_FLEET artifact (1/2/3-node
# fleets on a recorded check+shrink+pcomp mix with kill-node-mid-soak,
# wedge, partition and rolling-restart chaos cells — zero wrong
# verdicts, zero lost banked verdicts — plus the r13 router-HA cells:
# kill/wedge the ACTIVE router (lease takeover, split-brain refusal)
# and router-dead gossip convergence; docs/SERVING.md "Fleet")
FLEET_ARTIFACT ?= BENCH_FLEET_r13.json

# Monitor bench (tools/bench_monitor.py): host-only, CellJournal
# --resume rails; refreshes the committed BENCH_MONITOR artifact
# (streamed vs re-check-from-scratch on a growing 1k-event stream,
# decided-prefix bank resume, flip-to-push latency, streamed-vs-oneshot
# parity soak at zero wrong verdicts; docs/MONITOR.md)
MONITOR_ARTIFACT ?= BENCH_MONITOR_r14.json

# Generation bench (tools/bench_gen.py): host-only, CellJournal
# --resume rails; refreshes the committed BENCH_GEN artifact (steered
# vs unsteered fuzzing at matched engine-call budget — ≥3× flips or
# nodes/history on ≥2 families — every flip re-found by a fresh memo
# oracle, witnesses replayed via verify_witness, and the 2-node
# closed-loop soak at zero wrong verdicts with SLO health exit 0;
# docs/GENERATION.md)
GEN_ARTIFACT ?= BENCH_GEN_r17.json

# Durable-session chaos soak (tools/soak_sessions.py): host-only,
# CellJournal --resume rails; refreshes the committed BENCH_SESSIONS
# artifact (≥1000 concurrent sessions held open through a rolling
# SIGKILL restart of all three nodes, a SIGKILL of the active router
# with standby takeover off the shared lease + session-journal stores,
# and one node leave + one node join with handoff — zero wrong
# verdicts, zero lost flips, every resume riding banked decided
# prefixes; docs/MONITOR.md "Durability")
SESSIONS_ARTIFACT ?= BENCH_SESSIONS_r18.json

# Mesh-dispatch bench (tools/bench_mesh.py): host-only — forced
# virtual CPU devices (--xla_force_host_platform_device_count) stand
# in for the lane axis — on CellJournal --resume rails; refreshes the
# committed BENCH_MESH artifact (lanes/sec at mesh widths 1/2/4/8 on
# the four model families with kv pcomp-split, bit-identical
# verdict/witness/shrink/monitor parity across every width vs a fresh
# CPU oracle, and the 3-vs-1-node fleet cell re-run under 8 forced
# devices to DECIDE the previously waived ratio_n3_vs_n1 gate)
MESH_ARTIFACT ?= BENCH_MESH_r19.json

# Device-work-queue bench (tools/bench_devq.py): host-only — a forced
# 8-virtual-device CPU mesh stands in for the seized window — on
# CellJournal --resume rails; refreshes the committed BENCH_DEVQ
# artifact (four planes banked, a simulated window drained in score
# order with every verdict re-proved by a fresh host oracle at ZERO
# wrong verdicts, SIGKILL-mid-drain exactly-once resume, the matched
# host-ladder baseline, and window_utilization >= 0.8; docs/WINDOWS.md)
DEVQ_ARTIFACT ?= BENCH_DEVQ_r20.json

.PHONY: lint-gate lint-changed lint-sarif protocol test bench-pcomp \
	bench-shrink bench-obs bench-fleet bench-monitor bench-gen \
	soak-sessions bench-mesh bench-devq bench-report

lint-gate:
	$(PYTHON) -m qsm_tpu lint --json --out $(LINT_ARTIFACT)

# regenerate the committed wire-contract artifact (PROTOCOL.json +
# docs/PROTOCOL.md) from a fresh static extraction; lint family (l)
# fails the gate (QSM-PROTO-DRIFT) whenever a protocol edit lands
# without re-running this
protocol:
	$(PYTHON) -m qsm_tpu.analysis.protocol_model

lint-changed:
	$(PYTHON) -m qsm_tpu lint --changed $(or $(REF),HEAD)

lint-sarif:
	$(PYTHON) -m qsm_tpu lint --json --out $(LINT_ARTIFACT) \
		--sarif $(LINT_ARTIFACT:.json=.sarif)

bench-pcomp:
	JAX_PLATFORMS=cpu $(PYTHON) tools/bench_pcomp.py \
		--out $(PCOMP_ARTIFACT) --resume

bench-shrink:
	JAX_PLATFORMS=cpu $(PYTHON) tools/bench_shrink.py \
		--out $(SHRINK_ARTIFACT) --resume

bench-obs:
	JAX_PLATFORMS=cpu $(PYTHON) tools/bench_obs.py \
		--out $(OBS_ARTIFACT) --resume

bench-fleet:
	JAX_PLATFORMS=cpu $(PYTHON) tools/bench_fleet.py \
		--out $(FLEET_ARTIFACT) --resume

bench-monitor:
	JAX_PLATFORMS=cpu $(PYTHON) tools/bench_monitor.py \
		--out $(MONITOR_ARTIFACT) --resume

bench-gen:
	JAX_PLATFORMS=cpu $(PYTHON) tools/bench_gen.py \
		--out $(GEN_ARTIFACT) --resume

soak-sessions:
	JAX_PLATFORMS=cpu $(PYTHON) tools/soak_sessions.py \
		--out $(SESSIONS_ARTIFACT) --resume

# NOTE: no JAX_PLATFORMS pin here — the bench spawns its own children
# under forced_host_device_env (which sets the platform AND the
# forced device count per child)
bench-mesh:
	$(PYTHON) tools/bench_mesh.py \
		--out $(MESH_ARTIFACT) --resume

# same no-pin rationale as bench-mesh: the simulated window children
# get their forced device count from forced_host_device_env
bench-devq:
	$(PYTHON) tools/bench_devq.py \
		--out $(DEVQ_ARTIFACT) --resume

# Aggregate every committed BENCH_*.json into one per-round trend
# table (BENCH_REPORT.md + BENCH_REPORT.json, atomic + deterministic)
bench-report:
	JAX_PLATFORMS=cpu $(PYTHON) tools/bench_report.py

# the tier-1 quick lane (ROADMAP.md has the full pinned command)
test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow'
