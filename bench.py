"""Headline benchmark — histories/sec linearized at 32 ops × 8 pids.

Measures the batched ``JaxTPU`` Wing–Gong kernel against two host checkers:

* ``WingGongCPU`` (memo-less) — the reference's checker reimplemented
  faithfully, the baseline denominator defined in BASELINE.md (the Haskell
  original published no numbers);
* ``WingGongCPU(memo=True)`` — OUR best host checker (Lowe-style cache).
  ``vs_best_cpu`` is the honest headline: the device must beat this one,
  not just the naive oracle (VERDICT.md round 1, "What's weak" #2).

Prints ONE JSON line:
    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...,
     "vs_best_cpu": ..., "extras": {...}}

Robustness contract (VERDICT.md round 1, "Next round" #1): this script must
never hang and never die with a raw traceback.  The real chip is probed from
a subprocess with a bounded timeout; if the probe fails (wedged tunnel), the
same kernel is measured on the JAX CPU platform at reduced scale and the JSON
line says so honestly (``extras.device_fallback``).  Unexpected errors emit a
diagnostic JSON line with ``"error"`` and exit 1.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

import numpy as np

N_PIDS = 8
N_OPS = 32

# Round-long probe attempts (tools/probe_watcher.py appends one JSON line
# per bounded probe).  The BENCH artifact must reflect the best probe of the
# round, not one instant (VERDICT.md round 2, "Next round" #1).
PROBE_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "probe_log.jsonl")


def _append_probe_log(probe) -> None:
    try:
        with open(PROBE_LOG, "a") as f:
            f.write(json.dumps({
                "ts": round(time.time(), 1),
                "iso": datetime.datetime.now(
                    datetime.timezone.utc).isoformat(timespec="seconds"),
                "ok": probe.ok, "is_device": probe.is_device,
                "platform": probe.platform, "detail": probe.detail[:300],
                "source": "bench"}) + "\n")
    except OSError:
        pass


def _probe_attempts_summary() -> dict | None:
    """Summarize every probe attempt of the round for extras."""
    try:
        with open(PROBE_LOG) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
    except (OSError, ValueError):
        return None
    if not recs:
        return None
    return {
        "n": len(recs),
        "device_ok": sum(1 for r in recs if r.get("is_device")),
        "first_iso": recs[0].get("iso"),
        "last_iso": recs[-1].get("iso"),
        "last_detail": recs[-1].get("detail"),
    }


def _scale(on_tpu: bool) -> dict:
    """Benchmark scale: full on the real chip, reduced on the CPU fallback
    (the lockstep vmapped while-loop is orders of magnitude slower on host —
    an unreduced run would take hours, which is its own kind of hang)."""
    if on_tpu:
        return dict(n_unique=512, device_batch=4096, cpu_sample=64,
                    cpu_timebox_s=90.0, reps=3, budget=2_000)
    return dict(n_unique=128, device_batch=256, cpu_sample=24,
                cpu_timebox_s=45.0, reps=1, budget=2_000)


def build_corpus(spec, n_unique: int):
    from qsm_tpu.models import AtomicCasSUT, RacyCasSUT
    from qsm_tpu.utils.corpus import build_corpus as shared

    return shared(spec, (AtomicCasSUT, RacyCasSUT), n=n_unique,
                  n_pids=N_PIDS, max_ops=N_OPS, seed_base=1000,
                  seed_prefix="bench")


def run_bench(on_tpu: bool, probe_detail: str, profile_dir: str | None):
    from qsm_tpu.models import CasSpec
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    sc = _scale(on_tpu)
    spec = CasSpec()
    t0 = time.perf_counter()
    corpus = build_corpus(spec, sc["n_unique"])
    gen_s = time.perf_counter() - t0

    reps = (sc["device_batch"] + len(corpus) - 1) // len(corpus)
    device_corpus = (corpus * reps)[:sc["device_batch"]]

    # --- CPU oracle (baseline denominator), time-boxed -------------------
    # One history at a time so a single pathological interleaving search
    # can't consume the whole bench; the reference checker decides histories
    # one at a time too (SURVEY.md §3.5), so per-history timing is faithful.
    oracle = WingGongCPU(node_budget=20_000_000)
    cpu_verdicts = []
    cpu_times = []
    t0 = time.perf_counter()
    for h in corpus[:sc["cpu_sample"]]:
        t1 = time.perf_counter()
        cpu_verdicts.append(oracle.check_histories(spec, [h])[0])
        cpu_times.append(time.perf_counter() - t1)
        if time.perf_counter() - t0 > sc["cpu_timebox_s"]:
            break
    cpu_s = time.perf_counter() - t0
    cpu_verdicts = np.asarray(cpu_verdicts)
    cpu_rate = len(cpu_verdicts) / cpu_s

    # --- memoised CPU oracle (our best host checker) ---------------------
    memo = WingGongCPU(memo=True)
    t0 = time.perf_counter()
    memo_verdicts = memo.check_histories(spec, corpus)
    memo_rate = len(corpus) / (time.perf_counter() - t0)

    # --- device kernel ---------------------------------------------------
    # Bounded per-history iteration budget keeps batch latency flat; the
    # rare blowups report BUDGET_EXCEEDED and are excluded from the decided
    # count (the property layer resolves them via the oracle — SURVEY.md §7
    # hard-parts #5), so the headline rate only counts decided verdicts.
    backend = JaxTPU(spec, budget=sc["budget"])
    backend.check_histories(spec, device_corpus)  # warmup: compile + run
    backend.lockstep_cost = 0   # count only the timed passes below
    backend.rounds_run = 0
    if profile_dir:
        import jax

        jax.profiler.start_trace(profile_dir)
    t0 = time.perf_counter()
    for _ in range(sc["reps"]):
        dev_verdicts = backend.check_histories(spec, device_corpus)
    dev_s = time.perf_counter() - t0
    if profile_dir:
        import jax

        jax.profiler.stop_trace()
    budget_exceeded = int(np.sum(dev_verdicts == 2))
    dev_rate = sc["reps"] * (len(device_corpus) - budget_exceeded) / dev_s

    # --- parity (trust, but verify) --------------------------------------
    # Device vs BOTH host checkers.  Only count *wrong verdicts*: positions
    # where both sides decided and disagree; BUDGET_EXCEEDED on either side
    # is honest indecision, not a wrong answer.
    def wrong(host, dev):
        both = min(len(host), len(dev))
        hh, dd = np.asarray(host)[:both], np.asarray(dev)[:both]
        bad = (hh != 2) & (dd != 2) & (hh != dd)
        return set(np.nonzero(bad)[0].tolist())

    # union, not sum: a device verdict disagreeing with both host checkers
    # is ONE wrong verdict
    mismatches = len(wrong(cpu_verdicts, dev_verdicts)
                     | wrong(memo_verdicts, dev_verdicts))

    import jax
    return {
        "metric": f"histories_per_sec_linearized_{N_OPS}ops_x_{N_PIDS}pids",
        "value": round(dev_rate, 1),
        "unit": "histories/sec",
        "vs_baseline": round(dev_rate / cpu_rate, 2),
        "vs_best_cpu": round(dev_rate / memo_rate, 2),
        "extras": {
            "cpu_oracle_rate": round(cpu_rate, 3),
            "cpu_oracle_median_s": round(float(np.median(cpu_times)), 4),
            "cpu_memo_oracle_rate": round(memo_rate, 1),
            "cpu_sample": len(cpu_verdicts),
            "corpus_unique": len(corpus),
            "device": str(jax.devices()[0]),
            "device_fallback": None if on_tpu else "cpu",
            "tpu_probe": probe_detail,
            "device_batch": sc["device_batch"],
            "device_budget": sc["budget"],
            "budget_exceeded": budget_exceeded,
            "rescued": backend.rescued,
            "lockstep_iters": backend.lockstep_cost // sc["reps"],  # per pass
            "chunk_rounds": backend.rounds_run // sc["reps"],
            # measured once on the CPU-scale corpus (256 lanes, seed_base
            # 1000) with the round-2 rescue-ladder driver; only comparable
            # to the CPU-fallback run of THIS corpus, so omitted elsewhere
            "lockstep_iters_r2_ladder": (
                3_769_248 if not on_tpu and sc["device_batch"] == 256
                else None),
            "wrong_verdicts_on_sample": mismatches,
            "corpus_gen_sec": round(gen_s, 1),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--probe-timeout", type=float, default=60.0,
                    help="seconds to wait for the TPU backend probe")
    ap.add_argument("--force-cpu", action="store_true",
                    help="skip the probe and bench on the CPU platform")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="capture a jax.profiler trace of the timed device "
                         "passes into DIR")
    ap.add_argument("--retries", type=int, default=2,
                    help="extra spaced probe attempts if the first fails")
    ap.add_argument("--retry-interval", type=float, default=30.0,
                    help="seconds between probe retries")
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import force_cpu_platform, probe_default_backend

    if args.force_cpu:
        probe_detail = "skipped (--force-cpu)"
        on_tpu = False
    else:
        probe = probe_default_backend(args.probe_timeout)
        _append_probe_log(probe)
        probe_detail = probe.detail
        on_tpu = probe.is_device
        if not on_tpu and args.retries > 0:
            # the tunnel has healed mid-round before; a couple of spaced
            # re-probes at bench time are cheap relative to forfeiting the
            # round's only real-chip window
            for _ in range(args.retries):
                time.sleep(args.retry_interval)
                probe = probe_default_backend(args.probe_timeout)
                _append_probe_log(probe)
                probe_detail = probe.detail
                on_tpu = probe.is_device
                if on_tpu:
                    break
    if not on_tpu:
        force_cpu_platform()

    try:
        result = run_bench(on_tpu, probe_detail, args.profile)
    except Exception as e:  # noqa: BLE001 — diagnostic JSON, never a bare crash
        print(json.dumps({
            "metric": f"histories_per_sec_linearized_{N_OPS}ops_x_{N_PIDS}"
                      "pids",
            "value": 0, "unit": "histories/sec", "vs_baseline": 0,
            "error": f"{type(e).__name__}: {e}",
            "extras": {"tpu_probe": probe_detail,
                       "device_fallback": None if on_tpu else "cpu",
                       "probe_attempts": _probe_attempts_summary()},
        }))
        return 1
    result["extras"]["probe_attempts"] = _probe_attempts_summary()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
