"""Headline benchmark — histories/sec linearized at 32 ops × 8 pids.

Measures the batched ``JaxTPU`` Wing–Gong kernel against the ``WingGongCPU``
oracle (the reference's checker reimplemented faithfully — the denominator
defined in BASELINE.md; the Haskell original published no numbers).

Prints ONE JSON line:
    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
``value`` is device throughput (histories/sec); ``vs_baseline`` is the
speedup over the CPU oracle on the same corpus (target ≥100×, BASELINE.json).
"""

from __future__ import annotations

import json
import time

import numpy as np

N_PIDS = 8
N_OPS = 32
N_UNIQUE = 512          # distinct scheduler-produced histories
DEVICE_BATCH = 4096     # corpus tiled up to one full device batch
CPU_SAMPLE = 64         # oracle timed on a subset (it is ~1000x slower)
CPU_TIMEBOX_S = 90.0    # cap the oracle measurement wall-clock
REPS = 3


def build_corpus(spec):
    from qsm_tpu.models import AtomicCasSUT, RacyCasSUT
    from qsm_tpu.utils.corpus import build_corpus as shared

    return shared(spec, (AtomicCasSUT, RacyCasSUT), n=N_UNIQUE,
                  n_pids=N_PIDS, max_ops=N_OPS, seed_base=1000,
                  seed_prefix="bench")


def main():
    from qsm_tpu.models import CasSpec
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    spec = CasSpec()
    t0 = time.perf_counter()
    corpus = build_corpus(spec)
    gen_s = time.perf_counter() - t0

    reps = (DEVICE_BATCH + N_UNIQUE - 1) // N_UNIQUE
    device_corpus = (corpus * reps)[:DEVICE_BATCH]

    # --- CPU oracle (baseline denominator), time-boxed -------------------
    # One history at a time so a single pathological interleaving search
    # can't consume the whole bench; the reference checker decides histories
    # one at a time too (SURVEY.md §3.5), so per-history timing is faithful.
    oracle = WingGongCPU(node_budget=20_000_000)
    cpu_verdicts = []
    t0 = time.perf_counter()
    for h in corpus[:CPU_SAMPLE]:
        cpu_verdicts.append(oracle.check_histories(spec, [h])[0])
        if time.perf_counter() - t0 > CPU_TIMEBOX_S:
            break
    cpu_s = time.perf_counter() - t0
    cpu_verdicts = np.asarray(cpu_verdicts)
    cpu_rate = len(cpu_verdicts) / cpu_s

    # --- device kernel ---------------------------------------------------
    # Bounded per-history iteration budget keeps batch latency flat; the
    # rare blowups report BUDGET_EXCEEDED and are excluded from the decided
    # count (the property layer resolves them via the oracle — SURVEY.md §7
    # hard-parts #5), so the headline rate only counts decided verdicts.
    backend = JaxTPU(spec, budget=200_000)
    backend.check_histories(spec, device_corpus)  # warmup: compile + run
    t0 = time.perf_counter()
    for _ in range(REPS):
        dev_verdicts = backend.check_histories(spec, device_corpus)
    dev_s = time.perf_counter() - t0
    budget = int(np.sum(dev_verdicts == 2))  # Verdict.BUDGET_EXCEEDED
    dev_rate = REPS * (len(device_corpus) - budget) / dev_s

    # --- memoised CPU oracle (our improved checker, for honesty) ---------
    memo = WingGongCPU(memo=True)
    t0 = time.perf_counter()
    memo.check_histories(spec, corpus)
    memo_rate = len(corpus) / (time.perf_counter() - t0)

    # --- parity on the timed sample (trust, but verify) ------------------
    # Only count *wrong verdicts*: positions where both sides decided and
    # disagree.  BUDGET_EXCEEDED on either side is honest indecision.
    both = min(len(cpu_verdicts), len(dev_verdicts))
    c, d = cpu_verdicts[:both], dev_verdicts[:both]
    decided = (c != 2) & (d != 2)
    mismatches = int(np.sum(c[decided] != d[decided]))

    import jax
    print(json.dumps({
        "metric": f"histories_per_sec_linearized_{N_OPS}ops_x_{N_PIDS}pids",
        "value": round(dev_rate, 1),
        "unit": "histories/sec",
        "vs_baseline": round(dev_rate / cpu_rate, 2),
        "extras": {
            "cpu_oracle_rate": round(cpu_rate, 3),
            "cpu_memo_oracle_rate": round(memo_rate, 1),
            "cpu_sample": len(cpu_verdicts),
            "device": str(jax.devices()[0]),
            "device_batch": DEVICE_BATCH,
            "budget_exceeded": budget,
            "wrong_verdicts_on_sample": mismatches,
            "corpus_gen_sec": round(gen_s, 1),
        },
    }))


if __name__ == "__main__":
    main()
