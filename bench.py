"""Headline benchmark — histories/sec linearized at 32 ops × 8 pids.

Measures the batched ``JaxTPU`` Wing–Gong kernel against two host checkers:

* ``WingGongCPU`` (memo-less) — the reference's checker reimplemented
  faithfully, the baseline denominator defined in BASELINE.md (the Haskell
  original published no numbers);
* ``WingGongCPU(memo=True)`` — OUR best host checker (Lowe-style cache).
  ``vs_best_cpu`` is the honest headline: the device must beat this one,
  not just the naive oracle (VERDICT.md round 1, "What's weak" #2).

Prints ONE JSON line:
    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...,
     "vs_best_cpu": ..., "vs_best_host": ..., "extras": {...}}

The line is kept SMALL (≤ ~1.5 kB): the driver that records it tails only
~2 kB of stdout, and round 3's sweep-bloated line lost its ``value`` field
to that window (VERDICT.md round 3, "What's weak" #1).  Bulky data (the
max-ops sweep) goes to a separate committed artifact whose filename is
referenced from ``extras.sweep_file``.

Robustness contract (VERDICT.md round 1, "Next round" #1): this script must
never hang and never die with a raw traceback.  The real chip is probed from
a subprocess with a bounded timeout; if the probe fails (wedged tunnel), the
same kernel is measured on the JAX CPU platform at reduced scale and the JSON
line says so honestly (``extras.device_fallback``).  Unexpected errors emit a
diagnostic JSON line with ``"error"`` and exit 1.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

import numpy as np

N_PIDS = 8
N_OPS = 32

# Round-long probe attempts (tools/probe_watcher.py appends one JSON line
# per bounded probe).  The BENCH artifact must reflect the best probe of the
# round, not one instant (VERDICT.md round 2, "Next round" #1).
PROBE_LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "probe_log.jsonl")


def _append_probe_log(probe) -> None:
    try:
        with open(PROBE_LOG, "a") as f:
            f.write(json.dumps({
                "ts": round(time.time(), 1),
                "iso": datetime.datetime.now(
                    datetime.timezone.utc).isoformat(timespec="seconds"),
                "ok": probe.ok, "is_device": probe.is_device,
                "platform": probe.platform, "detail": probe.detail[:300],
                "source": "bench"}) + "\n")
    except OSError:
        pass


def _probe_attempts_summary() -> dict | None:
    """Summarize every probe attempt of the round for extras."""
    try:
        with open(PROBE_LOG) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
    except (OSError, ValueError):
        return None
    # watcher EVENT lines (seize-stage outcomes) share the log but are not
    # probes; counting them would inflate n / skew last_detail
    recs = [r for r in recs if "event" not in r]
    if not recs:
        return None
    return {
        "n": len(recs),
        "device_ok": sum(1 for r in recs if r.get("is_device")),
        "first_iso": recs[0].get("iso"),
        "last_iso": recs[-1].get("iso"),
        "last_detail": (recs[-1].get("detail") or "")[:120],
    }


# Window artifact: when the round-long watcher catches the tunnel in a
# healed window it runs this script on the real chip and caches the JSON
# line here; if the tunnel is wedged again at bench time, that cached line
# IS the round's headline (with full provenance in extras) — the artifact
# reflects the best probe of the round, not one instant.
WINDOW_ARTIFACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_TPU_WINDOW.json")


WINDOW_MAX_AGE_S = 14 * 3600.0  # a round is ~12 h; reject older leftovers

# single source for round-stamped artifact names (tools/probe_watcher.py
# keeps its own ROUND_TAG for the committed window copies — bump both)
ROUND_TAG = "r05"

# Frozen host-oracle denominators (tools/bench_host_baseline.py, measured
# once per round on ≥100-sample corpora).  VERDICT r4 weak #4: the live
# 14-18-sample oracle re-measurement injected ~30% noise into vs_baseline
# across windows; ratios against the frozen file are comparable across
# windows, with live ratios kept alongside and drift >20% flagged.
FROZEN_HOST_FILE = f"BASELINE_HOST_{ROUND_TAG}.json"


def _frozen_host_rates() -> dict | None:
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               FROZEN_HOST_FILE)) as f:
            d = json.load(f)
        return d if d.get("cpu_oracle_rate") else None
    except (OSError, ValueError):
        return None


def _load_window_artifact() -> dict | None:
    try:
        with open(WINDOW_ARTIFACT) as f:
            result = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(result, dict) or "value" not in result:
        return None
    if result.get("extras", {}).get("device_fallback") is not None:
        return None  # never promote a CPU-fallback line to a TPU headline
    # staleness bound: a stray artifact from a previous round must never
    # become THIS round's headline (the file is gitignored too, but belt
    # and braces — an old mtime also covers hand-copied files)
    try:
        age = time.time() - os.path.getmtime(WINDOW_ARTIFACT)
    except OSError:
        return None
    if age > WINDOW_MAX_AGE_S:
        return None
    return result


def _device_scale_rows(dirpath: str | None = None) -> list:
    """Data rows of the freshest DEVICE-captured bench_scale artifact
    (window copy preferred), or [] when none is usable."""
    here = dirpath or os.path.dirname(os.path.abspath(__file__))
    for name in ("BENCH_SCALE_TPU_WINDOW.json",
                 f"BENCH_SCALE_TPU_{ROUND_TAG}.json"):
        path = os.path.join(here, name)
        try:
            with open(path) as f:
                lines = [json.loads(ln) for ln in f if ln.strip()]
            age = time.time() - os.path.getmtime(path)
        except (OSError, ValueError):
            continue
        if age > WINDOW_MAX_AGE_S:
            continue  # a prior round's measurement may not match this
            # round's kernel; the next window re-scans anyway
        if not lines or lines[0].get("device_fallback") is not None:
            continue
        if len(lines) > 1:
            return lines[1:]
    return []


def best_scale_unroll(dirpath: str | None = None):
    """Unroll setting the on-chip A/B decided, or None when undecided.

    Compares the unroll8 control row against the unroll1 variant at the
    SAME batch width from a device-captured scale artifact (both
    zero-wrong).  Returns ``(unroll, rate)`` for the winner.  The round-4
    windows never measured this on-chip — the only post-unroll datapoint
    regressed 1.7× with everything else confounded (VERDICT r4 weak #3);
    this function is how the headline adopts whichever setting the real
    chip actually prefers."""
    rows = _device_scale_rows(dirpath)
    ok = [r for r in rows if r.get("wrong") == 0 and "error" not in r
          and "skipped" not in r and r.get("rate_h_per_s")]
    u1 = next((r for r in ok if r.get("variant") == "unroll1"), None)
    if u1 is None or u1.get("batch") is None:
        return None
    u8 = next((r for r in ok if "variant" not in r
               and r.get("batch") == u1["batch"]), None)
    if u8 is None:
        return None
    if u1["rate_h_per_s"] > u8["rate_h_per_s"]:
        return 1, float(u1["rate_h_per_s"])
    return 8, float(u8["rate_h_per_s"])


def best_scale_batch(min_gain: float = 1.2, dirpath: str | None = None):
    """Best lockstep batch width from a DEVICE-captured bench_scale
    artifact (tools/bench_scale.py), or None.

    The first real-TPU window showed per-trip latency dominating the
    chunked driver at 4096 lanes; wider batches amortize it.  Adoption
    discipline: only a width the scale scan actually measured on the real
    chip with ZERO wrong verdicts and ≥ ``min_gain`` × the 4096-row rate
    is adopted (the gain gate also bounds the adopted headline's
    wall-clock, which matters inside short healing windows).  Returns
    ``(batch, rate)`` or None."""
    all_rows = _device_scale_rows(dirpath)
    rows = [r for r in all_rows
            if r.get("wrong") == 0 and "error" not in r
            and "skipped" not in r and "variant" not in r
            and r.get("rate_h_per_s")]
    if not rows:
        return None
    base = next((r["rate_h_per_s"] for r in rows if r["batch"] == 4096),
                None)
    # a single timed rep at the adopted width must stay window-sized:
    # reps floors at 1, so batch/rate IS the timed wall-clock (the
    # round-4 window budget was ~116 s; 300 s still fits bench_timeout/2
    # with compile + host-oracle phases around it)
    rows = [r for r in rows
            if r["batch"] / r["rate_h_per_s"] <= 300.0]
    if not rows:
        return None
    best = max(rows, key=lambda r: r["rate_h_per_s"])
    if best["batch"] == 4096:
        return None  # nothing better than the default
    if base is None or best["rate_h_per_s"] < min_gain * base:
        return None  # no validated baseline, or win below the gate
    return int(best["batch"]), float(best["rate_h_per_s"])


def _scale(on_tpu: bool) -> dict:
    """Benchmark scale: full on the real chip, reduced on the CPU fallback
    (the lockstep vmapped while-loop is orders of magnitude slower on host —
    an unreduced run would take hours, which is its own kind of hang)."""
    if on_tpu:
        # reps=1: the round-5 seize runs the scale scan FIRST, so the
        # headline's job is one SHORT timed rep at the adopted
        # configuration (VERDICT r4 task #1: the window buys the
        # decision, not a third 300-440 s headline).  Run-to-run variance
        # is covered by the captures history the watcher appends
        # (BENCH_TPU_CAPTURES_*.jsonl), not by in-run reps.
        sc = dict(n_unique=512, device_batch=4096, cpu_sample=64,
                  cpu_timebox_s=90.0, reps=1, budget=2_000,
                  batch_from_scale=None, unroll=8, unroll_from_scale=None)
        adopted = best_scale_batch()
        if adopted is not None:
            sc["device_batch"] = adopted[0]
            sc["batch_from_scale"] = adopted[0]
        u = best_scale_unroll()
        if u is not None:
            sc["unroll"] = u[0]
            sc["unroll_from_scale"] = u[0]
        return sc
    return dict(n_unique=128, device_batch=256, cpu_sample=24,
                cpu_timebox_s=45.0, reps=1, budget=2_000,
                batch_from_scale=None, unroll=8, unroll_from_scale=None)


def _sweep_cells_measured(sw: dict) -> int:
    """Bucket cells a sweep actually measured (its coverage, for the
    monotonic keep-the-larger-device-capture rule)."""
    n = 0
    for backends in sw.get("cells", {}).values():
        for cell in backends.values():
            n += sum(1 for k in cell if k.isdigit())
    return n


def run_sweep(on_tpu: bool, buckets=None, n_sample=None,
              box_s: float = 60.0, total_box_s: float = 1500.0) -> dict:
    """Measure "max ops solved < 60 s" (BASELINE.json:2 second metric;
    VERDICT.md round 2, "Next round" #4): for CAS and queue, scan op
    buckets 12→128 (96/128 exceed the reference's largest config) per
    backend and report the largest bucket each backend decides a sample
    corpus at with zero BUDGET_EXCEEDED inside the 60 s box (host
    backends: per-history p90 must beat the box too; the batched device
    backend is timed per warm batch).  Early-exits a backend after its
    first unsolved bucket (cost is monotone in ops); backends with a
    native coverage cap stop there with a ``capped_at`` marker."""
    from qsm_tpu.models import AtomicCasSUT, CasSpec, QueueSpec, RacyCasSUT
    from qsm_tpu.models.queue import AtomicQueueSUT, RacyTwoPhaseQueueSUT
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.router import AutoDevice
    from qsm_tpu.ops.segdc import SegDC
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.utils.corpus import build_corpus as shared

    if n_sample is None:
        n_sample = 16 if on_tpu else 8
    if buckets is None:  # 96/128 exceed the reference's
        buckets = (12, 24, 48, 64, 96, 128)
    # largest config — long-context headroom (VERDICT r2 #4: "add buckets
    # beyond 64 if the device can take them")
    # per-backend coverage caps: past the native checker's taken-mask cap
    # the measurement would silently be the Python fallback's
    from qsm_tpu.native import NATIVE_MAX_OPS

    caps = {"cpp": NATIVE_MAX_OPS}

    from qsm_tpu.search.stats import collect_search_stats

    def _cell_search(backend) -> dict | None:
        # every sweep row carries its engine's SearchStats compact form
        # (iters/nodes per history — the search-efficiency plane's cost
        # record, qsm_tpu/search); None only for engines exposing none
        st = collect_search_stats(backend)
        return st.to_compact() if st is not None else None

    def host_cell(backend, spec, corpus):
        times, verds = [], []
        t0 = time.perf_counter()
        for h in corpus:
            t1 = time.perf_counter()
            verds.append(int(backend.check_histories(spec, [h])[0]))
            times.append(time.perf_counter() - t1)
            if time.perf_counter() - t0 > box_s:
                break
        und = sum(1 for v in verds if v == 2)
        p90 = float(np.percentile(times, 90)) if times else float("inf")
        return {
            "attempted": len(times), "of": len(corpus), "undecided": und,
            "median_s": round(float(np.median(times)), 4) if times else None,
            "p90_s": round(p90, 4) if times else None,
            "total_s": round(time.perf_counter() - t0, 2),
            "solved": (len(times) == len(corpus) and und == 0
                       and p90 <= box_s),
            "search": _cell_search(backend),
        }

    def device_cell(make_backend, spec, corpus):
        b = make_backend(spec)
        # one big chunk: sweep cells sit in the smallest batch bucket, so
        # the escalating schedule would only multiply compiles (a real
        # concern inside a short TPU healing window); for combinators the
        # JaxTPU lives at .inner (SegDC) or .plain (AutoDevice) —
        # patching the wrapper would be a silent no-op
        kern = getattr(b, "plain", None) or getattr(b, "inner", b)
        kern.CHUNK_SCHEDULE = (65536,)
        kern.UNROLL = 8  # the production setting (see run_bench)
        t0 = time.perf_counter()
        b.check_histories(spec, corpus)
        first = time.perf_counter() - t0
        t0 = time.perf_counter()
        v = b.check_histories(spec, corpus)
        warm = time.perf_counter() - t0
        und = int((v == 2).sum())
        return {
            "attempted": len(corpus), "of": len(corpus), "undecided": und,
            "batch_warm_s": round(warm, 3),
            "batch_first_s": round(first, 2),
            "per_history_s": round(warm / len(corpus), 4),
            "solved": und == 0 and warm <= box_s,
            "search": _cell_search(b),
        }

    # queue has no scalar step table; on the host-CPU fallback the lockstep
    # loop pays vmapped step_jax per iteration, so cap the per-lane budget
    # to keep cells bounded — BUDGET_EXCEEDED lanes then report honestly
    q_kw = (dict() if on_tpu
            else dict(budget=2_000, mid_budget=10_000, rescue_budget=100_000))

    from qsm_tpu.native import CppOracle, native_available

    configs = {
        "cas": (CasSpec, (AtomicCasSUT, RacyCasSUT), {
            "oracle": lambda s: WingGongCPU(node_budget=5_000_000),
            "memo": lambda s: WingGongCPU(memo=True),
            "cpp": lambda s: CppOracle(s),
            "device": lambda s: JaxTPU(s),
            "auto_device": lambda s: AutoDevice(s),
        }),
        "queue": (QueueSpec, (AtomicQueueSUT, RacyTwoPhaseQueueSUT), {
            "oracle": lambda s: WingGongCPU(node_budget=5_000_000),
            "memo": lambda s: WingGongCPU(memo=True),
            "cpp": lambda s: CppOracle(s),
            "device": lambda s: JaxTPU(s, **q_kw),
            "segdc_device": lambda s: SegDC(
                s, make_inner=lambda x: JaxTPU(x, **q_kw)),
            "auto_device": lambda s: AutoDevice(s, **q_kw),
        }),
    }
    if not native_available():
        # no toolchain: omit the cpp rows entirely rather than reporting
        # a fake "couldn't solve 12 ops" zero
        for _, _, backends in configs.values():
            backends.pop("cpp", None)

    cells: dict = {}
    solved: dict = {}
    deadline = time.perf_counter() + total_box_s
    hit_deadline = False
    for cname, (mk_spec, suts, backends) in configs.items():
        spec = mk_spec()
        corpora = {}
        cells[cname] = {}
        solved[cname] = {}
        for bname, mk in backends.items():
            cells[cname][bname] = {}
            best = 0
            for ops in buckets:
                # global deadline: the round-4 on-device sweep ran
                # >40 min — it must never starve the headline line of
                # the driver's end-of-round run (or outlive a healing
                # window).  Device cells are unbounded once started
                # (first-compile + two full batch passes), so they also
                # need a LOOK-AHEAD margin; host cells self-timebox at
                # box_s.  Remaining cells are marked, not silently
                # absent.
                margin = (240.0 if bname in ("device", "segdc_device",
                                             "auto_device") else 0.0)
                if time.perf_counter() > deadline - margin:
                    cells[cname][bname]["deadline_skipped"] = True
                    hit_deadline = True
                    break
                if ops > caps.get(bname, 1 << 30):
                    # past this backend's native coverage — mark the cap
                    # so "stopped at 64" is distinguishable from "failed
                    # the 96 bucket"
                    cells[cname][bname]["capped_at"] = caps[bname]
                    break
                if ops not in corpora:
                    corpora[ops] = shared(spec, suts, n=n_sample, n_pids=8,
                                          max_ops=ops, seed_base=1000,
                                          seed_prefix="sweep")
                corpus = corpora[ops]
                is_device = bname in ("device", "segdc_device",
                                      "auto_device")
                cell = (device_cell if is_device else host_cell)(
                    mk if is_device else mk(spec), spec, corpus)
                cells[cname][bname][str(ops)] = cell
                if cell["solved"]:
                    best = ops
                else:
                    break  # monotone: larger buckets only get harder
            solved[cname][bname] = best
    return {"solved": solved, "cells": cells, "sample": n_sample,
            "box_s": box_s, "pids": 8,
            "total_box_s": total_box_s, "hit_deadline": hit_deadline}


def build_corpus(spec, n_unique: int):
    from qsm_tpu.models import AtomicCasSUT, RacyCasSUT
    from qsm_tpu.utils.corpus import build_corpus as shared

    return shared(spec, (AtomicCasSUT, RacyCasSUT), n=n_unique,
                  n_pids=N_PIDS, max_ops=N_OPS, seed_base=1000,
                  seed_prefix="bench")


SWEEP_FILE = f"BENCH_SWEEP_{ROUND_TAG}.json"


def run_bench(on_tpu: bool, probe_detail: str, profile_dir: str | None,
              sweep: bool = True, sweep_file: str | None = None):
    from qsm_tpu.models import CasSpec
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    sc = _scale(on_tpu)
    spec = CasSpec()
    t0 = time.perf_counter()
    corpus = build_corpus(spec, sc["n_unique"])
    gen_s = time.perf_counter() - t0

    reps = (sc["device_batch"] + len(corpus) - 1) // len(corpus)
    device_corpus = (corpus * reps)[:sc["device_batch"]]

    # --- CPU oracle (baseline denominator), time-boxed -------------------
    # One history at a time so a single pathological interleaving search
    # can't consume the whole bench; the reference checker decides histories
    # one at a time too (SURVEY.md §3.5), so per-history timing is faithful.
    oracle = WingGongCPU(node_budget=20_000_000)
    cpu_verdicts = []
    cpu_times = []
    t0 = time.perf_counter()
    for h in corpus[:sc["cpu_sample"]]:
        t1 = time.perf_counter()
        cpu_verdicts.append(oracle.check_histories(spec, [h])[0])
        cpu_times.append(time.perf_counter() - t1)
        if time.perf_counter() - t0 > sc["cpu_timebox_s"]:
            break
    cpu_s = time.perf_counter() - t0
    cpu_verdicts = np.asarray(cpu_verdicts)
    cpu_rate = len(cpu_verdicts) / cpu_s

    # --- memoised CPU oracle (our best host checker) ---------------------
    memo = WingGongCPU(memo=True)
    t0 = time.perf_counter()
    memo_verdicts = memo.check_histories(spec, corpus)
    memo_rate = len(corpus) / (time.perf_counter() - t0)

    # --- native C++ host checker (qsm_tpu/native) ------------------------
    # Reported as an extra, not as vs_best_cpu's denominator: the metric
    # table pins vs_best_cpu to the memoised Python oracle (BASELINE.md),
    # and moving the goalpost mid-series would make rounds incomparable.
    cpp_rate = None
    cpp_wrong = None
    try:
        from qsm_tpu.native import CppOracle, native_available

        if native_available():
            cpp = CppOracle(spec)
            cpp.check_histories(spec, corpus)  # lib build + table compile
            t0 = time.perf_counter()
            cpp_verdicts = cpp.check_histories(spec, corpus)
            # a rate measured on the Python fallback is NOT a native rate —
            # only report when the native path really decided the corpus
            if cpp.native_histories > 0:
                cpp_rate = round(len(corpus) / (time.perf_counter() - t0), 1)
                cpp_wrong = int(np.sum(
                    (cpp_verdicts != 2) & (memo_verdicts != 2)
                    & (cpp_verdicts != memo_verdicts)))
    except Exception:  # noqa: BLE001 — optional fast path, never the bench
        pass

    # --- device kernel ---------------------------------------------------
    # Bounded per-history iteration budget keeps batch latency flat; the
    # rare blowups report BUDGET_EXCEEDED and are excluded from the decided
    # count (the property layer resolves them via the oracle — SURVEY.md §7
    # hard-parts #5), so the headline rate only counts decided verdicts.
    from qsm_tpu.utils.device import compile_cache_entries

    backend = JaxTPU(spec, budget=sc["budget"])
    # a scale-artifact-adopted width needs the split threshold raised too
    backend.MAX_BATCH = max(backend.MAX_BATCH, sc["device_batch"])
    # K micro-steps per while trip: 5.2× on the CPU platform (scale-scan
    # unroll8 variant, 228→1189 h/s, zero wrong), but the only post-unroll
    # on-chip datapoint regressed — so the setting is ADOPTED from the
    # scale scan's on-chip unroll A/B when one is banked (best_scale_unroll)
    # and defaults to 8 otherwise.  Verdict/iteration parity at any K is
    # pinned in tests/test_kernel_driver.py.
    backend.UNROLL = sc.get("unroll", 8)
    if on_tpu:
        # healing windows are short and first-compiles are the enemy: two
        # chunk stages instead of four halves the executables per bucket
        # at a small lockstep-waste cost (the escalation still happens,
        # just coarser)
        backend.CHUNK_SCHEDULE = (2048, 65536)
    cache_before = compile_cache_entries()
    t0 = time.perf_counter()
    backend.check_histories(spec, device_corpus)  # warmup: compile + run
    warm_s = time.perf_counter() - t0
    cache_after = compile_cache_entries()
    backend.lockstep_cost = 0   # count only the timed passes below
    backend.rounds_run = 0
    # search-accounting counters likewise restart at the timed passes so
    # the headline's SearchStats describe the measured configuration, not
    # the warmup (qsm_tpu/search/stats.py) — including rescued/deferred,
    # which search_stats() reports alongside the counters above
    backend.device_histories = 0
    backend.memo_prunes = 0
    backend.memo_inserts = 0
    backend.compactions = 0
    backend.rescued = 0
    backend.deferred_out_of_domain = 0
    if profile_dir:
        import jax

        jax.profiler.start_trace(profile_dir)
    rep_times = []
    t0 = time.perf_counter()
    for _ in range(sc["reps"]):
        t1 = time.perf_counter()
        dev_verdicts = backend.check_histories(spec, device_corpus)
        rep_times.append(round(time.perf_counter() - t1, 3))
    dev_s = time.perf_counter() - t0
    if profile_dir:
        import jax

        jax.profiler.stop_trace()
    budget_exceeded = int(np.sum(dev_verdicts == 2))
    dev_rate = sc["reps"] * (len(device_corpus) - budget_exceeded) / dev_s

    # --- parity (trust, but verify) --------------------------------------
    # Device vs BOTH host checkers.  Only count *wrong verdicts*: positions
    # where both sides decided and disagree; BUDGET_EXCEEDED on either side
    # is honest indecision, not a wrong answer.
    def wrong(host, dev):
        both = min(len(host), len(dev))
        hh, dd = np.asarray(host)[:both], np.asarray(dev)[:both]
        bad = (hh != 2) & (dd != 2) & (hh != dd)
        return set(np.nonzero(bad)[0].tolist())

    # union, not sum: a device verdict disagreeing with both host checkers
    # is ONE wrong verdict
    mismatches = len(wrong(cpu_verdicts, dev_verdicts)
                     | wrong(memo_verdicts, dev_verdicts))

    import jax

    # The full sweep is bulky; it lives in its own committed artifact so
    # the headline line stays under the driver's stdout-tail window.  Only
    # the small solved-summary and the artifact's filename ride the line.
    sweep_extras = {}
    if sweep:
        try:
            sw = run_sweep(on_tpu)
            sweep_extras = {"max_ops_solved_60s": sw["solved"]}
            if sw.get("hit_deadline"):
                # solved=0 rows past the cut would read as "failed the
                # 12-ops bucket"; the marker on the headline line keeps
                # truncation distinguishable from regression
                sweep_extras["sweep_truncated"] = True
            path = sweep_file or os.path.join(
                os.path.dirname(os.path.abspath(__file__)), SWEEP_FILE)
            sw["device"] = str(jax.devices()[0])
            sw["device_fallback"] = None if on_tpu else "cpu"
            sw["captured_iso"] = datetime.datetime.now(
                datetime.timezone.utc).isoformat(timespec="seconds")
            # a real-device sweep banked earlier in the round must never
            # be clobbered by a later CPU-fallback run; among device
            # captures, coverage is monotonic — a truncated rerun never
            # replaces a capture that measured MORE cells
            keep_existing = False
            if not on_tpu or sw.get("hit_deadline"):
                try:
                    with open(path) as f:
                        prev = json.load(f)
                    prev_device = prev.get("device_fallback") is None
                    if not on_tpu:
                        keep_existing = prev_device
                    else:
                        keep_existing = (prev_device
                                         and _sweep_cells_measured(prev)
                                         >= _sweep_cells_measured(sw))
                except (OSError, ValueError):
                    pass
            if not keep_existing:
                from qsm_tpu.resilience.checkpoint import atomic_write_json

                # tmp+rename: a bench killed mid-write (window closing)
                # must never leave a truncated sweep artifact behind
                atomic_write_json(path, sw, indent=1)
            sweep_extras["sweep_file"] = os.path.basename(path)
            if keep_existing:
                # the referenced artifact is an EARLIER (more complete
                # and/or real-device) run; this line's solved summary is
                # from the CURRENT sweep — mark the provenance split
                sweep_extras["sweep_file_is_earlier_device_run"] = True
        except Exception as e:  # noqa: BLE001 — the headline must survive
            sweep_extras = {"sweep_error": f"{type(e).__name__}: {e}"}

    # ratios against the frozen per-round host denominators, alongside the
    # live ones; live-vs-frozen drift >20% is flagged rather than silently
    # averaged away (VERDICT r4 task #5)
    frozen = _frozen_host_rates()
    frozen_extras = {}
    if frozen:
        f_naive = frozen["cpu_oracle_rate"]
        f_best = max(frozen.get("cpu_memo_oracle_rate") or 0.0,
                     frozen.get("cpp_oracle_rate") or 0.0)
        frozen_extras = {
            "vs_baseline_frozen": round(dev_rate / f_naive, 2),
            "vs_best_host_frozen": (round(dev_rate / f_best, 2)
                                    if f_best else None),
            "frozen_denominator_file": FROZEN_HOST_FILE,
            "denominator_drift_gt20pct": bool(
                abs(cpu_rate - f_naive) > 0.2 * f_naive),
        }

    return {
        "metric": f"histories_per_sec_linearized_{N_OPS}ops_x_{N_PIDS}pids",
        "value": round(dev_rate, 1),
        "unit": "histories/sec",
        "vs_baseline": round(dev_rate / cpu_rate, 2),
        "vs_best_cpu": round(dev_rate / memo_rate, 2),
        # the honest bar: the device against the builder's BEST host
        # checker, which since round 3 is the native C++ oracle when it is
        # available (VERDICT.md round 3, "Next round" #2).  vs_best_cpu
        # stays pinned to the memoised Python oracle for cross-round
        # comparability.
        "vs_best_host": round(dev_rate / max(memo_rate, cpp_rate or 0.0), 2),
        "extras": {
            **frozen_extras,
            "cpu_oracle_rate": round(cpu_rate, 3),
            "cpu_oracle_median_s": round(float(np.median(cpu_times)), 4),
            "cpu_memo_oracle_rate": round(memo_rate, 1),
            "cpp_oracle_rate": cpp_rate,
            "cpp_wrong_vs_memo": cpp_wrong,
            "cpu_sample": len(cpu_verdicts),
            "corpus_unique": len(corpus),
            "device": str(jax.devices()[0]),
            "device_fallback": None if on_tpu else "cpu",
            "tpu_probe": probe_detail[:160],
            "device_batch": sc["device_batch"],
            "batch_from_scale": sc.get("batch_from_scale"),
            "unroll": sc.get("unroll", 8),
            "unroll_from_scale": sc.get("unroll_from_scale"),
            "reps": sc["reps"],
            "per_rep_s": rep_times,
            "warm_s": round(warm_s, 2),
            "cache_entries_before": cache_before,
            "cache_entries_after": cache_after,
            "device_budget": sc["budget"],
            # the measured configuration, for cross-round comparability
            # (the TPU path coarsens the schedule to halve window compiles)
            "chunk_schedule": list(backend.CHUNK_SCHEDULE),
            "budget_exceeded": budget_exceeded,
            "rescued": backend.rescued,
            "lockstep_iters": backend.lockstep_cost // sc["reps"],  # per pass
            "chunk_rounds": backend.rounds_run // sc["reps"],
            # the search-efficiency plane's cost record (qsm_tpu/search):
            # device iters/history next to BOTH host oracles' nodes/history
            # — the decomposition of vs_best_host the round is judged on
            "search_device": backend.search_stats().to_compact(),
            "search_memo_nph": round(
                memo.search_stats().nodes_per_history, 1),
            "search_oracle_nph": round(
                oracle.search_stats().nodes_per_history, 1),
            # measured once on the CPU-scale corpus (256 lanes, seed_base
            # 1000) with the round-2 rescue-ladder driver; only comparable
            # to the CPU-fallback run of THIS corpus, so omitted elsewhere
            "lockstep_iters_r2_ladder": (
                3_769_248 if not on_tpu and sc["device_batch"] == 256
                else None),
            "wrong_verdicts_on_sample": mismatches,
            "corpus_gen_sec": round(gen_s, 1),
            # fault-handling self-description (qsm_tpu/resilience): zeros
            # on a clean run — a missing key would be a shrug, an
            # explicit 0 is a claim the run never degraded
            "resilience": _bench_resilience(backend),
            **sweep_extras,
        },
    }


def _bench_resilience(backend) -> dict:
    """The compact resilience block every bench artifact stamps."""
    from qsm_tpu.resilience.failover import collect_resilience

    r = collect_resilience(backend)
    return {"degradations": r.get("degradations", 0),
            "retries": r.get("retries", 0),
            "fallback_engine": r.get("fallback_engine")}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--probe-policy", default="bench-probe",
                    help="named RetryPolicy preset governing the probe "
                         "ladder (qsm_tpu/resilience/policy.py PRESETS; "
                         "the watcher's seize passes seize-probe)")
    ap.add_argument("--probe-timeout", type=float, default=None,
                    help="override the policy's per-attempt probe bound")
    ap.add_argument("--force-cpu", action="store_true",
                    help="skip the probe and bench on the CPU platform")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="capture a jax.profiler trace of the timed device "
                         "passes into DIR")
    ap.add_argument("--retries", type=int, default=None,
                    help="override the policy's extra probe attempts")
    ap.add_argument("--retry-interval", type=float, default=None,
                    help="override the policy's spacing between retries")
    ap.add_argument("--no-sweep", action="store_true",
                    help="skip the max-ops-solved-60s sweep")
    ap.add_argument("--sweep-file", default=None, metavar="PATH",
                    help=f"where the sweep artifact is written "
                         f"(default: {SWEEP_FILE} next to this script)")
    ap.add_argument("--require-device", action="store_true",
                    help="exit 3 immediately if the probe (after retries) "
                         "does not find a real device — never run the CPU "
                         "fallback workload.  For window-seize callers: a "
                         "fallback run inside an open TPU window wastes the "
                         "window's wall-clock on the host core.")
    args = ap.parse_args(argv)

    from qsm_tpu.resilience.policy import preset
    from qsm_tpu.utils.device import force_cpu_platform, probe_default_backend

    # ONE retry/deadline policy for the whole probe ladder: the named
    # preset is the source of truth (resilience/policy.py), the explicit
    # flags are per-run overrides — no hand-rolled constants here anymore
    policy = preset(args.probe_policy)
    if args.probe_timeout is not None:
        policy = policy.with_(timeout_s=args.probe_timeout)
    if args.retries is not None:
        policy = policy.with_(attempts=1 + max(0, args.retries))
    if args.retry_interval is not None:
        policy = policy.with_(backoff_s=args.retry_interval,
                              backoff_factor=1.0)
    if args.force_cpu:
        probe_detail = "skipped (--force-cpu)"
        on_tpu = False
    else:
        # the tunnel has healed mid-round before; the policy's spaced
        # re-probes are cheap relative to forfeiting the round's only
        # real-chip window — every attempt lands in the probe log
        probe = probe_default_backend(policy=policy,
                                      on_attempt=_append_probe_log)
        probe_detail = probe.detail
        on_tpu = probe.is_device
    if not on_tpu and args.require_device:
        print(json.dumps({
            "metric": "device_required", "value": 0, "unit": "",
            "vs_baseline": 0,
            "error": f"no device after {policy.attempts} probes "
                     f"(policy {policy.name})",
            "extras": {"tpu_probe": probe_detail, "device_fallback": "cpu",
                       "probe_attempts": _probe_attempts_summary()},
        }))
        return 3
    if not on_tpu:
        # the watcher may have caught a healed-tunnel window earlier in the
        # round and cached a REAL device run; that measured line is the
        # round's headline, with at-bench-time probe state in extras
        window = None if args.force_cpu else _load_window_artifact()
        if window is not None:
            ex = window.setdefault("extras", {})
            ex["headline_from_cached_window"] = True
            ex["window_captured_iso"] = window.pop("captured_iso", None)
            ex["tpu_probe_at_bench_time"] = probe_detail
            ex["probe_attempts"] = _probe_attempts_summary()
            # the cached line predates bench time, but the frozen host
            # denominators are per-round constants — compute the frozen
            # ratio family here so a window-seized headline ALWAYS carries
            # both families, not only the live ones it was captured with
            frozen = _frozen_host_rates()
            if frozen and window.get("value"):
                f_naive = frozen["cpu_oracle_rate"]
                f_best = max(frozen.get("cpu_memo_oracle_rate") or 0.0,
                             frozen.get("cpp_oracle_rate") or 0.0)
                ex.setdefault("vs_baseline_frozen",
                              round(window["value"] / f_naive, 2))
                if f_best:
                    ex.setdefault("vs_best_host_frozen",
                                  round(window["value"] / f_best, 2))
                ex.setdefault("frozen_denominator_file", FROZEN_HOST_FILE)
            print(_slim_line(window))
            return 0
        force_cpu_platform()

    # cross-process persistent compile cache, DEVICE runs only: inside a
    # healing window the seize pipeline runs several bench/scale/e2e
    # subprocesses — only the first should pay the 20-40 s first-compiles.
    # Not on the CPU fallback: XLA:CPU's AOT cache loader warns about
    # machine-feature mismatches ("could lead to SIGILL"), and the
    # fallback is the path that guards the round's headline.
    if on_tpu:
        from qsm_tpu.utils.device import enable_compile_cache

        enable_compile_cache()

    try:
        result = run_bench(on_tpu, probe_detail, args.profile,
                           sweep=not args.no_sweep,
                           sweep_file=args.sweep_file)
    except Exception as e:  # noqa: BLE001 — diagnostic JSON, never a bare crash
        print(json.dumps({
            "metric": f"histories_per_sec_linearized_{N_OPS}ops_x_{N_PIDS}"
                      "pids",
            "value": 0, "unit": "histories/sec", "vs_baseline": 0,
            "error": f"{type(e).__name__}: {e}",
            "extras": {"tpu_probe": probe_detail,
                       "device_fallback": None if on_tpu else "cpu",
                       "probe_attempts": _probe_attempts_summary()},
        }))
        return 1
    result["extras"]["probe_attempts"] = _probe_attempts_summary()
    print(_slim_line(result))
    return 0


# ~2 kB is the driver's observed stdout-tail window; stay clearly inside
# it so `value`/`vs_best_cpu`/`vs_best_host` always survive capture.
MAX_LINE = 1800


def _slim_line(result: dict) -> str:
    """One JSON line ≤ MAX_LINE chars.  Drops droppable extras in fixed
    priority order until it fits — the metric fields themselves are never
    touched; anything dropped is still in the committed sweep artifact or
    the probe log."""
    line = json.dumps(result)
    droppable = ("max_ops_solved_60s", "probe_attempts", "tpu_probe",
                 "chunk_schedule", "lockstep_iters_r2_ladder",
                 "cache_entries_before", "cache_entries_after",
                 "cpu_oracle_median_s", "corpus_gen_sec",
                 "frozen_denominator_file", "resilience",
                 # search stats drop LAST among extras: iph/nph are the
                 # decomposition the round is judged on
                 "search_oracle_nph", "search_memo_nph", "search_device")
    ex = result.get("extras", {})
    for key in droppable:
        if len(line) <= MAX_LINE:
            break
        if key in ex:
            del ex[key]
            ex["dropped_for_size"] = ex.get("dropped_for_size", []) + [key]
            line = json.dumps(result)
    return line


if __name__ == "__main__":
    sys.exit(main())
