"""Generation bench — does feedback steering find more than blind luck?

ISSUE 17's acceptance bars, as journal cells:

* ``steered_<fam>`` / ``unsteered_<fam>`` — the same engine-call
  budget (ROUNDS × BATCH histories through one memoised host oracle)
  spent two ways per family: the feedback loop (``SteeringLoop`` —
  mutate, score by flips + search-node deltas + corpus shape, keep)
  versus the DEFAULT profile generating blind.  The headline per
  family is ``steered / unsteered`` on flips and on search nodes per
  history; the gate is ≥3× on flips OR nodes/history for at least
  MIN_FAMILIES families — steering must beat matched-budget luck, not
  merely tie it.
* ``flip_audit`` — EVERY violation the steered arms found (collected
  via ``on_flip``, not the tail-capped keep window) re-checked by a
  fresh memoised oracle; ``missed`` MUST be 0 — a steered "flip" that
  a fresh oracle calls linearizable would mean the loop is chasing
  cache ghosts.  Plus the proof obligation on the other verdict: a
  best-profile batch per family run through ``check_witness`` and
  every LINEARIZABLE witness replayed search-free via
  ``verify_witness`` — ``witness_failures`` MUST be 0.
* ``soak_fleet`` — the closed loop against a real 2-node fleet (two
  in-process ``CheckServer`` nodes fronted by a ``FleetRouter``):
  ``fuzz_fleet`` soaks it with steered check requests + streamed
  monitor sessions, every fleet verdict oracle-audited client-side;
  gates are ``wrong_verdicts == 0`` and the fleet's own SLO/health
  answer mapping to exit 0.

Every row embeds the additive ``gen_*`` counters (SearchStats compact
keys ``gsq``/``gmu``/``gfl``/``gfr`` — tests/test_stats_merge.py) so
``bench_report.py`` trends generation volume alongside flip yield.

Output: resumable ``CellJournal`` committed as ``BENCH_GEN_<tag>.json``
(``make bench-gen``; probe_watcher archives it off-window beside the
LINT/MONITOR/FLEET artifacts and ``bench_report.py`` folds it into
BENCH_REPORT.md).
"""

from __future__ import annotations

import argparse
import datetime
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROUNDS = 12            # feedback rounds per arm (the matched budget)
BATCH = 16             # histories per round
FAMILIES = ("rangeset", "semaphore", "register")
MIN_FAMILIES = 2       # the ≥3× gate must hold on at least this many
GATE_RATIO = 3.0
SOAK_MODELS = ("rangeset", "semaphore")
SOAK_ROUNDS = 3
SOAK_BATCH = 8
GEN_PATH = "py"        # the byte-stable table: bench rows reproduce
                       # anywhere, device or not


def _backend():
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    return WingGongCPU(memo=True)


def _nodes_of(backend) -> int:
    from qsm_tpu.search.stats import collect_search_stats

    st = collect_search_stats(backend)
    return int(getattr(st, "nodes_explored", 0) or 0)


def _cell_steered(fam: str, flips_out: list) -> dict:
    from qsm_tpu.gen.steer import SteeringLoop
    from qsm_tpu.models.registry import MODELS

    spec = MODELS[fam].make_spec()
    backend = _backend()
    loop = SteeringLoop(
        spec, backend, batch=BATCH, seed=17, path=GEN_PATH,
        on_flip=lambda s, p, h: flips_out.append((fam, h)))
    t0 = time.perf_counter()
    reports = loop.run(ROUNDS)
    dt = time.perf_counter() - t0
    st = loop.stats
    best = loop.pool.best()
    return {"seconds": round(dt, 3), "rounds": ROUNDS, "batch": BATCH,
            "histories": st.gen_seqs, "flips": st.gen_flips,
            "nodes": _nodes_of(backend),
            "nodes_per_hist": round(
                _nodes_of(backend) / max(1, st.gen_seqs), 2),
            "best_profile": best.profile.to_dict(),
            "best_score": round(best.score, 2),
            "round_flips": [r["flips"] for r in reports],
            "search": loop.search_stats().to_compact()}


def _cell_unsteered(fam: str) -> dict:
    """The control: the IDENTICAL budget generated from the default
    profile with no feedback — sequential seeds, no mutation, no pool.
    Same oracle class, same batch geometry, same seed table family."""
    from qsm_tpu.gen.core import generate_batch
    from qsm_tpu.gen.profile import GenProfile
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.ops.backend import Verdict
    from qsm_tpu.search.stats import SearchStats

    spec = MODELS[fam].make_spec()
    backend = _backend()
    profile = GenProfile()
    flips = 0
    n = 0
    t0 = time.perf_counter()
    for r in range(ROUNDS):
        hists = generate_batch(spec, profile, 17_000 + r, BATCH,
                               path=GEN_PATH)
        verdicts = backend.check_histories(spec, hists)
        flips += sum(1 for v in verdicts
                     if int(v) == int(Verdict.VIOLATION))
        n += len(hists)
    dt = time.perf_counter() - t0
    return {"seconds": round(dt, 3), "rounds": ROUNDS, "batch": BATCH,
            "histories": n, "flips": flips,
            "nodes": _nodes_of(backend),
            "nodes_per_hist": round(_nodes_of(backend) / max(1, n), 2),
            "search": SearchStats(engine="gen-blind", gen_seqs=n,
                                  gen_flips=flips).to_compact()}


def _cell_flip_audit(flips, steered_cells) -> dict:
    """Module docstring: zero tolerance on both proof obligations."""
    from qsm_tpu.gen.core import generate_batch
    from qsm_tpu.gen.profile import GenProfile
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.ops.backend import Verdict, verify_witness

    specs = {fam: MODELS[fam].make_spec() for fam in FAMILIES}
    missed = 0
    for fam, h in flips:
        oracle = _backend()   # fresh per flip: no banked state
        v = int(oracle.check_histories(specs[fam], [h])[0])
        if v != int(Verdict.VIOLATION):
            missed += 1
    witnesses = 0
    witness_failures = 0
    for fam in FAMILIES:
        profile = GenProfile.from_dict(
            steered_cells[fam]["best_profile"])
        hists = generate_batch(specs[fam], profile, 4242, BATCH,
                               path=GEN_PATH)
        oracle = _backend()
        for h in hists:
            v, w = oracle.check_witness(specs[fam], h)
            if int(v) != int(Verdict.LINEARIZABLE):
                continue
            witnesses += 1
            if not verify_witness(specs[fam], h, w):
                witness_failures += 1
    return {"flips_audited": len(flips), "missed": missed,
            "witnesses_replayed": witnesses,
            "witness_failures": witness_failures}


def _cell_soak(run_dir: str) -> dict:
    """The 2-node closed loop (module docstring): CheckServer nodes,
    FleetRouter front, ``fuzz_fleet`` as the driver, the SLO/health
    plane as the judge."""
    from qsm_tpu.fleet.router import FleetRouter
    from qsm_tpu.gen.fleet import fuzz_fleet
    from qsm_tpu.serve.server import CheckServer

    nodes = [CheckServer(flush_s=0.005, max_lanes=16).start()
             for _ in range(2)]
    router = None
    try:
        router = FleetRouter(
            [(f"n{i}", s.address) for i, s in enumerate(nodes)],
            heartbeat_s=0.3, anti_entropy_s=0.0).start()
        t0 = time.perf_counter()
        rep = fuzz_fleet(router.address, list(SOAK_MODELS),
                         rounds=SOAK_ROUNDS, batch=SOAK_BATCH,
                         seed=17, path=GEN_PATH,
                         checkpoint_dir=run_dir)
        dt = time.perf_counter() - t0
        return {
            "seconds": round(dt, 2), "n_nodes": len(nodes),
            "models": list(SOAK_MODELS), "rounds": SOAK_ROUNDS,
            "batch": SOAK_BATCH,
            "histories": rep["seqs_total"],
            "flips": rep["flips_total"],
            "wrong_verdicts": rep["wrong_verdicts_total"],
            "witnesses_verified": sum(
                m["witnesses_verified"] for m in rep["models"].values()),
            "sessions": sum(len(m["sessions"])
                            for m in rep["models"].values()),
            "session_flips": sum(m["session_flips"]
                                 for m in rep["models"].values()),
            "sheds": sum(m["sheds"] for m in rep["models"].values()),
            "health_status": rep["health_status"],
            "exit_code": rep["exit_code"],
        }
    finally:
        if router is not None:
            router.stop()
        for s in nodes:
            s.stop()


def run(tag: str, out_path, resume: bool) -> dict:
    import tempfile

    from qsm_tpu.resilience.checkpoint import CellJournal

    path = out_path or os.path.join(REPO, f"BENCH_GEN_{tag}.json")
    header = {
        "artifact": "BENCH_GEN",
        "device_fallback": None,   # host-only bench: no device needed
        "platform": "cpu",
        "rounds": ROUNDS, "batch": BATCH, "families": list(FAMILIES),
        "gate_ratio": GATE_RATIO, "min_families": MIN_FAMILIES,
        "gen_path": GEN_PATH,
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)

    flips: list = []
    steered = {}
    unsteered = {}
    for fam in FAMILIES:
        audit_done = journal.complete("flip_audit") is not None
        cell = journal.complete(f"steered_{fam}")
        if cell is None:
            cell = journal.emit(f"steered_{fam}",
                                _cell_steered(fam, flips))
        elif not audit_done:
            # a resumed steered cell with the audit still owed: replay
            # the (deterministic: fixed seed, py table, fresh oracle)
            # arm to regenerate the flip histories the audit needs,
            # without emitting a duplicate row
            _cell_steered(fam, flips)
        steered[fam] = cell
        ucell = journal.complete(f"unsteered_{fam}")
        if ucell is None:
            ucell = journal.emit(f"unsteered_{fam}",
                                 _cell_unsteered(fam))
        unsteered[fam] = ucell

    audit = journal.complete("flip_audit")
    if audit is None:
        audit = journal.emit("flip_audit",
                             _cell_flip_audit(flips, steered))

    soak = journal.complete("soak_fleet")
    if soak is None:
        with tempfile.TemporaryDirectory(prefix="bench_gen_") as d:
            soak = journal.emit("soak_fleet", _cell_soak(d))

    ratios = {}
    families_passing = 0
    for fam in FAMILIES:
        s, u = steered[fam], unsteered[fam]
        flip_ratio = s["flips"] / max(1, u["flips"])
        node_ratio = (s["nodes_per_hist"]
                      / max(1e-9, u["nodes_per_hist"]))
        ok = (flip_ratio >= GATE_RATIO or node_ratio >= GATE_RATIO)
        families_passing += ok
        ratios[fam] = {"flips": f"{s['flips']}/{u['flips']}",
                       "flip_ratio": round(flip_ratio, 2),
                       "node_ratio": round(node_ratio, 2),
                       "gate_ok": ok}
    summary = {
        "families": ratios,
        "families_passing": families_passing,
        "max_flip_ratio": max(r["flip_ratio"] for r in ratios.values()),
        "flips_audited": audit["flips_audited"],
        "flips_missed_by_oracle": audit["missed"],
        "witnesses_replayed": audit["witnesses_replayed"],
        "witness_failures": audit["witness_failures"],
        "soak_wrong_verdicts": soak["wrong_verdicts"],
        "soak_health": soak["health_status"],
        "soak_exit_code": soak["exit_code"],
        # the gates (module docstring): steering beats matched-budget
        # luck on enough families, every flip survives a fresh oracle,
        # every witness replays, and the closed loop is wrong-free
        # against a healthy fleet
        "gate_ok": (families_passing >= MIN_FAMILIES
                    and audit["missed"] == 0
                    and audit["witness_failures"] == 0
                    and soak["wrong_verdicts"] == 0
                    and soak["exit_code"] == 0),
    }
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tag", default="r17")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="skip cells already banked in a compatible "
                         "prior artifact (CellJournal rails)")
    args = ap.parse_args(argv)
    summary = run(args.tag, args.out, args.resume)
    print(summary)
    return 0 if summary["gate_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
