"""Batched-shrink bench — frontier-at-once vs one-candidate-at-a-time.

The paper's fifth capability: a failure's shrink loop re-checks
thousands of candidate histories, and the reference pays them ONE AT A
TIME on CPU.  The shrink plane (qsm_tpu/shrink, ISSUE 10) generates the
whole frontier per greedy round and decides it in one planned dispatch;
this tool prices exactly that fold on seeded-bug corpora — racy kv and
racy cas, 64-op failing histories — on the CPU platform, no window
required:

* ``batched_{fam}`` — ``shrink_history``: planned host dispatch
  (``build_host_backend``: PComp outermost for kv, the failover host
  ladder for cas), fingerprint memo, one engine CALL per round.  Every
  result is audited: minimized history re-confirmed a VIOLATION by a
  FRESH memo oracle, 1-minimality proved by the certificate (one
  ``verify_witness``-replayable witness per drop-one neighbor).
* ``naive_{fam}`` — the SAME algorithm (same frontier, same
  smallest-still-failing selection — so the minimized history is
  bit-identical by construction, pinned per history) issuing one engine
  call per candidate with no memo: the reference's one-at-a-time shape.
  The gate compares ENGINE CALLS (dispatch invocations — the unit a
  device pays launch overhead and a server pays batching latency on).
  A first-accept greedy variant (step to the FIRST failing candidate,
  stop scanning) is also priced per family (``first_accept_calls``):
  it is a different algorithm — it cannot claim the smallest-candidate
  step and decides a different (order-dependent) trajectory — but the
  artifact reports it so the fold's win is never overstated.
* ``serve_shrink`` — the ``shrink`` verb end-to-end: a CheckServer
  minimizes the kv corpus over shared micro-batch lanes; every
  minimized history must be IDENTICAL to the in-process result, and a
  duplicate request must answer O(1) from the shrink bank.

Win condition (ISSUE 10 acceptance): ≥10× fewer engine checks than the
one-at-a-time baseline on both families, zero wrong verdicts (audits
all green), every minimized history 1-minimal + still a VIOLATION +
witnesses replaying through ``verify_witness``, and the serve verb
bit-identical to the in-process API.  Output: a resumable
``CellJournal`` committed as ``BENCH_SHRINK_<tag>.json``.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MAX_ROUNDS = 256
KV = {"n_keys": 16, "n_values": 4}
KV_PIDS, KV_OPS, KV_CORPUS = 8, 64, 6
CAS_PIDS, CAS_OPS, CAS_CORPUS = 4, 64, 4
SEED_SCAN = 120          # seeds probed while collecting failing histories
SERVE_DEADLINE_S = 300.0


def _families():
    from qsm_tpu.models.cas import CasSpec
    from qsm_tpu.models.kv import KvSpec, StaleCacheKvSUT
    from qsm_tpu.models.registry import MODELS

    kv = KvSpec(**KV)
    cas = MODELS["cas"].make_spec()
    return {
        "kv": (kv, StaleCacheKvSUT, KV_PIDS, KV_OPS, KV_CORPUS),
        "cas": (cas, MODELS["cas"].impls["racy"], CAS_PIDS, CAS_OPS,
                CAS_CORPUS),
    }


def _failing_corpus(spec, sut_cls, n, pids, ops, prefix):
    """``n`` seeded VIOLATION histories of exactly ``ops`` ops — the
    racy SUT run under the deterministic scheduler, kept iff the host
    ladder says VIOLATION (seeds are scanned in order, so the corpus is
    fully reproducible from this file alone)."""
    from qsm_tpu.core.generator import generate_program
    from qsm_tpu.resilience.failover import host_fallback
    from qsm_tpu.sched.runner import run_concurrent

    eng = host_fallback(spec)
    out = []
    for seed in range(SEED_SCAN):
        if len(out) >= n:
            break
        prog = generate_program(spec, seed=seed, n_pids=pids,
                                max_ops=ops, min_ops=ops)
        h = run_concurrent(sut_cls(spec), prog,
                           seed=f"{prefix}:{seed}").completed()
        if int(eng.check_histories(spec, [h])[0]) == 0:  # VIOLATION
            out.append(h)
    return out


def _engine(spec, history):
    """The batched plane's own engine construction (shrinker.py default)
    — built once per family so the naive twin re-uses the identical
    verdict source."""
    from qsm_tpu.search.planner import (build_host_backend, plan_search,
                                        profile_corpus)

    plan = plan_search(spec, profile_corpus([history], spec),
                       platform="cpu")
    return build_host_backend(spec, plan)


def bench_batched(spec, corpus) -> dict:
    from qsm_tpu.shrink import shrink_history, verify_certificate

    rows = []
    wrong = 0
    t0 = time.perf_counter()
    for h in corpus:
        res = shrink_history(spec, h, max_rounds=MAX_ROUNDS,
                             certificate=True)
        audit = verify_certificate(spec, res.history,
                                   res.certificate or [])
        ok = (res.ok and res.complete and res.one_minimal
              and audit["one_minimal_proved"]
              and audit["violation_reconfirmed"])
        if not ok:
            wrong += 1
        rows.append({
            "initial_ops": res.initial_ops, "final_ops": res.final_ops,
            "rounds": res.rounds, "engine_calls": res.engine_calls,
            "lanes": res.lanes_checked, "memo_hits": res.memo_hits,
            "one_minimal": res.one_minimal,
            "witnesses_replayed": audit["witnesses_replayed"],
            "violation_reconfirmed": audit["violation_reconfirmed"],
            "fingerprint": hash(res.history.fingerprint()) & 0xffffffff,
        })
    return {
        "histories": len(corpus),
        "seconds": round(time.perf_counter() - t0, 3),
        "engine_calls": sum(r["engine_calls"] for r in rows),
        "lanes": sum(r["lanes"] for r in rows),
        "rounds": sum(r["rounds"] for r in rows),
        "memo_hits": sum(r["memo_hits"] for r in rows),
        "mean_ratio": round(sum(r["final_ops"] / r["initial_ops"]
                                for r in rows) / max(len(rows), 1), 4),
        "wrong_verdicts": wrong,
        "per_history": rows,
    }


def _naive_one_at_a_time(spec, engine, history):
    """The same greedy loop as shrinker.py — same frontier, same
    smallest-still-failing selection — but every candidate is its own
    engine call and nothing is memoised: the reference's shrink shape.
    Returns (minimized, engine_calls, first_accept_calls) where
    ``first_accept_calls`` prices the stop-at-first-failure variant of
    the same scan order (a different algorithm, reported for honesty)."""
    from qsm_tpu.ops.backend import Verdict
    from qsm_tpu.shrink import shrink_frontier

    calls = 0
    fa_calls = 0

    def check_one(h):
        return int(engine.check_histories(spec, [h])[0])

    v = check_one(history)
    calls += 1
    fa_calls += 1
    best = history
    if v != int(Verdict.VIOLATION):
        return best, calls, fa_calls
    for _round in range(MAX_ROUNDS):
        cands, _trunc = shrink_frontier(spec, best)
        if not cands:
            break
        verdicts = []
        fa_counted = False
        for c in cands:  # one engine call per candidate: the baseline
            verdicts.append(check_one(c.history))
            calls += 1
            if not fa_counted:
                fa_calls += 1
                if verdicts[-1] == int(Verdict.VIOLATION):
                    fa_counted = True  # first-accept would stop here
        fail = next((i for i, vv in enumerate(verdicts)
                     if vv == int(Verdict.VIOLATION)), None)
        if fail is None:
            break
        best = cands[fail].history
    return best, calls, fa_calls


def bench_naive(spec, corpus, batched_row) -> dict:
    eng = _engine(spec, corpus[0])
    rows = []
    mismatches = 0
    t0 = time.perf_counter()
    for h, brow in zip(corpus, batched_row["per_history"]):
        mh, calls, fa_calls = _naive_one_at_a_time(spec, eng, h)
        same = (hash(mh.fingerprint()) & 0xffffffff
                == brow["fingerprint"])
        if not same:
            mismatches += 1
        rows.append({"engine_calls": calls,
                     "first_accept_calls": fa_calls,
                     "final_ops": len(mh), "identical_to_batched": same})
    return {
        "histories": len(corpus),
        "seconds": round(time.perf_counter() - t0, 3),
        "engine_calls": sum(r["engine_calls"] for r in rows),
        "first_accept_calls": sum(r["first_accept_calls"] for r in rows),
        "mismatched_results": mismatches,
        "per_history": rows,
    }


def bench_serve(corpus, batched_row) -> dict:
    """The shrink verb over shared lanes: identical minimized rows to
    the in-process path, duplicate answered from the bank."""
    import tempfile

    from qsm_tpu.serve.client import CheckClient
    from qsm_tpu.serve.protocol import history_to_rows, rows_to_history
    from qsm_tpu.serve.server import CheckServer
    from qsm_tpu.shrink import shrink_history

    tmp = tempfile.mkdtemp(prefix="qsm_bench_shrink_")
    srv = CheckServer(unix_path=os.path.join(tmp, "sock"),
                      cache_path=os.path.join(tmp, "bank.jsonl")).start()
    from qsm_tpu.models.kv import KvSpec

    spec = KvSpec(**KV)
    wrong = 0
    t0 = time.perf_counter()
    try:
        c = CheckClient(srv.address, timeout_s=SERVE_DEADLINE_S + 30)
        try:
            for h, brow in zip(corpus, batched_row["per_history"]):
                r = c.shrink("kv", h, spec_kwargs=KV,
                             deadline_s=SERVE_DEADLINE_S)
                served = rows_to_history(r["history"]).fingerprint()
                inproc = shrink_history(spec, h,
                                        certificate=False).history
                if not (r.get("ok") and r.get("complete")
                        and served == inproc.fingerprint()
                        and hash(served) & 0xffffffff
                        == brow["fingerprint"]):
                    wrong += 1
            dup = c.shrink("kv", corpus[0], spec_kwargs=KV,
                           deadline_s=SERVE_DEADLINE_S)
            stats = c.stats()["stats"]
        finally:
            c.close()
    finally:
        srv.stop()
    return {
        "histories": len(corpus),
        "seconds": round(time.perf_counter() - t0, 3),
        "mismatched_results": wrong,
        "duplicate_banked": bool(dup.get("cached")),
        "shrink": stats["shrink"],
        "batcher": {k: stats["batcher"][k]
                    for k in ("batches", "lanes", "mean_occupancy")},
    }


def run(tag: str, out_path: str | None, resume: bool) -> dict:
    from qsm_tpu.resilience.checkpoint import CellJournal

    path = out_path or os.path.join(REPO, f"BENCH_SHRINK_{tag}.json")
    header = {
        "artifact": "BENCH_SHRINK",
        "device_fallback": None,   # host-only bench: no window involved
        "platform": "cpu",
        "families": {"kv": {**KV, "pids": KV_PIDS, "ops": KV_OPS,
                            "corpus": KV_CORPUS},
                     "cas": {"pids": CAS_PIDS, "ops": CAS_OPS,
                             "corpus": CAS_CORPUS}},
        "max_rounds": MAX_ROUNDS,
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)
    fams = _families()
    corpora = {}

    def corpus_for(fam):
        if fam not in corpora:
            spec, sut, pids, ops, n = fams[fam]
            corpora[fam] = _failing_corpus(spec, sut, n, pids, ops,
                                           f"bench_shrink_{fam}")
        return corpora[fam]

    for fam in ("kv", "cas"):
        spec = fams[fam][0]
        if journal.complete(f"batched_{fam}") is None:
            journal.emit(f"batched_{fam}",
                         bench_batched(spec, corpus_for(fam)))
        if journal.complete(f"naive_{fam}") is None:
            journal.emit(f"naive_{fam}",
                         bench_naive(spec, corpus_for(fam),
                                     journal.complete(f"batched_{fam}")))
    if journal.complete("serve_shrink") is None:
        journal.emit("serve_shrink",
                     bench_serve(corpus_for("kv"),
                                 journal.complete("batched_kv")))

    ratios = {}
    wrong = 0
    for fam in ("kv", "cas"):
        b = journal.complete(f"batched_{fam}")
        nv = journal.complete(f"naive_{fam}")
        ratios[fam] = round(nv["engine_calls"]
                            / max(b["engine_calls"], 1), 1)
        wrong += b["wrong_verdicts"] + nv["mismatched_results"]
    serve = journal.complete("serve_shrink")
    wrong += serve["mismatched_results"]
    b_kv = journal.complete("batched_kv")
    summary = {
        "metric": "batched_vs_one_at_a_time_engine_calls",
        "calls_ratio_kv": ratios["kv"],
        "calls_ratio_cas": ratios["cas"],
        "gate_10x": all(r >= 10 for r in ratios.values()),
        "first_accept_calls_kv": journal.complete("naive_kv")[
            "first_accept_calls"],
        "wrong_verdicts": wrong,
        "mean_op_ratio_kv": b_kv["mean_ratio"],
        "serve_identical": serve["mismatched_results"] == 0,
        "serve_duplicate_banked": serve["duplicate_banked"],
        "resumed_cells": journal.resumed_cells,
    }
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    print(json.dumps({"metric": summary["metric"],
                      "calls_ratio_kv": summary["calls_ratio_kv"],
                      "calls_ratio_cas": summary["calls_ratio_cas"],
                      "gate_10x": summary["gate_10x"],
                      "wrong_verdicts": wrong,
                      "artifact": os.path.basename(path)}))
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tag", default="r10")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="adopt completed cells from an existing "
                         "artifact (CellJournal rails)")
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform()
    try:
        run(args.tag, args.out, args.resume)
    except Exception as e:  # noqa: BLE001 — diagnostic line, not a traceback
        print(json.dumps({"metric": "batched_vs_one_at_a_time_engine_calls",
                          "error": f"{type(e).__name__}: {e}"}))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
