"""Mesh-substrate bench — one lane axis, every shape, zero drift.

ISSUE 19's acceptance bars, as journal cells:

* ``scale_d{1,2,4,8}`` — the SAME fixed corpus (register + cas +
  queue + kv, the kv lanes pcomp-split on the nodes) checked through
  ``qsm_tpu.mesh.sharded_backend`` in a subprocess whose device count
  is forced via ``forced_host_device_env`` (utils/device.py) — the
  no-hardware recipe docs/MESH.md documents.  Each cell reports
  lanes/sec, the mesh-suffixed plan name (``…@meshN``), every verdict,
  every witness (first lanes per family, each LINEARIZABLE one
  replayed search-free through ``verify_witness``), one shrink run and
  one monitor-frontier window re-check driven by the sharded kernel.
* ``parity`` — verdicts AND witnesses bit-identical across every
  mesh shape, shrink result rows bit-equal, monitor verdict sequence
  bit-equal, and every verdict audited against a fresh host oracle:
  ``wrong_verdicts`` required 0.  This is the substrate's one promise:
  the mesh is a dispatch detail, never an answer detail.
* ``fleet_n{1,3}`` — the r13 fleet scaling cells re-run with every
  node process under a forced 8-device mesh (``bench_fleet``'s own
  recorded mix and drive loop), to DECIDE the ≥2× three-node gate the
  r13 artifact waived for insufficient cores: the ratio is recorded
  pass or fail, never waived (``gate_decided`` is stamped true).

Scaling honesty (the r08/r13 precedent, one level down): forcing N
virtual devices onto one host core multiplies PARTITIONS, not FLOPs —
XLA round-robins the shards over the same core, so lanes/sec across
``scale_d*`` is flat-to-slightly-down on this box, and the committed
curve says so (``host_cores`` is stamped).  The throughput gate here
is therefore NO-COLLAPSE (the 8-way mesh keeps >= ``COLLAPSE_TOL`` of
single-device throughput — sharding overhead must stay noise), while
monotone speedup remains the multi-chip window's claim to bank.  The
correctness gates (parity, zero wrong, witnesses replay) are absolute.

Output: resumable ``CellJournal`` committed as ``BENCH_MESH_<tag>.json``
(``make bench-mesh``; probe_watcher archives it off-window and
``bench_report.py`` folds it into BENCH_REPORT.md).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEVICE_COUNTS = (1, 2, 4, 8)
# (family, lanes, n_pids, max_ops, seed_base) — kv's 8-pid lanes are
# the pcomp-split shape (the planner decomposes per key on the
# registry's validated projection), so the sub-lane plane rides the
# mesh too; per-family seeds keep every family's verdict set mixed
FAMILY_SHAPES = (("register", 48, 6, 12, 11), ("cas", 48, 6, 14, 2026),
                 ("queue", 32, 6, 12, 2026), ("kv", 16, 8, 20, 11))
WITNESS_LANES = 8       # per family: witness parity + replay sample
BUDGET = 500_000
FLEET_DEVICES = 8       # every fleet node rides the forced 8-way mesh
FLEET_NODES = (1, 3)
SCALE_TIMEOUT_S = 900.0
FLEET_TIMEOUT_S = 1800.0
COLLAPSE_TOL = 0.5      # min(lanes/sec) / d1 lanes/sec floor


# ---------------------------------------------------------------------------
# the shared corpus (seed-derived: parent and children build the same
# histories without shipping them)
# ---------------------------------------------------------------------------

def _family_corpora():
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.utils.corpus import build_corpus

    out = {}
    for fam, lanes, n_pids, max_ops, seed in FAMILY_SHAPES:
        entry = MODELS[fam]
        spec = entry.make_spec()
        hists = build_corpus(
            spec, (entry.impls["atomic"], entry.impls["racy"]),
            n=lanes, n_pids=n_pids, max_ops=max_ops, seed_base=seed,
            seed_prefix=f"bench_mesh_{fam}")
        out[fam] = (spec, hists)
    return out


def _witness_json(witness):
    if witness is None:
        return None
    return [[int(a), int(b)] for a, b in witness]


# ---------------------------------------------------------------------------
# child cells (run under forced_host_device_env in a subprocess)
# ---------------------------------------------------------------------------

def _child_scale(n_devices: int, shrink_index: int) -> dict:
    import jax

    from qsm_tpu.mesh import batch_sharding, make_mesh, sharded_backend
    from qsm_tpu.monitor.frontier import IncrementalFrontier
    from qsm_tpu.ops.backend import verify_witness
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.search.planner import plan_search, profile_corpus
    from qsm_tpu.serve.protocol import history_to_rows
    from qsm_tpu.shrink.shrinker import shrink_history

    # the forced env really took: the mesh below is this wide
    assert jax.device_count() == n_devices, (jax.device_count(),
                                             n_devices)
    corpora = _family_corpora()
    sharding = (batch_sharding(make_mesh(n_devices))
                if n_devices > 1 else None)
    report = {"devices": n_devices, "families": {}}
    backends = {}
    for fam, (spec, hists) in corpora.items():
        # profiled plans: the kv lanes cross the pcomp gate, so the
        # per-key sub-lane plane rides the mesh in this sweep too
        profile = profile_corpus(hists, spec)
        backends[fam] = sharded_backend(spec, devices=n_devices,
                                        budget=BUDGET, profile=profile)
        plan = plan_search(spec, profile, mesh_devices=n_devices)
        report["families"][fam] = {"plan": plan.name,
                                   "pcomp": bool(plan.decompose_keys)}

    # warm pass: compiles banked so the timed pass measures dispatch
    for fam, (spec, hists) in corpora.items():
        backends[fam].check_histories(spec, hists)
    t0 = time.perf_counter()
    lanes = 0
    for fam, (spec, hists) in corpora.items():
        verdicts = backends[fam].check_histories(spec, hists)
        lanes += len(hists)
        report["families"][fam]["verdicts"] = [int(v) for v in verdicts]
    dt = time.perf_counter() - t0
    report["lanes"] = lanes
    report["seconds"] = round(dt, 3)
    report["lanes_per_sec"] = round(lanes / max(dt, 1e-9), 1)

    # witness lane: the kernel's own check_witness under the SAME
    # sharding, every LINEARIZABLE witness replayed search-free
    witness_failures = 0
    for fam, (spec, hists) in corpora.items():
        kern = JaxTPU(spec, budget=BUDGET, sharding=sharding)
        rows = []
        for h in hists[:WITNESS_LANES]:
            v, w = kern.check_witness(spec, h)
            rows.append([int(v), _witness_json(w)])
            if w is not None and not verify_witness(spec, h, w):
                witness_failures += 1
        report["families"][fam]["witnesses"] = rows
    report["witness_failures"] = witness_failures

    # shrink lane: minimize the parent-chosen failing cas history on a
    # mesh-planned backend; the minimized rows must be shape-invariant
    cas_spec, cas_hists = corpora["cas"]
    res = shrink_history(cas_spec, cas_hists[shrink_index],
                         backend=backends["cas"], certificate=False)
    report["shrink_ok"] = bool(res.ok)
    report["shrink_rows"] = history_to_rows(res.history)

    # monitor lane: the incremental frontier's window re-check driven
    # by the sharded kernel (oracle.check_from), verdict per event
    mon_spec, mon_hists = corpora["register"]
    oracle = JaxTPU(mon_spec, budget=BUDGET, sharding=sharding)
    stream = [h for h in mon_hists if h.n_pending == 0][0]
    frontier = IncrementalFrontier(mon_spec, oracle=oracle)
    seq = []
    for op in sorted(stream.completed().ops, key=lambda o: o.invoke_time):
        frontier.append_completed(op)
        seq.append(int(frontier.advance()))
    seq.append(int(frontier.check_window()))
    report["monitor_verdicts"] = seq
    return report


def _child_fleet(n_nodes: int) -> dict:
    import importlib.util

    import jax

    assert jax.device_count() == FLEET_DEVICES, jax.device_count()
    path = os.path.join(REPO, "tools", "bench_fleet.py")
    spec = importlib.util.spec_from_file_location("bench_fleet", path)
    bf = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bf)
    mix = bf._build_mix()
    with tempfile.TemporaryDirectory(prefix="bench_mesh_fleet_") as d:
        row = bf.bench_scaling(n_nodes, mix, d)
    row["mesh_devices_per_node"] = FLEET_DEVICES
    return row


def _spawn_child(kind: str, n: int, shrink_index: int = 0) -> dict:
    """One journal cell's worth of work in a subprocess whose JAX
    platform is pinned to N forced host devices BEFORE any import —
    the only way a device count can be a per-cell variable."""
    from qsm_tpu.utils.device import forced_host_device_env

    devices = n if kind == "scale" else FLEET_DEVICES
    timeout = SCALE_TIMEOUT_S if kind == "scale" else FLEET_TIMEOUT_S
    env = forced_host_device_env(devices)
    with tempfile.TemporaryDirectory(prefix="bench_mesh_") as d:
        out = os.path.join(d, "cell.json")
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", kind,
             "--n", str(n), "--shrink-index", str(shrink_index),
             "--child-out", out],
            capture_output=True, text=True, timeout=timeout, cwd=REPO,
            env=env)
        if r.returncode != 0:
            raise RuntimeError(
                f"child {kind} n={n} failed:\n"
                f"{(r.stdout or '')[-2000:]}\n{(r.stderr or '')[-2000:]}")
        with open(out) as f:
            return json.load(f)


# ---------------------------------------------------------------------------
# parent cells
# ---------------------------------------------------------------------------

def _cell_oracle() -> dict:
    """The host reference, computed once: expected verdicts per family
    (fresh memoised Wing–Gong) and the failing-cas index the shrink
    lane minimizes in every child."""
    from qsm_tpu.ops.backend import Verdict
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    corpora = _family_corpora()
    verdicts = {}
    for fam, (spec, hists) in corpora.items():
        oracle = WingGongCPU(memo=True)
        verdicts[fam] = [int(v)
                         for v in oracle.check_histories(spec, hists)]
    failing = [i for i, v in enumerate(verdicts["cas"])
               if v == int(Verdict.VIOLATION)]
    assert failing, "bench corpus lost its failing cas lanes"
    return {"verdicts": verdicts, "shrink_index": failing[0],
            "budget_code": int(Verdict.BUDGET_EXCEEDED)}


def _cell_parity(scale: dict, oracle: dict) -> dict:
    """Bit-identity across every mesh shape + the zero-wrong audit."""
    base = scale[DEVICE_COUNTS[0]]
    budget = oracle["budget_code"]
    families = {}
    wrong = 0
    for fam in base["families"]:
        v0 = base["families"][fam]["verdicts"]
        w0 = base["families"][fam]["witnesses"]
        v_ok = all(scale[n]["families"][fam]["verdicts"] == v0
                   for n in DEVICE_COUNTS)
        w_ok = all(scale[n]["families"][fam]["witnesses"] == w0
                   for n in DEVICE_COUNTS)
        want = oracle["verdicts"][fam]
        for n in DEVICE_COUNTS:
            got = scale[n]["families"][fam]["verdicts"]
            wrong += sum(1 for g, w in zip(got, want)
                         if g != w and budget not in (g, w))
        families[fam] = {"verdicts_identical": v_ok,
                         "witnesses_identical": w_ok}
    shrink_ok = all(scale[n]["shrink_rows"] == base["shrink_rows"]
                    and scale[n]["shrink_ok"] for n in DEVICE_COUNTS)
    monitor_ok = all(
        scale[n]["monitor_verdicts"] == base["monitor_verdicts"]
        for n in DEVICE_COUNTS)
    witness_failures = sum(scale[n]["witness_failures"]
                           for n in DEVICE_COUNTS)
    return {
        "device_counts": list(DEVICE_COUNTS),
        "families": families,
        "verdicts_identical": all(f["verdicts_identical"]
                                  for f in families.values()),
        "witnesses_identical": all(f["witnesses_identical"]
                                   for f in families.values()),
        "shrink_rows_identical": shrink_ok,
        "monitor_verdicts_identical": monitor_ok,
        "witness_failures": witness_failures,
        "wrong_verdicts": wrong,
    }


def run(tag: str, out_path, resume: bool) -> dict:
    from qsm_tpu.resilience.checkpoint import CellJournal

    path = out_path or os.path.join(REPO, f"BENCH_MESH_{tag}.json")
    header = {
        "artifact": "BENCH_MESH",
        "device_fallback": None,   # host-only: forced virtual devices
        "platform": "cpu",
        "device_counts": list(DEVICE_COUNTS),
        "families": [f[0] for f in FAMILY_SHAPES],
        "lanes_total": sum(f[1] for f in FAMILY_SHAPES),
        "budget": BUDGET,
        "fleet_devices_per_node": FLEET_DEVICES,
        "collapse_tol": COLLAPSE_TOL,
        "host_cores": os.cpu_count(),
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)

    oracle = journal.complete("oracle")
    if oracle is None:
        oracle = journal.emit("oracle", _cell_oracle())

    scale = {}
    for n in DEVICE_COUNTS:
        cell = journal.complete(f"scale_d{n}")
        if cell is None:
            cell = journal.emit(
                f"scale_d{n}",
                _spawn_child("scale", n, oracle["shrink_index"]))
        scale[n] = cell

    parity = journal.complete("parity")
    if parity is None:
        parity = journal.emit("parity", _cell_parity(scale, oracle))

    fleet = {}
    for n in FLEET_NODES:
        cell = journal.complete(f"fleet_n{n}")
        if cell is None:
            cell = journal.emit(f"fleet_n{n}", _spawn_child("fleet", n))
        fleet[n] = cell

    host_cores = os.cpu_count() or 1
    rates = {n: scale[n]["lanes_per_sec"] for n in DEVICE_COUNTS}
    d1 = rates[DEVICE_COUNTS[0]]
    ratio = (fleet[3]["histories_per_sec"]
             / max(fleet[1]["histories_per_sec"], 1e-9))
    summary = {
        "metric": "mesh_parity_and_scaling",
        "host_cores": host_cores,
        "lanes_per_sec": rates[DEVICE_COUNTS[-1]],
        "lanes_per_sec_by_devices": {str(n): rates[n]
                                     for n in DEVICE_COUNTS},
        "ratio_d8_vs_d1": round(rates[DEVICE_COUNTS[-1]]
                                / max(d1, 1e-9), 2),
        # module docstring: virtual devices multiply partitions, not
        # FLOPs — the throughput gate on this box is no-collapse; a
        # monotone curve is the multi-chip window's claim to bank
        "gate_no_collapse": bool(
            min(rates.values()) >= COLLAPSE_TOL * d1),
        "parity_bit_identical": bool(
            parity["verdicts_identical"]
            and parity["witnesses_identical"]
            and parity["shrink_rows_identical"]
            and parity["monitor_verdicts_identical"]),
        "wrong_verdicts": parity["wrong_verdicts"],
        "witness_failures": parity["witness_failures"],
        # the r13 waiver, DECIDED: both fleet cells really ran under
        # the forced mesh, so the ratio is a measurement either way
        "fleet_n1_hps": fleet[1]["histories_per_sec"],
        "fleet_n3_hps": fleet[3]["histories_per_sec"],
        "fleet_wrong_verdicts": sum(f["wrong_verdicts"]
                                    for f in fleet.values()),
        "ratio_n3_vs_n1": round(ratio, 2),
        "gate_2x_at_3_nodes": bool(ratio >= 2.0),
        "gate_waived_insufficient_cores": False,
        "gate_decided": True,
        "scaling_honesty": (
            f"host has {host_cores} core(s): every forced-device mesh "
            "and every fleet node shares it, so the recorded curves "
            "measure dispatch overhead and gate decisions, not chip "
            "scaling; the parity/zero-wrong gates are absolute"),
    }
    summary["gate_ok"] = bool(
        summary["parity_bit_identical"]
        and summary["wrong_verdicts"] == 0
        and summary["witness_failures"] == 0
        and summary["fleet_wrong_verdicts"] == 0
        and summary["gate_no_collapse"])
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tag", default="r19")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="skip cells already banked in a compatible "
                         "prior artifact (CellJournal rails)")
    ap.add_argument("--child", choices=("scale", "fleet"), default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--n", type=int, default=1, help=argparse.SUPPRESS)
    ap.add_argument("--shrink-index", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--child-out", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.child is not None:
        cell = (_child_scale(args.n, args.shrink_index)
                if args.child == "scale" else _child_fleet(args.n))
        with open(args.child_out, "w") as f:
            json.dump(cell, f)
        return 0
    summary = run(args.tag, args.out, args.resume)
    print(summary)
    return 0 if summary["gate_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
