"""End-to-end property throughput — trials/sec and the execute/check/shrink
wall-clock split (VERDICT.md round 2, "Next round" #8).

The 100× story is about the checking workload (SURVEY.md §3.5); this
artifact measures whether checking is actually where end-to-end time goes,
per backend.  Two runs per backend on the CAS 32×8 config:

* atomic SUT — no violation, steady-state generate/execute/check split;
* racy SUT — finds a violation and shrinks: the shrink split shows what
  batching shrink candidates into one backend call buys.

Usage: python tools/bench_e2e.py [--force-cpu] [--out BENCH_E2E_rN.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, "/root/repo")


def run_one(label: str, backend_name: str, make_backend, sut_name: str,
            n_trials: int, trial_batch: int = 1) -> dict:
    from qsm_tpu.core.property import PropertyConfig, prop_concurrent
    from qsm_tpu.models.registry import make
    from qsm_tpu.resilience.failover import collect_resilience

    spec, sut = make("cas", sut_name)
    backend = make_backend(spec)
    cfg = PropertyConfig(n_trials=n_trials, n_pids=8, max_ops=32, seed=7,
                         schedules_per_program=4, trial_batch=trial_batch)
    t0 = time.perf_counter()
    res = prop_concurrent(spec, sut, cfg, backend=backend)
    dt = time.perf_counter() - t0
    timings = {key: round(v, 3) for key, v in sorted(res.timings.items())}
    accounted = sum(res.timings.values())
    rz = collect_resilience(backend)
    return {
        "run": label, "backend": backend_name, "sut": sut_name,
        "ok": res.ok, "trials_run": res.trials_run,
        "histories_checked": res.histories_checked,
        "undecided": res.undecided,
        "seconds": round(dt, 2),
        "trials_per_sec": round(res.trials_run / dt, 2),
        "histories_per_sec": round(res.histories_checked / dt, 1),
        "timings_s": timings,
        "timings_pct": {key: round(100 * v / max(accounted, 1e-9), 1)
                        for key, v in sorted(res.timings.items())},
        "shrink_steps": (res.counterexample.shrink_steps
                         if res.counterexample else 0),
        # fault-handling self-description (qsm_tpu/resilience).  The
        # timings keys already fold the backend's own counters together
        # with property-layer degrade-to-oracle events (additive merge in
        # prop_concurrent), so they are the complete per-run count;
        # collect_resilience supplies the engine label and the zeros.
        "resilience": {
            "degradations": int(res.timings.get(
                "resilience_degradations", rz.get("degradations", 0))),
            "retries": int(res.timings.get(
                "resilience_retries", rz.get("retries", 0))),
            "fallback_engine": rz.get("fallback_engine"),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/root/repo/BENCH_E2E_r05.json")
    ap.add_argument("--force-cpu", action="store_true")
    ap.add_argument("--probe-timeout", type=float, default=None,
                    help="override the probe preset's per-attempt bound "
                         "(resilience/policy.py)")
    ap.add_argument("--trials", type=int, default=150)
    ap.add_argument("--resume", action="store_true",
                    help="adopt completed rows from an existing --out "
                         "journal (same artifact + device provenance) "
                         "instead of re-measuring them")
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import probe_or_force_cpu

    on_tpu, _detail, header = probe_or_force_cpu(args.force_cpu,
                                                 args.probe_timeout)

    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.resilience.checkpoint import CellJournal

    # per-cell journal (resilience/checkpoint.py): every row lands
    # atomically the moment it is measured — a window that closes mid-run
    # still banks the rows already measured (round-4's window_e2e died
    # twice leaving nothing; the all-at-the-end write was the reason) —
    # and --resume re-runs ZERO completed rows in the next window
    journal = CellJournal(args.out, {
        "artifact": "bench_e2e",
        "config": "cas 32ops x 8pids, 4 schedules", **header,
    }, resume=args.resume)
    def _hybrid(s):
        from qsm_tpu.ops.hybrid import HybridDevice

        return HybridDevice(s)

    # UNROLL stays on auto (8 on device, 1 on the CPU platform): e2e
    # corpora are tiny (4-256 histories/call), so the unrolled body's
    # ~2.4× compile cost lands INSIDE the measured runs and wipes out
    # the per-trip win on the fallback — measured: device atomic tb=1
    # fell 62 → 16 h/s with a forced unroll8 here, while the bench.py
    # corpus (4096+ lanes, warmup outside the timer) gains 5.2×.
    # EXCEPT on a real device with a banked scan verdict: then the e2e
    # device rows run whatever unroll the on-chip A/B decided, same as
    # the headline (bench.best_scale_unroll).
    adopted_unroll = None
    adopt_error = None
    if on_tpu:
        try:
            from bench import best_scale_unroll

            a = best_scale_unroll()
            adopted_unroll = a[0] if a else None
        except Exception as e:  # noqa: BLE001 — adoption is advisory,
            adopt_error = f"{type(e).__name__}: {e}"[:120]  # but recorded

    def _device(s):
        b = JaxTPU(s)
        if adopted_unroll is not None:
            b.UNROLL = adopted_unroll
        return b

    def _hybrid_adopted(s):
        b = _hybrid(s)
        if adopted_unroll is not None:
            b.device.UNROLL = adopted_unroll
        return b

    backends = {
        "memo": lambda s: WingGongCPU(memo=True),
        "device": _device,
        # device majority + host tail as one backend (ops/hybrid.py):
        # the e2e plan the scale-scan hybrid_derived row prices
        "hybrid": _hybrid_adopted,
    }
    try:
        from qsm_tpu.native import CppOracle, native_available

        if native_available():
            backends["cpp"] = lambda s: CppOracle(s)
    except Exception:  # noqa: BLE001 — optional fast path, never the bench
        pass
    # trial_batch=1 is the reference-shaped serial loop; 64 makes the
    # device see 256-lane batches (64 trials × 4 schedules) — the grouping
    # exists precisely because the split below showed per-call dispatch
    # dominating the device path at batch 4.  On a real device the
    # device-path rows run FIRST: they are the rows only a window can
    # measure (round-3 task #8, still open on-chip), and host rows would
    # burn window wall-clock on the host core.
    names = list(backends)
    if on_tpu:
        names.sort(key=lambda n: n not in ("device", "hybrid"))
    for bname in names:
        mk = backends[bname]
        for sut_name in ("atomic", "racy"):
            for tb in ((1,) if bname not in ("device", "hybrid")
                       else (1, 64)):
                key = f"{bname}:{sut_name}:tb{tb}"
                rec = journal.complete(key)
                if rec is None:
                    rec = run_one(f"cas-{sut_name}", bname, mk, sut_name,
                                  args.trials, trial_batch=tb)
                    rec["trial_batch"] = tb
                    if bname in ("device", "hybrid"):
                        # settings stamp: two artifacts with different
                        # effective UNROLL must be distinguishable
                        rec["unroll"] = (adopted_unroll if adopted_unroll
                                         is not None
                                         else ("auto" if on_tpu else 1))
                        rec["unroll_from_scale"] = adopted_unroll
                        if adopt_error:
                            rec["unroll_adopt_error"] = adopt_error
                    rec = journal.emit(key, rec)
                print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
