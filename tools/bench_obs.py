"""Obs-overhead bench — what does the trace plane cost the serve path?

ISSUE 11's acceptance bar: tracing-OFF overhead on the serve bench
path stays within noise (≤5%) of a no-obs baseline, and tracing-ON
cost is recorded honestly rather than assumed free.  Three cells, each
the same workload (R rounds × N distinct cas histories through a
single-process CheckServer over one client connection — the committed
BENCH_SERVE shape, corpus re-seeded per round so the checking path is
measured, not the cache):

* ``no_obs``       — the pre-obs build, simulated: the server's obs
  bundle is replaced by a null object whose every emit site is a
  no-op and whose request-latency histogram is stubbed out, so the
  hot path runs exactly the instructions it ran before this plane
  existed (minus the single ``if obs.on`` branches, which cannot be
  removed without a different build — stated, not hidden).
* ``tracing_off``  — the production default: obs constructed, tracing
  and flight disabled.  THE GATE CELL: its throughput must be within
  ``GATE_PCT`` of ``no_obs``.
* ``tracing_on``   — span log + flight ring enabled (metrics are
  always on): the honest price of full tracing, reported with the
  span-event count so events/history is reconstructible.

Fleet cells (ISSUE 15 — the plane went fleet-wide): the same recorded
mix driven through a 2-node fleet router, nodes tracing to their own
span logs:

* ``fleet_collect_off`` — router beat running, span COLLECTION off:
  the fleet baseline;
* ``fleet_collect_on``  — the router's collection sweep scraping both
  nodes' span logs (``obs.spans`` cursor pages) into the collected
  log on the same beat.  THE FLEET GATE CELL: within ``GATE_PCT`` of
  ``fleet_collect_off`` (an honesty row records when the 1–2-core
  host cannot host 4 processes without contention distorting it);
* ``federation_scrape`` — latency of one federated ``/metrics``
  scrape (scrape-time fan-out to both nodes), p50/p95 over N scrapes.

Output: a resumable ``CellJournal`` committed as
``BENCH_OBS_<tag>.json`` (``make bench-obs``; probe_watcher archives
it off-window beside the LINT/PCOMP/SHRINK artifacts).
"""

from __future__ import annotations

import argparse
import datetime
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = "cas"
PIDS, OPS = 4, 10
CORPUS_N = 32
ROUNDS = 6
REPS = 3           # cell repetitions; the best rep is the cell's rate
GATE_PCT = 5.0
FLEET_ROUNDS = 4   # fleet cells: the same mix through a 2-node router
FLEET_REPS = 2
FEDERATION_SCRAPES = 20


class _NullSpan:
    id = ""

    def add(self, **_a):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *_e):
        return None


class _NullObs:
    """The no-obs stand-in: same surface as Observability, zero work.
    ``metrics`` stays a real registry only because the constructor
    registers collectors against it — nothing observes into it during
    the bench."""

    on = False
    flight = None

    def __init__(self):
        from qsm_tpu.obs import MetricsRegistry

        self.metrics = MetricsRegistry()
        self.tracer = self
        self.events = 0
        self.enabled = False

    def span(self, *_a, **_k):
        return _NullSpan()

    def event(self, *_a, **_k):
        return ""

    def emit(self, *_a, **_k):
        return None

    def note_shed(self):
        return None

    def flight_path(self):
        return None

    def dump_flight(self, *_a, **_k):
        return None

    def close(self):
        return None

    def snapshot(self):
        return {"tracing": {"enabled": False, "events": 0},
                "flight": None}


def _corpus(spec, entry, seed_prefix):
    from qsm_tpu.utils.corpus import build_corpus

    return build_corpus(
        spec, (entry.impls["atomic"], entry.impls["racy"]),
        n=CORPUS_N, n_pids=PIDS, max_ops=OPS, seed_prefix=seed_prefix)


def _run_cell(kind: str, workdir: str) -> dict:
    """One cell: build the server variant, push ROUNDS distinct corpora
    through one client, return the best-rep rate + obs accounting."""
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.serve.client import CheckClient
    from qsm_tpu.serve.server import CheckServer

    entry = MODELS[MODEL]
    spec = entry.make_spec()
    kw = {}
    if kind == "no_obs":
        kw["obs"] = _NullObs()
    elif kind == "tracing_on":
        kw["trace_log"] = os.path.join(workdir, f"trace_{kind}.jsonl")
        kw["flight_dir"] = os.path.join(workdir, f"flight_{kind}")
    rep_rates = []
    events = 0
    for rep in range(REPS):
        server = CheckServer(max_lanes=CORPUS_N, **kw).start()
        try:
            if kind == "no_obs":
                # stub the always-on request-latency histogram too: the
                # pre-obs build had no observe() on the request path
                server._m_request_s = _NullHist()
            server.warm(MODEL)
            corpora = [
                _corpus(spec, entry, f"bench_obs_{rep}_{r}")
                for r in range(ROUNDS)]
            client = CheckClient(f"127.0.0.1:{server.port}")
            t0 = time.perf_counter()
            for hists in corpora:
                res = client.check(MODEL, hists, deadline_s=120)
                assert res.get("ok"), res
            dt = time.perf_counter() - t0
            client.close()
            rep_rates.append(ROUNDS * CORPUS_N / dt)
            events = server.obs.snapshot()["tracing"].get("events", 0)
        finally:
            server.stop()
    return {"cell": kind, "reps": REPS, "rounds": ROUNDS,
            "histories": ROUNDS * CORPUS_N,
            "rates_h_per_s": [round(r, 1) for r in rep_rates],
            "histories_per_sec": round(max(rep_rates), 1),
            "span_events": events}


class _NullHist:
    def observe(self, *_a, **_k):
        return None


def _run_fleet_cell(kind: str, workdir: str) -> dict:
    """One fleet cell: 2 in-process nodes (tracing to their own span
    logs) behind a router; the collection beat is the only variable
    between ``fleet_collect_off`` and ``fleet_collect_on``."""
    from qsm_tpu.fleet.router import FleetRouter
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.resilience.policy import preset
    from qsm_tpu.serve.client import CheckClient
    from qsm_tpu.serve.server import CheckServer

    entry = MODELS[MODEL]
    spec = entry.make_spec()
    rep_rates = []
    collected = 0
    for rep in range(FLEET_REPS):
        cdir = os.path.join(workdir, f"{kind}_{rep}")
        # the tracer degrades (by design) instead of creating parents:
        # a missing cell dir would silently bench an empty span log
        os.makedirs(cdir, exist_ok=True)
        nodes = [CheckServer(
            node_id=f"n{i}",
            trace_log=os.path.join(cdir, f"n{i}.jsonl"),
            flush_s=0.005).start() for i in range(2)]
        router_kw = {}
        if kind == "fleet_collect_on":
            router_kw["collect_dir"] = os.path.join(cdir, "collect")
            router_kw["collect_s"] = 0.25
        router = FleetRouter(
            [(s.node_id, s.address) for s in nodes],
            policy=preset("fleet-route").with_(timeout_s=10.0),
            probe_policy=preset("fleet-probe").with_(timeout_s=2.0),
            heartbeat_s=0.5,
            # the beat thread runs either way (equal baseline): ae
            # sweeps no-op against replog-less nodes, so the only
            # working difference between the cells is collection
            anti_entropy_s=0.25,
            trace_log=os.path.join(cdir, "router.jsonl"),
            **router_kw).start()
        try:
            for s in nodes:
                s.warm(MODEL)
            corpora = [
                _corpus(spec, entry, f"bench_obs_fleet_{rep}_{r}")
                for r in range(FLEET_ROUNDS)]
            client = CheckClient(router.address, timeout_s=120.0)
            t0 = time.perf_counter()
            for hists in corpora:
                res = client.check(MODEL, hists, deadline_s=120)
                assert res.get("ok"), res
            dt = time.perf_counter() - t0
            client.close()
            rep_rates.append(FLEET_ROUNDS * CORPUS_N / dt)
            if router.collector is not None:
                router.collect_sweep()  # the tail the beat missed
                collected = router.collector.snapshot()[
                    "events_collected"]
        finally:
            router.stop()
            for s in nodes:
                s.stop()
    return {"cell": kind, "reps": FLEET_REPS, "rounds": FLEET_ROUNDS,
            "histories": FLEET_ROUNDS * CORPUS_N,
            "rates_h_per_s": [round(r, 1) for r in rep_rates],
            "histories_per_sec": round(max(rep_rates), 1),
            "events_collected": collected}


def _run_federation_cell(workdir: str) -> dict:
    """Federated-scrape latency: one /metrics fan-out to both nodes,
    timed over N scrapes after a small warm mix."""
    from qsm_tpu.fleet.router import FleetRouter
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.resilience.policy import preset
    from qsm_tpu.serve.client import CheckClient
    from qsm_tpu.serve.server import CheckServer

    entry = MODELS[MODEL]
    spec = entry.make_spec()
    cdir = os.path.join(workdir, "federation")
    os.makedirs(cdir, exist_ok=True)
    nodes = [CheckServer(node_id=f"n{i}",
                         flush_s=0.005).start() for i in range(2)]
    router = FleetRouter(
        [(s.node_id, s.address) for s in nodes],
        policy=preset("fleet-route").with_(timeout_s=10.0),
        probe_policy=preset("fleet-probe").with_(timeout_s=2.0),
        heartbeat_s=0.5, anti_entropy_s=0.0,
        trace_log=os.path.join(cdir, "router.jsonl")).start()
    try:
        client = CheckClient(router.address, timeout_s=120.0)
        res = client.check(MODEL, _corpus(spec, entry, "bench_obs_fed"),
                           deadline_s=120)
        assert res.get("ok"), res
        times = []
        n_samples = 0
        for _ in range(FEDERATION_SCRAPES):
            t0 = time.perf_counter()
            doc = client.metrics()
            times.append((time.perf_counter() - t0) * 1000.0)
            assert doc.get("ok"), doc
            n_samples = len(doc.get("samples") or [])
        client.close()
    finally:
        router.stop()
        for s in nodes:
            s.stop()
    times.sort()
    import math

    # nearest-rank percentiles: ceil(q*N)-1 (int(N*0.95) would index
    # the MAX for N=20 and report the outlier as p95)
    p50 = times[max(0, math.ceil(0.50 * len(times)) - 1)]
    p95 = times[max(0, math.ceil(0.95 * len(times)) - 1)]
    return {"cell": "federation_scrape",
            "scrapes": FEDERATION_SCRAPES,
            "samples_per_scrape": n_samples,
            "p50_ms": round(p50, 2),
            "p95_ms": round(p95, 2),
            "max_ms": round(times[-1], 2)}


def run(tag: str, out_path, resume: bool) -> dict:
    from qsm_tpu.resilience.checkpoint import CellJournal

    path = out_path or os.path.join(REPO, f"BENCH_OBS_{tag}.json")
    header = {
        "artifact": "BENCH_OBS",
        "device_fallback": None,   # host-only bench: no window involved
        "platform": "cpu",
        "model": MODEL, "pids": PIDS, "ops": OPS,
        "corpus_n": CORPUS_N, "rounds": ROUNDS, "reps": REPS,
        "gate_pct": GATE_PCT,
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)
    workdir = tempfile.mkdtemp(prefix="qsm_bench_obs_")
    cells = {}
    for kind in ("no_obs", "tracing_off", "tracing_on"):
        row = journal.complete(kind)
        if row is None:
            row = journal.emit(kind, _run_cell(kind, workdir))
        cells[kind] = row
    for kind in ("fleet_collect_off", "fleet_collect_on"):
        row = journal.complete(kind)
        if row is None:
            row = journal.emit(kind, _run_fleet_cell(kind, workdir))
        cells[kind] = row
    row = journal.complete("federation_scrape")
    if row is None:
        row = journal.emit("federation_scrape",
                           _run_federation_cell(workdir))
    cells["federation_scrape"] = row
    base = cells["no_obs"]["histories_per_sec"]
    off = cells["tracing_off"]["histories_per_sec"]
    on = cells["tracing_on"]["histories_per_sec"]
    f_off = cells["fleet_collect_off"]["histories_per_sec"]
    f_on = cells["fleet_collect_on"]["histories_per_sec"]
    overhead_off = round((base - off) / base * 100.0, 2) if base else 0.0
    overhead_on = round((base - on) / base * 100.0, 2) if base else 0.0
    overhead_collect = (round((f_off - f_on) / f_off * 100.0, 2)
                        if f_off else 0.0)
    host_cores = os.cpu_count() or 1
    events_collected = cells["fleet_collect_on"].get(
        "events_collected", 0)
    # a collect-on cell that collected nothing measured nothing: the
    # overhead number would be vacuously flattering — refuse the gate
    collect_ok = (overhead_collect <= GATE_PCT
                  and events_collected > 0)
    summary = {
        "no_obs_h_per_s": base,
        "tracing_off_h_per_s": off,
        "tracing_on_h_per_s": on,
        # negative = the obs-off build measured FASTER than the null
        # baseline (pure run-to-run noise); the gate is one-sided
        "tracing_off_overhead_pct": overhead_off,
        "tracing_on_overhead_pct": overhead_on,
        "fleet_collect_off_h_per_s": f_off,
        "fleet_collect_on_h_per_s": f_on,
        "collect_overhead_pct": overhead_collect,
        "federation_scrape_p50_ms":
            cells["federation_scrape"]["p50_ms"],
        "gate_pct": GATE_PCT,
        "host_cores": host_cores,
        "gate_ok": overhead_off <= GATE_PCT and collect_ok,
        "span_events_on": cells["tracing_on"].get("span_events", 0),
        "events_collected": events_collected,
    }
    if not collect_ok and events_collected > 0 and host_cores < 4:
        # the r08/r12-style honesty row: router + 2 nodes + client is
        # 4 processes — a 1–2-core host measures contention, not the
        # collection plane.  Waivable ONLY when collection actually
        # ran (events_collected > 0): a zero-collection cell measured
        # nothing and must fail outright, never be waived away.
        summary["gate_ok"] = overhead_off <= GATE_PCT
        summary["collect_gate_waived_insufficient_cores"] = True
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tag", default="r15")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="skip cells already banked in a compatible "
                         "prior artifact (CellJournal rails)")
    args = ap.parse_args(argv)
    summary = run(args.tag, args.out, args.resume)
    print(summary)
    return 0 if summary["gate_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
