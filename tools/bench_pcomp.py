"""P-compositionality bench — long-history kv corpora, decomposed vs whole.

Search cost is exponential in history length, so the repo's corpora
stalled at 64 ops: a 256-op kv history fits no native 64-bit taken mask
and no useful memo budget whole, while its per-key sub-histories are
16 short register histories.  Round 9 (ISSUE 9) wires the per-key split
(ops/pcomp.py, Horn & Kroening PAPERS.md:5) end-to-end; this tool prices
it on the CPU platform — no window required — at 64/256/1024 ops:

* ``decomp_{ops}`` — ``PComp`` over the host cpp→memo ladder (the serve
  plane's ``auto`` shape): one planned batch of ALL per-key
  sub-histories.  EVERY verdict is independently verified: LINEARIZABLE
  must yield a stitched whole-history witness that ``verify_witness``
  replays (the decomposed path's certificate), VIOLATION must be
  re-found by a FRESH memo oracle on at least one per-key sub-history.
* ``whole_{ops}`` — the undecomposed host ladder (native C++ when the
  toolchain is present, bounded memo oracle past its 64-op mask),
  per-history under a node budget and a per-cell time box: the honest
  "what this cost before" denominator.  Histories the box cuts are
  ``unattempted`` (never silently skipped), so the per-history cost is
  a LOWER bound and every ratio derived from it is conservative.
* ``serve_pool`` — split lanes riding the WORKER POOL: a 2-worker
  ``CheckServer`` decomposes kv-256 requests into register sub-lanes,
  micro-batches them across 2 clients, banks per-sub-history cache
  rows, and a one-key change to a checked history re-checks exactly
  one key.  Verdict names are pinned to the direct decomposed run.

Win condition (ISSUE 9 acceptance): kv-256 decomposed ≥10× the whole
path on wall-clock AND search nodes/history, kv-1024 fully decided by
the decomposed path (the whole path cannot), zero wrong verdicts, and
every decomposed LINEARIZABLE history carrying a verified stitched
witness.  Output: a resumable ``CellJournal`` (``--resume`` re-runs
zero completed cells) committed as ``BENCH_PCOMP_<tag>.json``.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_KEYS = 16
N_VALUES = 4
N_PIDS = 16
# (ops, corpus size, whole-path node budget): the budget shrinks with
# length because the whole path's per-node cost grows with the taken
# tuple — the box, not the budget, is the real bound past 256 ops
SIZES = ((64, 24, 20_000_000), (256, 16, 1_000_000), (1024, 8, 200_000))
TIME_BOX_S = 150.0      # per whole_{ops} cell
SERVE_OPS = 256
SERVE_CLIENTS = 2
SERVE_WORKERS = 2
SERVE_DEADLINE_S = 300.0


def _spec():
    from qsm_tpu.models import KvSpec

    return KvSpec(n_keys=N_KEYS, n_values=N_VALUES)


def _corpus(spec, n_ops: int, n: int):
    from qsm_tpu.models import AtomicKvSUT, StaleCacheKvSUT
    from qsm_tpu.utils.corpus import build_corpus

    return build_corpus(
        spec, (AtomicKvSUT, StaleCacheKvSUT), n=n, n_pids=N_PIDS,
        max_ops=n_ops, seed_base=n_ops * 1000,
        seed_prefix=f"bench_pcomp_{n_ops}")


def _host_ladder(spec, node_budget: int):
    """The undecomposed host path exactly as shipped (cpp→memo), with
    an explicit node budget so 256/1024-op cells terminate honestly
    (BUDGET_EXCEEDED, never a guess)."""
    from qsm_tpu.native import CppOracle, native_available
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    if native_available():
        return CppOracle(spec, node_budget=node_budget)
    return WingGongCPU(memo=True, node_budget=node_budget)


def bench_decomposed(spec, corpus, n_ops: int) -> dict:
    """One planned decomposed batch + independent verification of every
    verdict (module docstring)."""
    from qsm_tpu.ops.backend import Verdict, verify_witness
    from qsm_tpu.ops.pcomp import PComp, split_history
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.resilience.failover import host_fallback
    from qsm_tpu.search.planner import plan_search, profile_corpus

    profile = profile_corpus(corpus, spec)
    plan = plan_search(spec, profile, platform="cpu")
    pc = PComp(spec, make_inner=host_fallback)
    t0 = time.perf_counter()
    verdicts = np.asarray(pc.check_histories(spec, corpus))
    wall = time.perf_counter() - t0
    st = pc.search_stats()

    # -- verification (outside the timed region: it is audit, not cost)
    wrong = 0
    witnesses_verified = 0
    violations_reconfirmed = 0
    t_verify = time.perf_counter()
    for h, v in zip(corpus, verdicts):
        if v == int(Verdict.LINEARIZABLE):
            wv, w = pc.check_witness(spec, h)
            if (wv != Verdict.LINEARIZABLE or w is None
                    or not verify_witness(spec, h, w)):
                wrong += 1
            else:
                witnesses_verified += 1
        elif v == int(Verdict.VIOLATION):
            # a fresh, memo-only oracle must re-find the violation in
            # some per-key sub-history — independent of the ladder that
            # produced the verdict
            subs = list(split_history(spec, h).values())
            fresh = WingGongCPU(memo=True)
            sub_v = fresh.check_histories(spec.projected_spec(), subs)
            if int((np.asarray(sub_v) == int(Verdict.VIOLATION)).sum()):
                violations_reconfirmed += 1
            else:
                wrong += 1
    verify_s = time.perf_counter() - t_verify
    n = len(corpus)
    return {
        "engine": pc.name,
        "ops": n_ops, "histories": n,
        "seconds": round(wall, 3),
        "seconds_per_history": round(wall / n, 4),
        "histories_per_sec": round(n / wall, 1),
        "undecided": int((verdicts == int(Verdict.BUDGET_EXCEEDED)).sum()),
        "violations": int((verdicts == int(Verdict.VIOLATION)).sum()),
        "nodes_per_history": round(st.nodes_per_history, 1),
        "wrong_verdicts": wrong,
        "witnesses_verified": witnesses_verified,
        "violations_reconfirmed": violations_reconfirmed,
        "verify_seconds": round(verify_s, 3),
        "plan": plan.describe(),
        "search": st.to_compact(),
    }


def bench_whole(spec, corpus, n_ops: int, node_budget: int) -> dict:
    """The undecomposed denominator: history by history so the time box
    can cut between histories — a cut history is ``unattempted``, never
    half-measured."""
    from qsm_tpu.ops.backend import Verdict
    from qsm_tpu.search.stats import collect_search_stats

    ladder = _host_ladder(spec, node_budget)
    verdicts = []
    t0 = time.perf_counter()
    attempted = 0
    for h in corpus:
        if time.perf_counter() - t0 > TIME_BOX_S:
            break
        verdicts.append(int(ladder.check_histories(spec, [h])[0]))
        attempted += 1
    wall = time.perf_counter() - t0
    st = collect_search_stats(ladder)
    v = np.asarray(verdicts)
    row = {
        "engine": getattr(ladder, "name", type(ladder).__name__),
        "ops": n_ops, "histories": len(corpus),
        "attempted": attempted,
        "unattempted": len(corpus) - attempted,
        "node_budget": node_budget,
        "time_box_s": TIME_BOX_S,
        "seconds": round(wall, 3),
        "undecided": int((v == int(Verdict.BUDGET_EXCEEDED)).sum()),
        "violations": int((v == int(Verdict.VIOLATION)).sum()),
        "nodes_per_history": (round(st.nodes_explored / attempted, 1)
                              if st is not None and attempted else None),
    }
    if attempted:
        row["seconds_per_history"] = round(wall / attempted, 4)
        row["histories_per_sec"] = round(attempted / wall, 1)
        if len(corpus) - attempted or row["undecided"]:
            row["note"] = ("time-boxed/budgeted: per-history cost is a "
                           "LOWER bound, ratios derived from it are "
                           "conservative")
    return row


def _one_key_variant(spec, h):
    """A copy of ``h`` with ONE op's value changed on its own key — the
    sub-cache demo input (every other key's sub-history fingerprint is
    unchanged)."""
    import dataclasses

    from qsm_tpu.core.history import History
    from qsm_tpu.models.kv import PUT

    ops = list(h.ops)
    for j, op in enumerate(ops):
        if op.cmd == PUT:
            ops[j] = dataclasses.replace(
                op, arg=(op.arg - op.arg % N_VALUES)
                + ((op.arg % N_VALUES) + 1) % N_VALUES)
            break
    return History(ops)


def bench_serve_pool(spec, corpus, expected_names) -> dict:
    """Split lanes riding the worker pool (module docstring)."""
    import tempfile

    from qsm_tpu.serve.client import CheckClient
    from qsm_tpu.serve.server import CheckServer

    kw = {"n_keys": N_KEYS, "n_values": N_VALUES}
    tmp = tempfile.mkdtemp(prefix="qsm_bench_pcomp_")
    srv = CheckServer(unix_path=os.path.join(tmp, "sock"),
                      workers=SERVE_WORKERS,
                      cache_path=os.path.join(tmp, "bank.jsonl")).start()
    try:
        halves = [corpus[::2], corpus[1::2]]
        results: list = [None] * SERVE_CLIENTS
        t0 = time.perf_counter()

        def client(ci: int) -> None:
            c = CheckClient(srv.address, timeout_s=SERVE_DEADLINE_S + 30)
            try:
                results[ci] = c.check("kv", halves[ci], spec_kwargs=kw,
                                      deadline_s=SERVE_DEADLINE_S)
            finally:
                c.close()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(SERVE_CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        served = {0: results[0]["verdicts"], 1: results[1]["verdicts"]}
        want = {0: [expected_names[i] for i in range(0, len(corpus), 2)],
                1: [expected_names[i] for i in range(1, len(corpus), 2)]}
        wrong = sum(a != b for ci in (0, 1)
                    for a, b in zip(served[ci], want[ci]))
        # one-key change: only the touched key's sub-lane may re-check
        c = CheckClient(srv.address, timeout_s=SERVE_DEADLINE_S + 30)
        try:
            st1 = c.stats()["stats"]
            res3 = c.check("kv", [_one_key_variant(spec, corpus[0])],
                           spec_kwargs=kw, deadline_s=SERVE_DEADLINE_S)
            st2 = c.stats()["stats"]
        finally:
            c.close()
        d_subs = (st2["pcomp"]["sub_lanes"] - st1["pcomp"]["sub_lanes"])
        d_hits = (st2["pcomp"]["sub_cache_hits"]
                  - st1["pcomp"]["sub_cache_hits"])
        pool_rows = st2.get("pool") or {}
        n = len(corpus)
        return {
            "workers": SERVE_WORKERS, "clients": SERVE_CLIENTS,
            "ops": SERVE_OPS, "histories": n,
            "seconds": round(wall, 3),
            "histories_per_sec": round(n / wall, 1),
            "wrong_verdicts": wrong + (0 if res3.get("ok") else 1),
            "pcomp": st2["pcomp"],
            "one_key_change": {
                "sub_lanes": d_subs, "sub_cache_hits": d_hits,
                "recheck_keys": d_subs - d_hits},
            "pool": pool_rows,
            "batches": results[0].get("batches"),
        }
    finally:
        srv.stop()


def run(tag: str, out_path: str | None, resume: bool) -> dict:
    from qsm_tpu.resilience.checkpoint import CellJournal

    spec = _spec()
    path = out_path or os.path.join(REPO, f"BENCH_PCOMP_{tag}.json")
    header = {
        "artifact": "BENCH_PCOMP",
        "device_fallback": None,   # host-only bench: no window involved
        "platform": "cpu",
        "model": "kv", "n_keys": N_KEYS, "n_values": N_VALUES,
        "pids": N_PIDS,
        "sizes": [s[0] for s in SIZES],
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)
    corpora = {}

    def corpus_for(n_ops: int, n: int):
        if n_ops not in corpora:
            corpora[n_ops] = _corpus(spec, n_ops, n)
        return corpora[n_ops]

    for n_ops, n, budget in SIZES:
        if journal.complete(f"decomp_{n_ops}") is None:
            journal.emit(f"decomp_{n_ops}",
                         bench_decomposed(spec, corpus_for(n_ops, n),
                                          n_ops))
        if journal.complete(f"whole_{n_ops}") is None:
            journal.emit(f"whole_{n_ops}",
                         bench_whole(spec, corpus_for(n_ops, n), n_ops,
                                     budget))
    if journal.complete("serve_pool") is None:
        n = dict((s[0], s[1]) for s in SIZES)[SERVE_OPS]
        corpus = corpus_for(SERVE_OPS, n)
        dec = journal.complete(f"decomp_{SERVE_OPS}")
        # the decomposed cell is the serve cell's verdict reference —
        # recompute the names the same engine produced
        from qsm_tpu.ops.pcomp import PComp
        from qsm_tpu.resilience.failover import host_fallback
        from qsm_tpu.serve.protocol import VERDICT_NAMES

        ref = PComp(spec, make_inner=host_fallback).check_histories(
            spec, corpus)
        names = [VERDICT_NAMES[int(v)] for v in ref]
        assert dec is not None
        journal.emit("serve_pool", bench_serve_pool(spec, corpus, names))

    d256 = journal.complete("decomp_256")
    w256 = journal.complete("whole_256")
    d1024 = journal.complete("decomp_1024")
    w1024 = journal.complete("whole_1024")
    serve = journal.complete("serve_pool")
    wall_ratio = (w256["seconds_per_history"]
                  / max(d256["seconds_per_history"], 1e-9)
                  if w256.get("seconds_per_history") else None)
    nodes_ratio = (w256["nodes_per_history"]
                   / max(d256["nodes_per_history"], 1e-9)
                   if w256.get("nodes_per_history") else None)
    rows = [journal.complete(f"{kind}_{s[0]}")
            for s in SIZES for kind in ("decomp", "whole")]
    wrong_total = sum((r or {}).get("wrong_verdicts", 0) for r in rows) \
        + serve.get("wrong_verdicts", 0)
    summary = {
        "metric": "kv256_decomposed_vs_whole",
        "wall_ratio_256": round(wall_ratio, 1) if wall_ratio else None,
        "nodes_ratio_256": round(nodes_ratio, 1) if nodes_ratio else None,
        "gate_10x_wall": bool(wall_ratio and wall_ratio >= 10),
        "gate_10x_nodes": bool(nodes_ratio and nodes_ratio >= 10),
        "kv1024_decomposed_decided": (d1024["undecided"] == 0
                                      and d1024["wrong_verdicts"] == 0),
        "kv1024_whole_out_of_reach": bool(
            w1024["unattempted"] or w1024["undecided"]),
        "wrong_verdicts": wrong_total,
        "witnesses_verified": sum((journal.complete(f"decomp_{s[0]}")
                                   or {}).get("witnesses_verified", 0)
                                  for s in SIZES),
        "serve_pool_split_lanes": serve["pcomp"]["sub_lanes"],
        "one_key_recheck_keys": serve["one_key_change"]["recheck_keys"],
        "resumed_cells": journal.resumed_cells,
    }
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    print(json.dumps({"metric": summary["metric"],
                      "wall_ratio_256": summary["wall_ratio_256"],
                      "nodes_ratio_256": summary["nodes_ratio_256"],
                      "kv1024_decided": summary[
                          "kv1024_decomposed_decided"],
                      "wrong_verdicts": wrong_total,
                      "artifact": os.path.basename(path)}))
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tag", default="r09")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="adopt completed cells from an existing "
                         "artifact (CellJournal rails)")
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform()
    try:
        run(args.tag, args.out, args.resume)
    except Exception as e:  # noqa: BLE001 — diagnostic line, not a traceback
        print(json.dumps({"metric": "kv256_decomposed_vs_whole",
                          "error": f"{type(e).__name__}: {e}"}))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
