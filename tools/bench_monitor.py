"""Monitor bench — what does streaming buy over re-checking from scratch?

ISSUE 14's acceptance bars, as journal cells:

* ``streamed_growing``  — a growing EVENTS-event register stream fed
  chunk-by-chunk through an in-process ``MonitorSession`` (decide after
  every chunk — the live-monitor cadence).  The incremental frontier
  commits quiescent cuts as they appear, so each re-decide touches the
  o(n) open window only.
* ``scratch_growing``   — the same stream re-checked FROM SCRATCH at
  every chunk boundary (fresh memoised oracle per re-check: the cost a
  session-less serve tier would pay).  The headline ratio
  ``scratch_s / streamed_s`` is the incrementality measurement; the
  gate is streamed strictly cheaper (expected: orders of magnitude on
  1k events).
* ``resume_banked``     — the SAME stream replayed into a fresh session
  sharing the first run's verdict cache: every cut must resume from the
  decided-prefix bank (``prefix_hits == advances``, zero engine folds)
  — the node-restart path priced.
* ``flip_latency``      — a served session (CheckServer ``session.*``
  ops) fed a stream with a seeded mid-stream violation; measures
  append→flip-response wall clock (the flip carries the minimized
  repro, so this prices detection + shrink + certificate).
* ``parity_soak``       — streamed event-by-event verdicts vs the
  one-shot host ladder across register/cas/queue/kv racy corpora;
  ``wrong_verdicts`` MUST be 0 (the zero-wrong acceptance bar).

Output: resumable ``CellJournal`` committed as
``BENCH_MONITOR_<tag>.json`` (``make bench-monitor``; probe_watcher
archives it off-window beside the LINT/PCOMP/SHRINK/OBS artifacts and
``bench_report.py`` folds it into BENCH_REPORT.md).
"""

from __future__ import annotations

import argparse
import datetime
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

EVENTS = 1000          # the growing-history cell's stream length
CHUNK = 20             # events appended per decide
FLIP_REPS = 5
PARITY_N = 10          # histories per family in the parity soak
FAMILIES = ("register", "cas", "queue", "kv")


def _stream_rows(n_ops: int):
    """A mostly-sequential register stream with overlap bursts: long
    quiescent runs (the monitor's friendly case) interrupted by real
    concurrency every 8 ops so windows are exercised too."""
    rows = []
    t = 0
    for i in range(n_ops):
        val = (i % 3) + 1
        if i % 8 == 7:
            # one overlapping pair: two pids in flight at once
            rows.append([0, 1, val, 0, t, t + 3])
            rows.append([1, 0, 0, val, t + 1, t + 2])
            t += 4
        else:
            cmd = 1 if i % 2 == 0 else 0
            arg = val if cmd == 1 else 0
            resp = 0 if cmd == 1 else rows[-1][2] if rows else 0
            if cmd == 0:
                # read back the last written value (linearizable)
                last_w = next((r[2] for r in reversed(rows)
                               if r[1] == 1), 0)
                resp = last_w
            rows.append([0, cmd, arg, resp, t, t + 1])
            t += 2
    return rows


def _cell_streamed(spec, rows, bank) -> dict:
    from qsm_tpu.monitor import MonitorSession

    s = MonitorSession("bench", spec, bank=bank)
    t0 = time.perf_counter()
    for i in range(0, len(rows), CHUNK):
        s.append(rows[i:i + CHUNK])
        s.decide()
    v = s.close()
    dt = time.perf_counter() - t0
    c = s.counters()
    return {"seconds": round(dt, 4), "verdict": int(v),
            "events": c["events"], "advances": c["advances"],
            "prefix_hits": c["prefix_hits"],
            "window_checks": c["window_checks"],
            "decides": -(-len(rows) // CHUNK),
            "search": s_stats(c)}


def s_stats(c) -> dict:
    """The compact monitor record bench rows embed (SearchStats keys)."""
    from qsm_tpu.search.stats import SearchStats

    return SearchStats(engine="monitor", session_events=c["events"],
                       frontier_advances=c["advances"],
                       prefix_hits=c["prefix_hits"]).to_compact()


def _cell_scratch(spec, rows) -> dict:
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.utils.report import history_from_rows

    t0 = time.perf_counter()
    nodes = 0
    v = 1
    for i in range(CHUNK, len(rows) + CHUNK, CHUNK):
        oracle = WingGongCPU(memo=True)   # fresh: no cross-check memo
        h = history_from_rows(rows[:i])
        v = int(oracle.check_histories(spec, [h])[0])
        nodes += oracle.nodes_explored
    dt = time.perf_counter() - t0
    return {"seconds": round(dt, 4), "verdict": v,
            "nodes_explored": nodes,
            "rechecks": -(-len(rows) // CHUNK)}


def _cell_flip(workdir: str) -> dict:
    from qsm_tpu.serve.client import CheckClient, SessionHandle
    from qsm_tpu.serve.server import CheckServer

    lat = []
    shrunk = []
    for rep in range(FLIP_REPS):
        server = CheckServer(flush_s=0.005, max_lanes=16).start()
        try:
            client = CheckClient(f"127.0.0.1:{server.port}")
            h = SessionHandle(client, "register")
            # a clean prefix (writes of 1) as LIVE events — the
            # monitor cadence: a respond is final on arrival
            for _ in range(6):
                h.append([{"type": "invoke", "pid": 0, "cmd": 1,
                           "arg": 1},
                          {"type": "respond", "pid": 0, "resp": 0}])
            t0 = time.perf_counter()
            out = h.append([{"type": "invoke", "pid": 1, "cmd": 0,
                             "arg": 0},
                            {"type": "respond", "pid": 1,
                             "resp": 2}])  # reads unwritten 2
            dt = time.perf_counter() - t0
            assert out.get("flip"), out
            lat.append(dt)
            shrunk.append(out["flip"]["final_ops"])
            h.close()
            client.close()
        finally:
            server.stop()
    lat.sort()
    return {"reps": FLIP_REPS,
            "flip_latency_p50_ms": round(lat[len(lat) // 2] * 1e3, 2),
            "flip_latency_max_ms": round(lat[-1] * 1e3, 2),
            "repro_final_ops": shrunk}


def _cell_parity() -> dict:
    from qsm_tpu.core.spec import projection_report
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.monitor import MonitorSession
    from qsm_tpu.resilience.failover import host_fallback
    from qsm_tpu.serve.protocol import history_to_rows
    from qsm_tpu.utils.corpus import build_corpus

    wrong = 0
    checked = 0
    per_family = {}
    for fam in FAMILIES:
        entry = MODELS[fam]
        spec = entry.make_spec()
        hists = build_corpus(
            spec, (entry.impls["atomic"], entry.impls["racy"]),
            n=PARITY_N, n_pids=3, max_ops=10,
            seed_prefix=f"bench_mon_{fam}")
        ladder = host_fallback(spec)
        want = [int(v) for v in ladder.check_histories(spec, hists)]
        proj = None
        if not projection_report(spec):
            p = spec.projected_spec()
            if p.name in MODELS:
                proj = p
        fam_wrong = 0
        for k, h in enumerate(hists):
            s = MonitorSession(f"par{k}", spec, proj_spec=proj)
            for row in history_to_rows(h):
                s.append([row])
                s.decide()
            got = s.close()
            checked += 1
            if got != want[k]:
                fam_wrong += 1
        wrong += fam_wrong
        per_family[fam] = {"histories": len(hists), "wrong": fam_wrong,
                           "per_key": proj is not None}
    return {"histories": checked, "wrong_verdicts": wrong,
            "families": per_family}


def run(tag: str, out_path, resume: bool) -> dict:
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.resilience.checkpoint import CellJournal
    from qsm_tpu.serve.cache import VerdictCache

    path = out_path or os.path.join(REPO, f"BENCH_MONITOR_{tag}.json")
    header = {
        "artifact": "BENCH_MONITOR",
        "device_fallback": None,   # host-only bench: no window involved
        "platform": "cpu",
        "events": EVENTS, "chunk": CHUNK,
        "flip_reps": FLIP_REPS, "parity_n": PARITY_N,
        "families": list(FAMILIES),
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)
    spec = MODELS["register"].make_spec()
    rows = _stream_rows(EVENTS // 2)   # 2 events (inv+resp) per op
    bank = VerdictCache(max_entries=65_536)

    streamed = journal.complete("streamed_growing")
    resume_row = journal.complete("resume_banked")
    if streamed is None or resume_row is None:
        # the two cells share one bank: resume must replay THIS run
        streamed = journal.emit("streamed_growing",
                                _cell_streamed(spec, rows, bank))
        resume_row = journal.emit("resume_banked",
                                  _cell_streamed(spec, rows, bank))
    scratch = journal.complete("scratch_growing")
    if scratch is None:
        scratch = journal.emit("scratch_growing",
                               _cell_scratch(spec, rows))
    flip = journal.complete("flip_latency")
    if flip is None:
        flip = journal.emit("flip_latency", _cell_flip(""))
    parity = journal.complete("parity_soak")
    if parity is None:
        parity = journal.emit("parity_soak", _cell_parity())

    ratio = (scratch["seconds"] / streamed["seconds"]
             if streamed["seconds"] else float("inf"))
    summary = {
        "events": EVENTS,
        "streamed_s": streamed["seconds"],
        "scratch_s": scratch["seconds"],
        "scratch_over_streamed": round(ratio, 1),
        "resume_prefix_hits": resume_row["prefix_hits"],
        "resume_advances": resume_row["advances"],
        "resume_all_banked": (resume_row["prefix_hits"]
                              == resume_row["advances"]
                              and resume_row["advances"] > 0),
        "flip_latency_p50_ms": flip["flip_latency_p50_ms"],
        "wrong_verdicts": parity["wrong_verdicts"],
        # the gates: streamed strictly cheaper than scratch on the
        # growing history, every resumed cut a bank hit, zero wrong
        "gate_ok": (ratio > 2.0
                    and resume_row["prefix_hits"]
                    == resume_row["advances"]
                    and parity["wrong_verdicts"] == 0),
    }
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tag", default="r14")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="skip cells already banked in a compatible "
                         "prior artifact (CellJournal rails)")
    args = ap.parse_args(argv)
    summary = run(args.tag, args.out, args.resume)
    print(summary)
    return 0 if summary["gate_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
