"""Search-efficiency bench — iterations-per-history on the CAS-32 corpus.

The round-5 windows priced the kernel's node-work multiplier: ~182k
lockstep iterations per history on the banked device headline while the
memoised host oracle decided the same corpus exploring 10²–10³ nodes.
That multiplier is SEARCH (order, memo coverage, decomposition), not step
throughput, and it is hardware-independent — so this tool measures it on
the CPU platform, no window required, engine by engine:

* ``oracle`` / ``memo``   — host checkers' nodes/history (the denominator
  the device's iters/history is judged against);
* ``hand``                — ``JaxTPU`` exactly as every round ran it
  (hand-tuned chunk schedule, coarse buckets, TPU-safe-region memo caps);
* ``planned_kernel``      — the same kernel steered by ``plan_search``
  (fine buckets, full-size memo tables, geometric schedule), ordering
  and decomposition OFF: the driver-policy win alone;
* ``planned_full``        — ``build_backend``'s planned checker with
  postcondition-aware ordering and quiescent-cut decomposition on.

Every row carries the engine's full ``SearchStats`` and its verdict
parity against the memoised oracle (the verdict contract: a plan changes
iteration counts ONLY).  Output: one slim JSON line to stdout, the full
document to ``BENCH_SEARCH_<tag>.json`` next to bench.py.  The committed
artifact is the regression anchor for the ≥10× iters-per-history
acceptance gate (tests/test_search.py pins the live ratio).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_PIDS = 8
N_OPS = 32


def run(n_corpus: int, tag: str, out_path: str | None) -> dict:
    from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.search import plan_search, profile_corpus
    from qsm_tpu.search.planner import build_backend
    from qsm_tpu.utils.corpus import build_corpus

    spec = CasSpec()
    corpus = build_corpus(spec, (AtomicCasSUT, RacyCasSUT), n=n_corpus,
                          n_pids=N_PIDS, max_ops=N_OPS, seed_base=1000,
                          seed_prefix="bench")
    profile = profile_corpus(corpus, spec)
    plan = plan_search(spec, profile, platform="cpu")

    rows = []
    memo_verdicts = None

    def measure(name, backend):
        nonlocal memo_verdicts
        t0 = time.perf_counter()
        v = np.asarray(backend.check_histories(spec, corpus))
        dt = time.perf_counter() - t0
        st = backend.search_stats()
        wrong = None
        if memo_verdicts is not None:
            both = (memo_verdicts != 2) & (v != 2)
            wrong = int(np.sum(both & (memo_verdicts != v)))
        row = {
            "engine": name,
            "histories": len(corpus),
            "seconds": round(dt, 2),
            "undecided": int((v == 2).sum()),
            "wrong_vs_memo": wrong,
            "iters_per_history": round(st.iters_per_history, 1),
            "nodes_per_history": round(st.nodes_per_history, 1),
            "search": st.to_dict(),
        }
        rows.append(row)
        return v

    # host denominators first (memo also pins the parity reference)
    memo = WingGongCPU(memo=True)
    memo_verdicts = measure("memo", memo)
    # the naive reference walks the same corpus un-memoised; CAS-32 stays
    # tractable (the bench headline timeboxes it — here the whole corpus
    # is the point, nodes/history must cover every verdict)
    measure("oracle", WingGongCPU(node_budget=20_000_000))

    measure("hand", JaxTPU(spec))

    kernel_plan = plan_search(spec, profile, platform="cpu")
    # driver policy alone: strip the two search modes off the plan
    import dataclasses

    kernel_only = dataclasses.replace(kernel_plan, ordering=False,
                                      decompose=False,
                                      name=kernel_plan.name + "-kernel")
    measure("planned_kernel", JaxTPU(spec, plan=kernel_only))

    measure("planned_full", build_backend(spec, plan))

    by = {r["engine"]: r for r in rows}
    ratio = (by["hand"]["iters_per_history"]
             / max(by["planned_full"]["iters_per_history"], 1e-9))
    doc = {
        "metric": f"iters_per_history_cas_{N_OPS}ops_x_{N_PIDS}pids",
        "value": by["planned_full"]["iters_per_history"],
        "unit": "lockstep iters/history",
        "hand_iters_per_history": by["hand"]["iters_per_history"],
        "reduction_vs_hand": round(ratio, 1),
        "memo_oracle_nodes_per_history": by["memo"]["nodes_per_history"],
        "oracle_nodes_per_history": by["oracle"]["nodes_per_history"],
        "corpus": {"n": len(corpus), "pids": N_PIDS, "ops": N_OPS,
                   "mean_segments": round(profile.mean_segments, 2)},
        "plan": plan.describe(),
        "platform": "cpu",
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "rows": rows,
    }
    path = out_path or os.path.join(REPO, f"BENCH_SEARCH_{tag}.json")
    from qsm_tpu.resilience.checkpoint import atomic_write_json

    atomic_write_json(path, doc, indent=1)
    slim = {k: doc[k] for k in
            ("metric", "value", "unit", "hand_iters_per_history",
             "reduction_vs_hand", "memo_oracle_nodes_per_history",
             "oracle_nodes_per_history")}
    slim["wrong_verdicts"] = sum(r["wrong_vs_memo"] or 0 for r in rows)
    slim["artifact"] = os.path.basename(path)
    print(json.dumps(slim))
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--corpus", type=int, default=128)
    ap.add_argument("--tag", default="r06")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform()
    try:
        run(args.corpus, args.tag, args.out)
    except Exception as e:  # noqa: BLE001 — diagnostic line, not a traceback
        print(json.dumps({"metric": "iters_per_history", "value": 0,
                          "error": f"{type(e).__name__}: {e}"}))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
