"""Round-long TPU probe watcher (VERDICT.md round 2, "Next round" #1).

The chip tunnel has been wedged at bench time in every prior round, but it
HEALS IN WINDOWS: round 3's first probe found a live ``TPU v5 lite0`` that
was gone again 11 minutes later.  Logging probes is therefore not enough —
the watcher must *seize* a window the moment one opens:

* probe the default backend from a bounded subprocess every ``--interval``
  seconds (default 180 s: the one observed window was shorter than the old
  600 s interval), appending one JSON line per attempt to
  ``probe_log.jsonl``;
* on a successful device probe, immediately run ``python bench.py``
  (itself probe-guarded and hang-proof) in a bounded subprocess and — if it
  really ran on the device — save its JSON line to
  ``BENCH_TPU_WINDOW.json``.  ``bench.py`` uses that cached artifact as the
  round's headline when the tunnel is wedged again at bench time, with full
  provenance in ``extras``.

Every attempt (probe or window bench) is one JSON line in the log, so the
round's BENCH artifact reflects the best probe of the round, not one
instant.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, "/root/repo")

from qsm_tpu.resilience.checkpoint import (atomic_write_json,  # noqa: E402
                                           atomic_write_text)
from qsm_tpu.resilience.faults import InjectedFault, inject  # noqa: E402
from qsm_tpu.resilience.policy import preset  # noqa: E402
from qsm_tpu.utils.device import probe_default_backend  # noqa: E402

REPO = "/root/repo"
LOG = os.path.join(REPO, "probe_log.jsonl")
WINDOW_ARTIFACT = os.path.join(REPO, "BENCH_TPU_WINDOW.json")

# The persistent device-work queue a serve node banks into (serve
# --devq-dir; docs/WINDOWS.md).  None -> REPO/devq, resolved lazily so
# the tests' sandboxed REPO is honored; QSM_DEVQ_DIR overrides both.
DEVQ_DIR: str | None = None

# Round-stamped COMMITTED twins of the gitignored runtime artifacts
# (VERDICT.md round 3, "Next round" #1: a caught window must leave
# committed evidence — the driver commits any uncommitted files at round
# end, so writing these non-ignored paths is sufficient even if no human
# is watching when the window opens).
ROUND_TAG = "r05"

# Full-matrix measured-row counts for the resumable window tools: e2e is
# memo(2 suts) + device(2 suts x 2 trial_batches) + hybrid(ditto) — the
# optional cpp rows are host-measurable off-window and not gated on;
# configs is one row per registry model family.  Completeness gates and
# _run_tool min_rows both use these so a promoted PARTIAL never
# suppresses the resumable re-run that finishes the scan.
E2E_MIN_ROWS = 10
CONFIGS_MIN_ROWS = 7

COMMITTED_COPIES = {
    WINDOW_ARTIFACT: os.path.join(REPO, f"BENCH_TPU_{ROUND_TAG}.json"),
    os.path.join(REPO, "BENCH_CONFIGS_TPU_WINDOW.json"):
        os.path.join(REPO, f"BENCH_CONFIGS_TPU_{ROUND_TAG}.json"),
    os.path.join(REPO, "BENCH_E2E_TPU_WINDOW.json"):
        os.path.join(REPO, f"BENCH_E2E_TPU_{ROUND_TAG}.json"),
    os.path.join(REPO, "BENCH_SCALE_TPU_WINDOW.json"):
        os.path.join(REPO, f"BENCH_SCALE_TPU_{ROUND_TAG}.json"),
}

# Every banked headline ALSO appends here (committed, never overwritten):
# run-to-run variance across windows stays visible without digging
# through git history (ADVICE.md round 4).
CAPTURES_LOG = os.path.join(REPO, f"BENCH_TPU_CAPTURES_{ROUND_TAG}.jsonl")

# Committed archive of the pre-seize static-analysis findings (the lint
# gate below); one JSON document, refreshed whenever the gate runs.
# The lint artifact tracks the ANALYZER round (r07 added the family-g
# interprocedural race analyzer), independent of the window artifacts'
# ROUND_TAG — renaming those retires banked measurements, renaming this
# just says which rule set produced the findings.
LINT_ROUND = "r20"  # family (o): device-work-queue discipline — r20
LINT_ARTIFACT = os.path.join(REPO, f"LINT_{LINT_ROUND}.json")

# --- off-window archive registry ------------------------------------
# Every HOST-ONLY gate artifact is ONE declarative row here — script,
# round-stamped filename, full-scan row floor, log event, time bound —
# replacing the seven hand-cloned constant blocks + ``_maybe_archive_*``
# wrappers that each prior plane pasted in (and that drifted: adding a
# plane meant editing three places).  Each runs once per watcher
# process, on CellJournal --resume rails, entirely off-window: device
# probing is untouched (host work; the tunnel's state is irrelevant).
# Round tags are per-plane — each tracks the round its bench semantics
# last changed, decoupled from the window artifacts' ROUND_TAG.
class ArchiveGate:
    """One host-only committed bench artifact the watcher keeps banked."""

    def __init__(self, key: str, script: str, round_tag: str,
                 min_rows: int, event: str, timeout: float, doc: str):
        self.key = key
        self.script = script          # under tools/, CellJournal rails
        self.round_tag = round_tag
        self.min_rows = min_rows      # full-scan measured-row floor
        self.event = event            # probe_log event name
        self.timeout = timeout
        self.doc = doc
        self.attempted = False        # once per watcher process

    @property
    def artifact(self) -> str:
        # lazy: REPO is monkeypatched into a sandbox by the tests
        stem = ("BENCH_SESSIONS" if self.key == "sessions"
                else f"BENCH_{self.key.upper()}")
        return os.path.join(REPO, f"{stem}_{self.round_tag}.json")


ARCHIVE_GATES = [
    ArchiveGate("pcomp", "bench_pcomp.py", "r09", 8, "pcomp_bench",
                1800.0, "P-compositionality: kv long-history corpora, "
                "decomp vs whole on the cpp→memo ladder"),
    ArchiveGate("shrink", "bench_shrink.py", "r10", 6, "shrink_bench",
                1800.0, "batched shrink: frontier-at-once vs "
                "one-at-a-time on racy kv/cas failing corpora"),
    ArchiveGate("obs", "bench_obs.py", "r15", 7, "obs_bench", 900.0,
                "obs overhead: serve path with obs absent / tracing "
                "off / tracing on + fleet collection/federation"),
    ArchiveGate("fleet", "bench_fleet.py", "r13", 11, "fleet_bench",
                1200.0, "fleet soak: 1/2/3-node scaling + kill/wedge/"
                "partition/rolling-restart chaos + router-HA/gossip"),
    ArchiveGate("monitor", "bench_monitor.py", "r14", 6,
                "monitor_bench", 900.0, "monitor: streamed vs scratch, "
                "bank resume, flip-to-push, streamed-vs-oneshot parity"),
    ArchiveGate("gen", "bench_gen.py", "r17", 9, "gen_bench", 900.0,
                "generation: steered vs unsteered at matched budget, "
                "flip/witness audit, closed-loop soak"),
    ArchiveGate("sessions", "soak_sessions.py", "r18", 2,
                "sessions_soak", 1500.0, "durable-session chaos soak: "
                "≥1000 sessions through restarts/takeover/handoff"),
    ArchiveGate("mesh", "bench_mesh.py", "r19", 9, "mesh_bench",
                2700.0, "mesh dispatch: lanes/sec-by-width curve, "
                "cross-width parity, decided fleet-scaling gate"),
    # window arbitrage (r20): simulated 8-device window drains a banked
    # four-plane queue — zero wrong verdicts vs the host ladder, exactly-
    # once kill/resume, utilization ≥ the SLO floor.  Full scan = bank +
    # drain + kill_resume + host_baseline + fleet + summary.
    ArchiveGate("devq", "bench_devq.py", "r20", 6, "devq_bench",
                1200.0, "device-work queue: banked planes drained in a "
                "simulated window, oracle-proved, exactly-once resume"),
]

# Cached verdict of the pre-seize lint gate, keyed on a SOURCE
# fingerprint — not process lifetime: the watcher runs all round while
# the builder edits the very specs/kernels the analysis covers, so a
# cached refusal must clear when the defect is fixed (or every later
# window is wasted on a stale verdict) and a cached pass must expire
# when a defect lands.  main() warms it BEFORE the probe loop; a
# mid-round source change re-runs the ~30 s analysis inside the next
# seize — the correct trade for a fresh verdict.
_LINT_STATE: dict = {}


def _lint_fingerprint() -> str:
    """Cheap staleness key: newest mtime + file count over every input
    the analysis reads — the package sources AND the ``.qsmlint``
    whitelist (accepting a finding by whitelisting it touches only the
    whitelist, and must clear a cached refusal just like a code fix).
    Uncommitted edits count — git state would not."""
    latest, count = 0.0, 0
    # PROTOCOL.json is a lint INPUT too: family (l)'s drift check
    # compares the committed contract against a fresh extraction, so
    # regenerating it must clear a cached drift refusal
    paths = [os.path.join(REPO, ".qsmlint"),
             os.path.join(REPO, "bench.py"),
             os.path.join(REPO, "PROTOCOL.json")]
    # tools/ is part of the scanned corpus too (families d–g read the
    # bench drivers and this watcher): edits there must re-lint
    for sub in ("qsm_tpu", "tools"):
        for dirpath, _dirs, files in os.walk(os.path.join(REPO, sub)):
            paths.extend(os.path.join(dirpath, f) for f in files
                         if f.endswith(".py"))
    for p in paths:
        try:
            latest = max(latest, os.path.getmtime(p))
            count += 1
        except OSError:
            pass
    return f"{count}:{latest}"


def _preflight_lint(timeout_s: float = 420.0) -> bool:
    """The window-seize gate: run ``python -m qsm_tpu lint`` (CPU-pinned
    by the lint command itself — it can never touch the tunnel) and
    refuse to spend a healing window when the analyzer finds
    non-whitelisted error-severity defects (a spec whose step_jax
    diverges from the oracle, a retracing kernel, a VMEM-blowing table
    spec ... would burn the window on statically-knowable failures).

    Verdict semantics: rc 0 -> seize allowed; rc 1 (real findings) ->
    seize REFUSED; any other failure (timeout, crash, missing module)
    -> allowed with a logged warning — analyzer trouble must not cost
    the round its windows.  Cached per source fingerprint (see
    ``_LINT_STATE``)."""
    key = _lint_fingerprint()
    if _LINT_STATE.get("key") == key:
        return _LINT_STATE["ok"]
    t0 = time.time()
    cache = True
    try:
        r = subprocess.run(
            [sys.executable, "-m", "qsm_tpu", "lint", "--json",
             "--out", LINT_ARTIFACT],
            capture_output=True, text=True, timeout=timeout_s, cwd=REPO)
        ok = r.returncode != 1
        detail = ("clean" if r.returncode == 0 else
                  "error findings; seize refused" if r.returncode == 1
                  else f"lint rc {r.returncode}; waved through: "
                       + (r.stderr or r.stdout)[-200:])
    except subprocess.TimeoutExpired:
        # TRANSIENT trouble (a pegged machine, the very condition the
        # watcher runs under) is waved through but NOT cached: caching
        # ok=True under the fingerprint would silently disarm the gate
        # for these sources for the rest of the round
        ok, detail, cache = True, \
            f"lint exceeded {timeout_s:.0f}s; waved through", False
    except OSError as e:
        ok, detail, cache = True, \
            f"lint failed to launch ({e!r}); waved through", False
    if cache:
        _LINT_STATE["key"] = key
        _LINT_STATE["ok"] = ok
    _log(event="window_lint", ok=ok,
         seconds=round(time.time() - t0, 1), detail=detail)
    return ok


def _bank_committed_copy(runtime_path: str) -> None:
    dst = COMMITTED_COPIES.get(runtime_path)
    if dst is None:
        return
    try:
        with open(runtime_path) as f:
            data = f.read()
        # tmp+rename: the committed twin is what the round's evidence
        # rests on — a watcher killed mid-copy must not truncate it
        atomic_write_text(dst, data)
    except OSError:
        pass  # the runtime artifact still exists; copy is best-effort


def _log(**rec) -> None:
    rec.setdefault("ts", round(time.time(), 1))
    rec.setdefault("iso", datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds"))
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


# probe_log.jsonl is append-only and was at 717 rows (9 device hits) by
# round 6 — almost all of it the same wedged-tunnel line.  Past this
# many rows the watcher compacts it via tools/soak_prune.py
# --compact-probe-log (atomic; keeps every device-hit row, every event
# row, and the last N failures for cadence context).
PROBE_LOG_COMPACT_ROWS = 2000
PROBE_LOG_KEEP_FAILURES = 500
# size precheck so the steady loop never line-counts a small log
_PROBE_LOG_SIZE_FLOOR = 64 * 1024


def _maybe_compact_probe_log() -> None:
    try:
        if os.path.getsize(LOG) < _PROBE_LOG_SIZE_FLOOR:
            return
        with open(LOG) as f:
            rows = sum(1 for ln in f if ln.strip())
    except OSError:
        return
    if rows <= PROBE_LOG_COMPACT_ROWS:
        return
    # the compactor lives next to this file — resolve by module
    # location, not REPO (tests sandbox REPO into a tmp dir)
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "soak_prune.py")
    try:
        r = subprocess.run(
            [sys.executable, script, "--compact-probe-log", LOG,
             "--keep-failures", str(PROBE_LOG_KEEP_FAILURES)],
            capture_output=True, text=True, timeout=120.0)
        detail = (r.stdout or r.stderr or "").strip()[-200:]
        _log(event="probe_log_compact", ok=r.returncode == 0,
             rows_before=rows, detail=detail)
    except (subprocess.TimeoutExpired, OSError) as e:
        _log(event="probe_log_compact", ok=False,
             rows_before=rows, detail=f"{type(e).__name__}: {e}")


def _maybe_archive(gate: ArchiveGate) -> None:
    """Off-window: (re)bank one registered host-only CellJournal bench
    artifact when it is missing or incomplete.  Once per watcher
    process (the benches are minutes of host CPU), and --resume means
    a partial from a killed attempt is finished, not re-paid."""
    if gate.attempted:
        return
    gate.attempted = True
    artifact = gate.artifact
    if _tool_rows(artifact) >= gate.min_rows:
        _log(event=gate.event, ok=True, detail="already banked; kept")
        return
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          gate.script)
    try:
        r = subprocess.run(
            [sys.executable, script, "--out", artifact, "--resume"],
            capture_output=True, text=True, timeout=gate.timeout,
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"))
        detail = (r.stdout or r.stderr or "").strip()[-200:]
        _log(event=gate.event, ok=r.returncode == 0,
             rows=_tool_rows(artifact), detail=detail)
    except (subprocess.TimeoutExpired, OSError) as e:
        # the journal keeps every completed cell; the next watcher
        # process resumes from there
        _log(event=gate.event, ok=False, rows=_tool_rows(artifact),
             detail=f"{type(e).__name__}: {e}")


def _run_window_bench(bench_timeout: float, extra_args, label: str,
                      bank: bool = True) -> bool:
    """One bounded bench.py run; writes the artifact iff it really ran on
    the device AND ``bank`` is set (profiled runs pass bank=False: their
    timings include tracer overhead and must never become the headline).
    Returns True on a captured device line."""
    t0 = time.time()
    try:
        # probe bounds/retries by NAME: the seize-probe preset in
        # resilience/policy.py is the single source of the old
        # "--probe-timeout 60 --retries 4 --retry-interval 10" literals
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--probe-policy", "seize-probe", "--require-device",
             *extra_args],
            capture_output=True, text=True, timeout=bench_timeout, cwd=REPO)
    except subprocess.TimeoutExpired:
        _log(event=label, ok=False,
             detail=f"bench exceeded {bench_timeout:.0f}s (window closed "
                    "mid-run?)")
        return False
    line = (r.stdout or "").strip().splitlines()
    try:
        result = json.loads(line[-1]) if line else {}
    except ValueError:
        result = {}
    # diagnostic detail for the log: a --require-device abort (rc 3) has
    # no extras.device, but its "error" field says why the stage failed
    diag = (result.get("extras", {}).get("device")
            or result.get("error") or "") if result else (r.stderr or "")[-200:]
    # a cached-window ECHO is not a device run: when the spawned bench's
    # own probe finds the tunnel wedged it reprints the existing artifact
    # (rc 0, device_fallback None) — accepting that would refresh the
    # artifact's mtime/captured_iso forever and defeat every staleness
    # guard, so reject anything marked headline_from_cached_window
    on_device = (r.returncode == 0 and result
                 and result.get("extras", {}).get("device_fallback") is None
                 and not result.get("extras", {}).get(
                     "headline_from_cached_window")
                 and not result.get("error"))
    _log(event=label, ok=bool(on_device),
         rc=r.returncode, seconds=round(time.time() - t0, 1),
         detail=diag)
    if on_device and bank:
        result["captured_iso"] = datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds")
        atomic_write_json(WINDOW_ARTIFACT, result)
        _bank_committed_copy(WINDOW_ARTIFACT)
        try:  # per-capture history (ADVICE.md round 4): append, never clobber
            with open(CAPTURES_LOG, "a") as f:
                f.write(json.dumps(result) + "\n")
        except OSError:
            pass
    return bool(on_device)


def _scale_complete(path: str) -> bool:
    """Content-based completeness of the banked bench_scale artifact: a
    row-count gate went stale the moment the width ladder grew (round-4
    review), so require an answer (measured or error) for EVERY width of
    the CURRENT ladder plus the two diagnostic variants.  An error row
    (e.g. OOM at the widest) is a final answer; a 'skipped' marker is
    not."""
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench_scale_ladder",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "bench_scale.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        widths = set(mod.DEVICE_BATCHES)
    except Exception:  # noqa: BLE001 — no ladder, no completeness claim
        return False
    try:
        with open(path) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
    except (OSError, ValueError):
        return False
    if not lines or lines[0].get("device_fallback") is not None:
        return False
    have_widths = {r.get("batch") for r in lines[1:]
                   if "variant" not in r and "skipped" not in r}
    have_variants = {r.get("variant") for r in lines[1:]
                     if "variant" in r and "skipped" not in r}
    # pallas: the round-5 A/B cell (an error row IS an answer — the
    # prototype failing to compile on the real Mosaic stack decides the
    # escalation question too)
    return widths <= have_widths and {"unroll1", "budget2k",
                                      "pallas"} <= have_variants


def _tool_rows(path: str) -> int:
    """MEASURED non-header JSONL rows of a banked tool artifact (0 on any
    trouble).  Rows the tool marked ``skipped`` (time box cut) are not
    measurements — counting them would let a cut scan satisfy min_rows
    and suppress the re-run that finishes it."""
    n = 0
    try:
        with open(path) as f:
            for i, ln in enumerate(f):
                if not ln.strip():
                    continue
                if i == 0:
                    continue  # header
                try:
                    if "skipped" not in json.loads(ln):
                        n += 1
                except ValueError:
                    pass
    except OSError:
        return 0
    return n


def _run_tool(script: str, out_path: str, timeout: float, label: str,
              min_rows: int = 0, extra_args=(),
              resumable: bool = False) -> None:
    """Bank one auxiliary artifact (bench_configs / bench_e2e /
    bench_scale) from the open window.  Device-capture discipline mirrors
    _run_window_bench: a previously banked REAL-device artifact is never
    clobbered by a CPU-fallback run (the tool writes to a temp path,
    promoted only when its header shows no fallback), ``ok`` in the log
    means "device capture", and the window is re-probed first so a closed
    window costs one bounded probe instead of a full CPU-fallback
    workload.  ``min_rows``: a banked artifact with fewer data rows (a
    promoted partial from a closed window) does NOT suppress a re-run —
    the next window finishes the scan.  ``resumable``: seed the tool's
    temp output from the banked artifact and pass ``--resume`` so cells
    measured in an earlier window are NOT re-paid — the scan picks up at
    the first unbanked cell (resilience/checkpoint.py CellJournal); the
    monotonic more-rows-wins promotion below then holds trivially."""
    if os.path.exists(out_path) and _tool_rows(out_path) >= min_rows:
        _log(event=label, ok=True, detail="already banked; kept")
        return
    p = probe_default_backend(policy=preset("window-reprobe"))
    if not p.is_device:
        _log(event=label, ok=False, detail=f"window closed: {p.detail}")
        return
    t0 = time.time()
    tmp = f"{out_path}.{os.getpid()}.tmp"
    resume_args = ()
    if resumable and os.path.exists(out_path):
        try:
            with open(out_path) as f:
                atomic_write_text(tmp, f.read())
            resume_args = ("--resume",)
        except OSError:
            pass  # no seed: the tool starts the scan from cell 1
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", script),
             "--out", tmp, *resume_args, *extra_args],
            capture_output=True, text=True, timeout=timeout, cwd=REPO)
    except subprocess.TimeoutExpired:
        # tools that write incrementally (bench_scale) may have banked
        # usable rows before the window closed — promote a partial
        # device-headed artifact rather than discarding measurements
        partial = False
        try:
            with open(tmp) as f:
                partial = json.loads(
                    f.readline()).get("device_fallback") is None
        except (OSError, ValueError):
            pass
        # never clobber an earlier bank that holds MORE device rows —
        # progress must be monotonic across flickering windows
        if partial and _tool_rows(tmp) <= _tool_rows(out_path):
            partial = False
        if partial:
            os.replace(tmp, out_path)
            _bank_committed_copy(out_path)
        _log(event=label, ok=partial,
             detail=f"exceeded {timeout:.0f}s (window closed mid-run?)"
                    + ("; partial rows promoted" if partial else ""))
        return
    on_device = False
    try:
        with open(tmp) as f:
            header = json.loads(f.readline())
        on_device = header.get("device_fallback") is None
    except (OSError, ValueError):
        pass
    # monotonic here too, not just on timeout: a time-boxed rerun that
    # exits rc 0 with FEWER measured rows (cells cut to 'skipped'
    # markers by --time-box on a slow tunnel) must not clobber a richer
    # banked partial and its committed copy
    demoted = on_device and _tool_rows(tmp) < _tool_rows(out_path)
    if on_device and not demoted:
        os.replace(tmp, out_path)
        _bank_committed_copy(out_path)
    else:
        try:
            os.remove(tmp)
        except OSError:
            pass
    _log(event=label, ok=on_device and not demoted, rc=r.returncode,
         seconds=round(time.time() - t0, 1),
         **({"detail": "device run banked fewer rows than existing; "
                       "kept the richer bank"} if demoted else {}))


def _maybe_drain_devq(budget_s: float) -> None:
    """Window arbitrage (qsm_tpu/devq, docs/WINDOWS.md): spend part of
    the open window on the banked device-work queue.  Runs
    tools/window_drain.py in a bounded subprocess — it re-probes, builds
    the mesh from the probed device set, drains in score order with the
    window deadline threaded through, and commits the drain artifact
    beside the bench evidence.  A missing/empty queue costs one stat."""
    devq_dir = (os.environ.get("QSM_DEVQ_DIR") or DEVQ_DIR
                or os.path.join(REPO, "devq"))
    if not os.path.isdir(devq_dir):
        return  # no node ever banked here: nothing to say, even in the log
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "window_drain.py")
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, script, "--dir", devq_dir,
             "--window-s", str(max(30.0, budget_s * 0.9)),
             "--out", os.path.join(REPO, "DEVQ_DRAIN_WINDOW.json"),
             "--resume"],
            capture_output=True, text=True, timeout=budget_s, cwd=REPO)
        line = (r.stdout or "").strip().splitlines()
        try:
            rep = json.loads(line[-1]) if line else {}
        except ValueError:
            rep = {}
        _log(event="window_devq_drain", ok=r.returncode == 0,
             seconds=round(time.time() - t0, 1),
             drained=rep.get("drained"),
             utilization=rep.get("window_utilization"),
             detail=(r.stderr or "").strip()[-200:])
    except (subprocess.TimeoutExpired, OSError) as e:
        # the drain journals per item (CellJournal): a window that
        # closes mid-drain resumes exactly-once from the journal
        _log(event="window_devq_drain", ok=False,
             seconds=round(time.time() - t0, 1),
             detail=f"{type(e).__name__}: {e}")


def _headline_settings() -> dict:
    """(batch, unroll) the banked headline actually ran with, or {}."""
    try:
        with open(WINDOW_ARTIFACT) as f:
            ex = json.load(f).get("extras", {})
        return {"batch": ex.get("device_batch"), "unroll": ex.get("unroll")}
    except (OSError, ValueError):
        return {}


def _seize_window(bench_timeout: float) -> bool:
    """The tunnel just answered.  Round-5 order (VERDICT.md round 4,
    "Next round" #1): the window buys the DECISION first, not a third
    300-440 s headline — both round-4 windows spent themselves on the
    headline and died before the scan that decides how to make the
    headline fast.

      1. scale scan — unroll A/B + width ladder, time-boxed cells,
         incremental rows promoted even from a window that dies mid-cell;
      2. SHORT headline (1 timed rep; bench.py adopts the scan's batch
         AND unroll) — re-run whenever the banked headline's settings
         differ from what the scan decided;
      3. e2e (device/hybrid rows incl. the on-chip trial_batch A/B);
      4. one profiled run (never banked: tracer overhead);
      5. per-config matrix;
      6. the max-ops sweep LAST (longest by far; outlived round-4's
         48-min window)."""
    try:
        # fault site (resilience/faults.py): seize-abort paths are
        # tier-1 testable without a chip; no-op in production
        inject("seize")
    except InjectedFault as e:
        _log(event="window_seize", ok=False, detail=f"fault-injected: {e}")
        return False
    scale_path = os.path.join(REPO, "BENCH_SCALE_TPU_WINDOW.json")
    scale_done = _scale_complete(scale_path)

    def headline_state():
        """(fresh, settings_current) of the banked headline vs the scan."""
        try:
            age = time.time() - os.path.getmtime(WINDOW_ARTIFACT)
        except OSError:
            age = float("inf")
        adopted_batch = adopted_unroll = None
        try:
            from bench import best_scale_batch, best_scale_unroll
            a = best_scale_batch(dirpath=REPO)
            adopted_batch = a[0] if a else 4096
            u = best_scale_unroll(dirpath=REPO)
            adopted_unroll = u[0] if u else None
        except Exception:  # noqa: BLE001 — adoption is advisory
            pass
        cur = _headline_settings()
        current = (
            cur.get("batch") is not None
            and (adopted_batch is None
                 or cur.get("batch") == adopted_batch)
            and (adopted_unroll is None
                 or cur.get("unroll") == adopted_unroll))
        return age <= 3 * 3600.0, current

    # row-count completeness, NOT existence: a partial promoted from a
    # timed-out window must not suppress the resumable re-run that
    # finishes it (resume adopts banked cells, so convergence is cheap).
    # e2e full matrix = memo(2) + device(4) + hybrid(4) rows (the cpp
    # rows are host-measurable off-window and not gated on); configs =
    # one row per model family.
    e2e_done = _tool_rows(
        os.path.join(REPO, "BENCH_E2E_TPU_WINDOW.json")) >= E2E_MIN_ROWS
    configs_done = _tool_rows(
        os.path.join(REPO, "BENCH_CONFIGS_TPU_WINDOW.json")) \
        >= CONFIGS_MIN_ROWS
    # a profile directory is "captured" only once a completed trace file
    # exists inside it — jax.profiler creates the directory at trace
    # START, so a run killed mid-trace must not suppress retries
    profile_dir = os.path.join(REPO, "profiles", f"{ROUND_TAG}_tpu")
    profile_done = False
    for _root, _dirs, files in os.walk(profile_dir):
        if any(f.endswith(".xplane.pb") for f in files):
            profile_done = True
            break
    # the sweep is banked only when its artifact shows a real-device
    # capture; the filename tracks ROUND_TAG (a literal went stale on
    # round bumps) and a missing device_fallback key means NOT banked
    sweep_done = False
    try:
        with open(os.path.join(
                REPO, f"BENCH_SWEEP_{ROUND_TAG}.json")) as f:
            sweep_done = json.load(f).get(
                "device_fallback", "absent") is None
    except (OSError, ValueError):
        pass

    fresh, settings_current = headline_state()
    if (scale_done and fresh and settings_current and e2e_done
            and profile_done and configs_done and sweep_done):
        return True  # everything banked: a healthy tunnel cycle is silent

    # --- 0. the static-analysis gate (cached; main() warms it OFF-window
    # so a healthy run pays nothing here): statically-detectable defects
    # must never spend a healing window -----------------------------------
    if not _preflight_lint():
        return False

    # --- 1. the scale scan: the decision artifact ------------------------
    if scale_done:
        _log(event="window_scale", ok=True, detail="already banked; kept")
    else:
        # subprocess bound > --time-box so an in-flight cell may finish;
        # partial rows are promoted either way (incremental writes)
        _run_tool("bench_scale.py", scale_path, bench_timeout,
                  "window_scale", min_rows=1 << 30,
                  extra_args=("--time-box", "600"), resumable=True)
        fresh, settings_current = headline_state()  # scan may re-decide

    # --- 2. short headline at the adopted configuration ------------------
    if fresh and settings_current:
        _log(event="window_bench_headline", ok=True,
             detail="fresh capture, settings match the scan; kept")
        banked = True
    else:
        banked = _run_window_bench(bench_timeout / 4, ["--no-sweep"],
                                   "window_bench_headline")
    if not banked:
        return False
    # chase the upgrades only while the window is demonstrably open;
    # after a failed bank the flicker closed — a full sweep on the
    # CPU fallback would block probing for up to bench_timeout.
    # --- 2.5 window arbitrage: drain the banked device-work queue -------
    # (bounded; the demonstrably-open window pays for fleet-banked work
    # before the long sweep can eat the rest of it)
    _maybe_drain_devq(bench_timeout / 4)
    # --- 3. e2e: the on-chip trial_batch A/B -----------------------------
    if e2e_done:
        _log(event="window_e2e", ok=True, detail="already banked; kept")
    else:
        _run_tool("bench_e2e.py",
                  os.path.join(REPO, "BENCH_E2E_TPU_WINDOW.json"),
                  bench_timeout / 2, "window_e2e",
                  min_rows=E2E_MIN_ROWS, resumable=True)
    # --- 4. a PROFILED run, never banked (tracer overhead must not
    # deflate the headline artifact) — the first real-TPU trace ----------
    if profile_done:
        _log(event="window_profile", ok=True, detail="already captured")
    else:
        _run_window_bench(bench_timeout / 4,
                          ["--no-sweep", "--profile", profile_dir],
                          "window_profile", bank=False)
    # --- 5. per-config matrix -------------------------------------------
    _run_tool("bench_configs.py",
              os.path.join(REPO, "BENCH_CONFIGS_TPU_WINDOW.json"),
              bench_timeout, "window_configs",
              min_rows=CONFIGS_MIN_ROWS, resumable=True)
    # --- 6. the max-ops sweep: longest by far, strictly last ------------
    if sweep_done:
        _log(event="window_bench_full", ok=True,
             detail="device sweep already banked; kept")
    else:
        _run_window_bench(bench_timeout, [], "window_bench_full")
    return banked


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=180.0)
    ap.add_argument("--timeout", type=float, default=None,
                    help="override the watcher-probe preset's per-probe "
                         "bound (resilience/policy.py)")
    ap.add_argument("--bench-timeout", type=float, default=1800.0)
    ap.add_argument("--once", action="store_true")
    ap.add_argument("--no-bench", action="store_true",
                    help="log probes only; never launch the window bench")
    args = ap.parse_args()
    if not args.no_bench:
        # warm the lint gate BEFORE the probe loop: the analysis runs on
        # the CPU while the tunnel is (typically) wedged anyway, so a
        # later healed window is never spent on it
        _preflight_lint()
        # same logic for every registered host-only gate artifact:
        # bank them off-window so no healed window ever waits behind
        # them (ARCHIVE_GATES — one declarative row per plane)
        for gate in ARCHIVE_GATES:
            _maybe_archive(gate)
    while True:
        t0 = time.time()
        _maybe_compact_probe_log()  # bounded; no-op below the threshold
        p = probe_default_backend(args.timeout,
                                  policy=preset("watcher-probe"))
        _log(ok=p.ok, is_device=p.is_device, platform=p.platform,
             detail=p.detail[:300])
        if p.is_device and not args.no_bench:
            # freshness of the headline is judged inside _seize_window so
            # a banked headline never suppresses the still-missing
            # configs/e2e/profile/sweep upgrades
            _seize_window(args.bench_timeout)
        if args.once:
            return 0 if p.is_device else 1
        time.sleep(max(1.0, args.interval - (time.time() - t0)))


if __name__ == "__main__":
    sys.exit(main())
