"""Round-long TPU probe watcher (VERDICT.md round 2, "Next round" #1).

The chip tunnel has been wedged at bench time in both prior rounds; a single
probe at the end of a round forfeits any healing window.  This watcher runs in
the background for the whole round, probing the default backend from a bounded
subprocess every ``--interval`` seconds and appending one JSON line per
attempt to ``probe_log.jsonl``:

    {"ts": <unix>, "iso": "...", "ok": bool, "platform": "...", "detail": "..."}

``bench.py`` reads this log at bench time and reports every attempt in
``extras.probe_attempts`` so the round's BENCH artifact reflects the *best*
probe of the round, not one instant.
"""

from __future__ import annotations

import argparse
import datetime
import json
import sys
import time

sys.path.insert(0, "/root/repo")

from qsm_tpu.utils.device import probe_default_backend  # noqa: E402

LOG = "/root/repo/probe_log.jsonl"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600.0)
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args()
    while True:
        t0 = time.time()
        p = probe_default_backend(args.timeout)
        rec = {
            "ts": round(t0, 1),
            "iso": datetime.datetime.now(datetime.timezone.utc).isoformat(
                timespec="seconds"),
            "ok": p.ok,
            "is_device": p.is_device,
            "platform": p.platform,
            "detail": p.detail[:300],
        }
        with open(LOG, "a") as f:
            f.write(json.dumps(rec) + "\n")
        if args.once:
            return 0 if p.is_device else 1
        time.sleep(max(1.0, args.interval - (time.time() - t0)))


if __name__ == "__main__":
    sys.exit(main())
