"""Batch-width / unroll scaling artifact — the DECISION measurement the
round-5 seize pipeline banks FIRST (VERDICT.md round 4, "Next round" #1).

BENCH_TPU_r04.json (the round-4 banked windows) left two open questions
the headline alone cannot answer:

* does UNROLL=8 help or hurt on the real chip?  (The only post-unroll
  on-chip datapoint moved the WRONG way: 105.6 → 61.6 h/s across the
  unroll landing, with host denominators also shifting ~3×, so the
  regression is unattributed.)
* is per-trip cost flat in lane width?  (If yes, throughput scales with
  batch until HBM binds and vs_best_host ≥ 1 is reachable; if no, the
  flagship formally pivots to the hybrid backend.)

Cell order is therefore DECISION-first, so a window that closes after
any prefix still decides something:

  1. unroll8 @ 4096  — the exact headline configuration (control row);
  2. unroll1 @ 4096  — the unroll A/B at matched width;
  3. unroll8 @ 16384 / 65536 / 262144 — the width ladder;
  4. budget2k / oneshot diagnostics at the best width.

Rows are written incrementally (header first, then one JSON line per
cell as it lands) and every row stamps the kernel settings it ran with
(unroll, chunk schedule, budget, MAX_BATCH) so the artifact is
self-describing across kernel changes.

bench.py reads the best zero-wrong-verdict row of a DEVICE-captured copy
of this artifact and adopts its batch (and unroll, when the unroll1
control beats the unroll8 control) for the headline; the watcher
(tools/probe_watcher.py) banks it during a window BEFORE the headline.

Probe-guarded exactly like bench.py.  Usage:

    python tools/bench_scale.py [--force-cpu] [--out BENCH_SCALE_rN.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

# CPU-fallback rows use a reduced width ladder: the vmapped while-loop is
# orders of magnitude slower on host, and the point of a fallback run is
# pipeline validation, not measurement.
# 262144 runs cache-off (slots=0) in its initial bucket; survivors
# compact into cached buckets.  Compile-validated at width on the
# CPU backend (6.5 s, ~0.9 GB device footprint -- nowhere near HBM).
DEVICE_BATCHES = (4096, 16384, 65536, 262144)
CPU_BATCHES = (256, 1024)
TIME_BOX_S = 900.0  # stop starting new rows beyond this much measuring

# Width of the unroll A/B cells.  Both controls run at the SAME width so
# the comparison isolates the unroll knob (the round-4 windows confounded
# unroll with everything else that moved between captures).
CONTROL_BATCH = 4096
CPU_CONTROL_BATCH = 256


def run_scale(on_tpu: bool, out_path: str, header: dict,
              time_box_s: float = TIME_BOX_S, resume: bool = False) -> list:
    from bench import build_corpus
    from qsm_tpu.models import CasSpec
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.resilience.checkpoint import CellJournal
    from qsm_tpu.utils.device import compile_cache_entries

    spec = CasSpec()
    n_unique = 512 if on_tpu else 128
    corpus = build_corpus(spec, n_unique)
    memo = WingGongCPU(memo=True)
    memo_verdicts = np.asarray(memo.check_histories(spec, corpus))

    # native host rate on this corpus — the denominator for the derived
    # hybrid number (device majority + cpp tail) the budget2k variant
    # enables
    cpp_rate = None
    try:
        from qsm_tpu.native import CppOracle, native_available
        if native_available():
            cpp = CppOracle(spec)
            cpp.check_histories(spec, corpus)  # build + table compile
            t0 = time.perf_counter()
            cpp.check_histories(spec, corpus)
            if cpp.native_histories > 0:
                cpp_rate = round(
                    len(corpus) / (time.perf_counter() - t0), 1)
    except Exception:  # noqa: BLE001 — optional fast path
        pass

    # Per-cell journal (resilience/checkpoint.py): every row lands
    # atomically (tmp+rename) the moment its cell finishes, and --resume
    # preloads cells a killed/timed-out earlier run already measured —
    # a window that closes after cell 2 of 6 banks 2 cells and the next
    # window starts at cell 3.  The header's resumed_cells count keeps
    # the artifact honest about what was inherited vs re-measured.
    journal = CellJournal(out_path, {
        "artifact": "bench_scale", "corpus_unique": len(corpus),
        "cpp_rate_h_per_s": cpp_rate,
        "compile_cache_entries_at_start": compile_cache_entries(),
        **header}, resume=resume)

    def _timed_cell(row, batch, make_backend, counters):
        """The shared cell scaffold: tile the corpus to ``batch`` lanes,
        warm (compile) with cache-entry stamps, zero the per-run
        ``counters`` (row_key -> backend attr), run ONE timed pass, and
        score rate/undecided/wrong against the tiled memo verdicts.  One
        definition so the pallas A/B rows stay comparable with the XLA
        rows they exist to be compared against (any change to the rate
        or wrong-verdict math lands in every cell)."""
        reps = (batch + len(corpus) - 1) // len(corpus)
        device_corpus = (corpus * reps)[:batch]
        tiled_memo = np.tile(memo_verdicts, reps)[:batch]
        try:
            backend = make_backend()
            row.setdefault("settings", {})["cache_entries_before"] = \
                compile_cache_entries()
            t0 = time.perf_counter()
            backend.check_histories(spec, device_corpus)  # compile + warm
            row["warm_s"] = round(time.perf_counter() - t0, 2)
            row["settings"]["cache_entries_after"] = \
                compile_cache_entries()
            # zero EVERY per-run counter the row reports, or the stats
            # mix the warm pass with the timed pass
            for attr in counters.values():
                setattr(backend, attr, type(getattr(backend, attr))(0))
            t0 = time.perf_counter()
            verdicts = np.asarray(
                backend.check_histories(spec, device_corpus))
            wall = time.perf_counter() - t0
            undecided = int(np.sum(verdicts == 2))
            both = (verdicts != 2) & (tiled_memo != 2)
            row.update({
                "wall_s": round(wall, 3),
                "rate_h_per_s": round((batch - undecided) / wall, 1),
                "undecided": undecided,
                "wrong": int(np.sum(both & (verdicts != tiled_memo))),
            })
            row.update({key: (round(getattr(backend, attr), 3)
                              if isinstance(getattr(backend, attr), float)
                              else getattr(backend, attr))
                        for key, attr in counters.items()})
        except Exception as e:  # noqa: BLE001 — a failed cell must not
            # lose the earlier cells' rows (OOM at 262144, or the pallas
            # prototype failing to compile on the real Mosaic stack, are
            # real possible outcomes this tool exists to discover)
            row["error"] = f"{type(e).__name__}: {e}"[:300]
        return row

    def measure_pallas(batch):
        """The Pallas-vs-XLA-loop A/B cell (VERDICT r4 task #4): same
        corpus, same budget semantics, whole iteration chunks inside one
        Mosaic kernel launch instead of an XLA while-loop.  Only ever
        run on a real device (interpret mode on the fallback is not a
        measurement)."""
        from qsm_tpu.ops.pallas_kernel import PallasTPU

        row = {"batch": batch, "variant": "pallas"}

        def mk():
            backend = PallasTPU(spec, budget=2_000)
            backend.MAX_BATCH = batch
            # total_budget stamped so the row is self-describing
            # (ADVICE.md round 5, finding 3: budget=2000 alone implied a
            # 2k iteration cap while the inherited mid=50k/rescue=500k
            # defaults let the kernel run to 552k).  The DEFAULTS are
            # deliberately kept: the XLA control row this cell is the
            # A/B against runs the same inherited budgets — zeroing
            # them only here would confound driver with a 276×
            # iteration-cap difference.
            row["settings"] = {
                "pallas_chunk": backend.PALLAS_CHUNK,
                "lanes_per_block": backend.LANES,
                "cache_slots": backend.PALLAS_CACHE_SLOTS,
                "budget": 2_000,
                "total_budget": backend.total_budget,
            }
            return backend

        return _timed_cell(row, batch, mk, {
            "pallas_calls": "pallas_calls",
            "lockstep_iters": "lockstep_cost",
        })

    def measure(batch, variant=None, schedule=None, backend_kw=None,
                unroll=8):
        # unroll=8 is the production setting bench.py runs the headline
        # with (5.2x on the CPU platform; per-trip overhead dominates) —
        # width rows measure THAT kernel so best_scale_batch adoption
        # and the headline share a basis; the unroll1 control row keeps
        # the A/B on-chip evidence.
        row = {"batch": batch}
        if variant:
            row["variant"] = variant

        def mk():
            backend = JaxTPU(spec, budget=2_000, **(backend_kw or {}))
            backend.MAX_BATCH = batch
            backend.UNROLL = unroll
            if schedule is not None:
                backend.CHUNK_SCHEDULE = schedule
            elif on_tpu:
                backend.CHUNK_SCHEDULE = (2048, 65536)
            # the settings stamp makes every row self-describing across
            # kernel-default changes (VERDICT r4 weak #3: the banked
            # windows never recorded what they actually ran)
            row["settings"] = {
                "unroll": unroll,
                "chunk_schedule": list(backend.CHUNK_SCHEDULE),
                "budget": 2_000,
                "mid_budget": (backend_kw or {}).get(
                    "mid_budget", "default"),
                "total_budget": backend.total_budget,
            }
            return backend

        return _timed_cell(row, batch, mk, {
            "lockstep_iters": "lockstep_cost",
            "rounds": "rounds_run",
            "host_sync_s": "host_sync_s",
            "compactions": "compactions",
            "rescued": "rescued",
        })

    def cell(key, make_row):
        """One journaled cell: adopt the banked row on resume (zero
        re-run — the time box spends only on cells still unmeasured),
        else measure and bank atomically."""
        prev = journal.complete(key)
        if prev is not None:
            return prev
        return journal.emit(key, make_row())

    t_start = time.perf_counter()
    widths = DEVICE_BATCHES if on_tpu else CPU_BATCHES
    control = CONTROL_BATCH if on_tpu else CPU_CONTROL_BATCH

    # --- decision cells first (VERDICT r4 task #1) -----------------------
    # 1. unroll8 control at the headline width: the row every later width
    #    and the adopted headline compare against.
    cell(f"b{control}", lambda: measure(control))
    # 2. unroll1 at the SAME width: the on-chip unroll A/B the round-4
    #    windows never measured.  Runs second because it is the single
    #    cheapest cell that decides a kernel setting.
    if (journal.complete(f"b{control}:unroll1") is not None
            or time.perf_counter() - t_start <= time_box_s):
        cell(f"b{control}:unroll1",
             lambda: measure(control, variant="unroll1", unroll=1))
    else:
        journal.emit(f"b{control}:unroll1",
                     {"batch": control, "variant": "unroll1",
                      "skipped": "time box exhausted"})
    # 3. the Pallas-vs-XLA-loop A/B at the control width (device only:
    #    interpret mode on the fallback would measure the interpreter).
    if on_tpu:
        if (journal.complete(f"b{control}:pallas") is not None
                or time.perf_counter() - t_start <= time_box_s):
            cell(f"b{control}:pallas", lambda: measure_pallas(control))
        else:
            journal.emit(f"b{control}:pallas",
                         {"batch": control, "variant": "pallas",
                          "skipped": "time box exhausted"})
    # 4. the width ladder (control width already measured above).
    for batch in widths:
        if batch == control:
            continue
        if (journal.complete(f"b{batch}") is None
                and time.perf_counter() - t_start > time_box_s):
            journal.emit(f"b{batch}",
                         {"batch": batch, "skipped": "time box exhausted"})
            continue
        cell(f"b{batch}", lambda batch=batch: measure(batch))

    # Diagnostic variants at the widest healthy width — they separate the
    # two cost hypotheses the banked window can't distinguish (per-TRIP
    # latency vs per-chunk-CALL dispatch) and locate the budget knee:
    #   oneshot: a single 65536-iteration chunk = fewest device calls,
    #            most lockstep waste; wins iff call dispatch dominates.
    #   budget2k: no mid/rescue budget = straggler lanes report
    #            BUDGET_EXCEEDED instead of burning tail trips; the
    #            decided-lane rate shows what the tail costs the batch.
    # best_scale_batch ignores variant rows by construction.
    good = [r for r in journal.rows()[1:]
            if r.get("wrong") == 0 and "error" not in r
            and "skipped" not in r and "variant" not in r
            and r.get("rate_h_per_s")]
    if good and time.perf_counter() - t_start > time_box_s:
        # marked, not silently absent — and the watcher's min_rows gate
        # counts rows, so the marker alone does not fake completeness;
        # a future window re-runs the scan and gets the diagnostics
        journal.emit("diagnostics", {"variant": "diagnostics",
                                     "skipped": "time box exhausted"})
    if good and time.perf_counter() - t_start <= time_box_s:
        bstar = max(good, key=lambda r: r["rate_h_per_s"])["batch"]
        # matched-width unroll A/B at the ADOPTED width (ADVICE.md round
        # 5, finding 1): when the ladder picks a width other than the
        # control, the headline would otherwise run a (width, unroll)
        # pair never measured together on-chip — the exact settings
        # confound that burned round 4.  best_scale_unroll keeps
        # comparing at the FIRST unroll1 row's width (the control), so
        # this extra cell is diagnostic, not adoption-changing.
        if bstar != control:
            cell(f"b{bstar}:unroll1",
                 lambda: measure(bstar, variant="unroll1", unroll=1))
        cell(f"b{bstar}:oneshot",
             lambda: measure(bstar, variant="oneshot", schedule=(65536,)))
        if (journal.complete(f"b{bstar}:budget2k") is not None
                or time.perf_counter() - t_start <= time_box_s):
            b2k = cell(f"b{bstar}:budget2k",
                       lambda: measure(bstar, variant="budget2k",
                                       backend_kw=dict(mid_budget=0,
                                                       rescue_budget=0)))
            # Derived, not separately measured: the hybrid execution plan
            # (device decides the easy majority under the 2k budget, the
            # BUDGET_EXCEEDED tail goes to the native host checker — the
            # property layer's oracle-resolution contract, priced).
            if (cpp_rate and "error" not in b2k
                    and b2k.get("wrong") == 0):
                wall = b2k["wall_s"] + b2k["undecided"] / cpp_rate
                cell(f"b{bstar}:hybrid_derived", lambda: {
                    "batch": bstar, "variant": "hybrid_derived",
                    "wall_s": round(wall, 3),
                    "rate_h_per_s": round(bstar / wall, 1),
                    "from": "budget2k.wall_s + undecided/cpp_rate",
                    "undecided": 0, "wrong": 0})
        else:
            journal.emit(f"b{bstar}:budget2k",
                         {"variant": "budget2k",
                          "skipped": "time box exhausted"})
    return journal.rows()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/root/repo/BENCH_SCALE_r05.json")
    ap.add_argument("--force-cpu", action="store_true")
    ap.add_argument("--probe-timeout", type=float, default=None,
                    help="override the probe preset's per-attempt bound "
                         "(resilience/policy.py)")
    ap.add_argument("--time-box", type=float, default=TIME_BOX_S,
                    help="stop starting new cells beyond this many "
                         "seconds of measuring (the watcher passes a "
                         "window-sized box)")
    ap.add_argument("--resume", action="store_true",
                    help="adopt completed cells from an existing --out "
                         "journal (same artifact + device provenance) "
                         "instead of re-measuring them — a scan killed "
                         "after N cells re-runs zero of them")
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import probe_or_force_cpu

    on_tpu, _detail, header = probe_or_force_cpu(args.force_cpu,
                                                 args.probe_timeout)
    lines = run_scale(on_tpu, args.out, header, time_box_s=args.time_box,
                      resume=args.resume)
    for ln in lines:
        print(json.dumps(ln))
    return 0


if __name__ == "__main__":
    sys.exit(main())
