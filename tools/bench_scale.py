"""Batch-width scaling artifact — does widening the lockstep batch
amortize the per-trip latency the first real-TPU window exposed?

BENCH_TPU_r04.json (the round-4 banked window) showed the chunked device
driver at 105.6 h/s with batch 4096: ~7.6k while-loop trips per timed
rep at ~5 ms/trip, i.e. per-trip LATENCY, not lane width, dominates on
the axon tunnel (a 1-core CPU pays 3.6 ms/trip on a 256-lane batch of
the same kernel).  If per-trip cost is flat in width, throughput scales
with batch until HBM bandwidth binds — this tool measures exactly that
on the real chip: histories/sec at batch 4096 / 16384 / 65536 on the
bench.py CAS corpus, with full verdict parity against the memoised host
oracle on every lane.

Each row is measured with a fresh ``JaxTPU`` whose ``MAX_BATCH`` is
raised to the row's batch (the buckets above 4096 exist only for this —
ops/jax_kernel.py).  Rows are written incrementally (header first, then
one JSON line per batch as it lands) so a window that closes mid-scan
still leaves the smaller batches' measurements in the artifact.

bench.py reads the best zero-wrong-verdict row of a DEVICE-captured copy
of this artifact and adopts its batch for the headline; the watcher
(tools/probe_watcher.py) banks it during a window and re-benches the
headline when the best batch beats the banked headline's.

Probe-guarded exactly like bench.py.  Usage:

    python tools/bench_scale.py [--force-cpu] [--out BENCH_SCALE_rN.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

# CPU-fallback rows use a reduced width ladder: the vmapped while-loop is
# orders of magnitude slower on host, and the point of a fallback run is
# pipeline validation, not measurement.
# 262144 runs cache-off (slots=0) in its initial bucket; survivors
# compact into cached buckets.  Compile-validated at width on the
# CPU backend (6.5 s, ~0.9 GB device footprint -- nowhere near HBM).
DEVICE_BATCHES = (4096, 16384, 65536, 262144)
CPU_BATCHES = (256, 1024)
TIME_BOX_S = 900.0  # stop starting new rows beyond this much measuring


def run_scale(on_tpu: bool, out_path: str, header: dict) -> list:
    from bench import build_corpus
    from qsm_tpu.models import CasSpec
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    spec = CasSpec()
    n_unique = 512 if on_tpu else 128
    corpus = build_corpus(spec, n_unique)
    memo = WingGongCPU(memo=True)
    memo_verdicts = np.asarray(memo.check_histories(spec, corpus))

    # native host rate on this corpus — the denominator for the derived
    # hybrid number (device majority + cpp tail) the budget2k variant
    # enables
    cpp_rate = None
    try:
        from qsm_tpu.native import CppOracle, native_available
        if native_available():
            cpp = CppOracle(spec)
            cpp.check_histories(spec, corpus)  # build + table compile
            t0 = time.perf_counter()
            cpp.check_histories(spec, corpus)
            if cpp.native_histories > 0:
                cpp_rate = round(
                    len(corpus) / (time.perf_counter() - t0), 1)
    except Exception:  # noqa: BLE001 — optional fast path
        pass

    lines = [{"artifact": "bench_scale", "corpus_unique": len(corpus),
              "cpp_rate_h_per_s": cpp_rate, **header}]
    with open(out_path, "w") as f:
        f.write(json.dumps(lines[0]) + "\n")
        f.flush()

    def measure(batch, variant=None, schedule=None, backend_kw=None,
                unroll=8):
        # unroll=8 is the production setting bench.py runs the headline
        # with (5.2x on the CPU platform; per-trip overhead dominates) —
        # width rows measure THAT kernel so best_scale_batch adoption
        # and the headline share a basis; the unroll1 control row keeps
        # the A/B on-chip evidence.
        reps = (batch + len(corpus) - 1) // len(corpus)
        device_corpus = (corpus * reps)[:batch]
        tiled_memo = np.tile(memo_verdicts, reps)[:batch]
        row = {"batch": batch}
        if variant:
            row["variant"] = variant
        try:
            backend = JaxTPU(spec, budget=2_000, **(backend_kw or {}))
            backend.MAX_BATCH = batch
            backend.UNROLL = unroll
            if schedule is not None:
                backend.CHUNK_SCHEDULE = schedule
            elif on_tpu:
                backend.CHUNK_SCHEDULE = (2048, 65536)
            t0 = time.perf_counter()
            backend.check_histories(spec, device_corpus)  # compile + warm
            row["warm_s"] = round(time.perf_counter() - t0, 2)
            # zero EVERY per-run counter the row reports, or the stats
            # mix the warm pass with the timed pass
            backend.lockstep_cost = 0
            backend.rounds_run = 0
            backend.host_sync_s = 0.0
            backend.compactions = 0
            backend.rescued = 0
            t0 = time.perf_counter()
            verdicts = np.asarray(
                backend.check_histories(spec, device_corpus))
            wall = time.perf_counter() - t0
            undecided = int(np.sum(verdicts == 2))
            both = (verdicts != 2) & (tiled_memo != 2)
            row.update({
                "wall_s": round(wall, 3),
                "rate_h_per_s": round((batch - undecided) / wall, 1),
                "undecided": undecided,
                "wrong": int(np.sum(both
                             & (verdicts != tiled_memo))),
                "lockstep_iters": backend.lockstep_cost,
                "rounds": backend.rounds_run,
                "host_sync_s": round(backend.host_sync_s, 3),
                "compactions": backend.compactions,
                "rescued": backend.rescued,
            })
        except Exception as e:  # noqa: BLE001 — a failed width must not
            # lose the smaller widths' rows (OOM at 65536 is a real
            # possible outcome this tool exists to discover)
            row["error"] = f"{type(e).__name__}: {e}"[:300]
        return row

    def emit(row):
        lines.append(row)
        f = open(out_path, "a")
        f.write(json.dumps(row) + "\n")
        f.close()

    t_start = time.perf_counter()
    widths = DEVICE_BATCHES if on_tpu else CPU_BATCHES
    for batch in widths:
        if time.perf_counter() - t_start > TIME_BOX_S:
            emit({"batch": batch, "skipped": "time box exhausted"})
            continue
        emit(measure(batch))

    # Diagnostic variants at the widest healthy width — they separate the
    # two cost hypotheses the banked window can't distinguish (per-TRIP
    # latency vs per-chunk-CALL dispatch) and locate the budget knee:
    #   oneshot: a single 65536-iteration chunk = fewest device calls,
    #            most lockstep waste; wins iff call dispatch dominates.
    #   budget2k: no mid/rescue budget = straggler lanes report
    #            BUDGET_EXCEEDED instead of burning tail trips; the
    #            decided-lane rate shows what the tail costs the batch.
    # best_scale_batch ignores variant rows by construction.
    good = [r for r in lines[1:]
            if r.get("wrong") == 0 and "error" not in r
            and "skipped" not in r and r.get("rate_h_per_s")]
    if good and time.perf_counter() - t_start > TIME_BOX_S:
        # marked, not silently absent — and the watcher's min_rows gate
        # counts rows, so the marker alone does not fake completeness;
        # a future window re-runs the scan and gets the diagnostics
        emit({"variant": "diagnostics", "skipped": "time box exhausted"})
    if good and time.perf_counter() - t_start <= TIME_BOX_S:
        bstar = max(good, key=lambda r: r["rate_h_per_s"])["batch"]
        emit(measure(bstar, variant="unroll1", unroll=1))
        emit(measure(bstar, variant="oneshot", schedule=(65536,)))
        if time.perf_counter() - t_start <= TIME_BOX_S:
            b2k = measure(bstar, variant="budget2k",
                          backend_kw=dict(mid_budget=0, rescue_budget=0))
            emit(b2k)
            # Derived, not separately measured: the hybrid execution plan
            # (device decides the easy majority under the 2k budget, the
            # BUDGET_EXCEEDED tail goes to the native host checker — the
            # property layer's oracle-resolution contract, priced).
            if (cpp_rate and "error" not in b2k
                    and b2k.get("wrong") == 0):
                wall = b2k["wall_s"] + b2k["undecided"] / cpp_rate
                emit({"batch": bstar, "variant": "hybrid_derived",
                      "wall_s": round(wall, 3),
                      "rate_h_per_s": round(bstar / wall, 1),
                      "from": "budget2k.wall_s + undecided/cpp_rate",
                      "undecided": 0, "wrong": 0})
        else:
            emit({"variant": "budget2k", "skipped": "time box exhausted"})
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/root/repo/BENCH_SCALE_r04.json")
    ap.add_argument("--force-cpu", action="store_true")
    ap.add_argument("--probe-timeout", type=float, default=45.0)
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import probe_or_force_cpu

    on_tpu, _detail, header = probe_or_force_cpu(args.force_cpu,
                                                 args.probe_timeout)
    lines = run_scale(on_tpu, args.out, header)
    for ln in lines:
        print(json.dumps(ln))
    return 0


if __name__ == "__main__":
    sys.exit(main())
