"""Pruning-soundness soak: pruned vs unpruned exploration on many random
programs across every model family (the round-3 burn-in lesson: 400+
trials catch what 120 don't — docs/EXPERIMENTS.md).

For each (family, seed): enumerate the delivery tree twice, pruned and
unpruned, bounded by --max-schedules.  Whenever BOTH walks exhaust, the
distinct-history fingerprint sets must be IDENTICAL; when only the pruned
walk exhausts, its history set must be a superset of the truncated
unpruned walk's.  Any divergence prints the reproducer (family, impl,
seed, pids, ops) and exits 1.

    python tools/soak_prune.py --per-family 60 [--pids 3] [--ops 5]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, "/root/repo")

from qsm_tpu.utils.device import force_cpu_platform  # noqa: E402

force_cpu_platform()

from qsm_tpu.core.generator import generate_program  # noqa: E402
from qsm_tpu.models.registry import MODELS, SutFactory, make  # noqa: E402
from qsm_tpu.sched.systematic import _enumerate  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--per-family", type=int, default=60)
    ap.add_argument("--pids", type=int, default=3)
    ap.add_argument("--ops", type=int, default=5)
    ap.add_argument("--max-schedules", type=int, default=4_000)
    ap.add_argument("--impl", default="racy",
                    help="racy impls have the richer interleaving trees")
    args = ap.parse_args(argv)

    t0 = time.time()
    total = both_exh = pruned_only = mismatches = 0
    saved = 0
    for family in sorted(MODELS):
        spec, _ = make(family, args.impl)
        for seed in range(args.per_family):
            prog = generate_program(spec, seed=seed, n_pids=args.pids,
                                    max_ops=args.ops)
            factory = SutFactory(family, args.impl)
            up_h, up_n, up_exh, _ = _enumerate(
                factory, prog, args.max_schedules, 100_000, prune=False)
            pr_h, pr_n, pr_exh, _ = _enumerate(
                factory, prog, args.max_schedules, 100_000, prune=True)
            total += 1
            saved += max(0, up_n - pr_n)
            up_set = {h.fingerprint() for h in up_h}
            pr_set = {h.fingerprint() for h in pr_h}
            if up_exh and pr_exh:
                both_exh += 1
                ok = up_set == pr_set
            elif pr_exh:
                pruned_only += 1
                ok = up_set <= pr_set
            else:
                ok = True  # both truncated: no completeness claim to check
            if not pr_exh and up_exh:
                ok = False  # pruning must never LOSE exhaustion
            if not ok:
                mismatches += 1
                print(json.dumps({
                    "MISMATCH": {"family": family, "impl": args.impl,
                                 "seed": seed, "pids": args.pids,
                                 "ops": args.ops,
                                 "unpruned": [len(up_set), up_n, up_exh],
                                 "pruned": [len(pr_set), pr_n, pr_exh]}}),
                    flush=True)
    print(json.dumps({
        "programs": total, "both_exhausted": both_exh,
        "pruned_only_exhausted": pruned_only,
        "schedules_saved": saved, "mismatches": mismatches,
        "families": len(MODELS), "per_family": args.per_family,
        "pids": args.pids, "ops": args.ops,
        "seconds": round(time.time() - t0, 1)}))
    return 1 if mismatches else 0


if __name__ == "__main__":
    sys.exit(main())
