"""Pruning-soundness soak + probe-log compaction.

**Soak** (the original job): pruned vs unpruned exploration on many
random programs across every model family (the round-3 burn-in lesson:
400+ trials catch what 120 don't — docs/EXPERIMENTS.md).

For each (family, seed): enumerate the delivery tree twice, pruned and
unpruned, bounded by --max-schedules.  Whenever BOTH walks exhaust, the
distinct-history fingerprint sets must be IDENTICAL; when only the pruned
walk exhausts, its history set must be a superset of the truncated
unpruned walk's.  Any divergence prints the reproducer (family, impl,
seed, pids, ops) and exits 1.

    python tools/soak_prune.py --per-family 60 [--pids 3] [--ops 5]

**Compaction** (``--compact-probe-log PATH``): ``probe_log.jsonl`` is
append-only and grows every watcher round (717 rows and counting by
round 6) while almost all of it is the same wedged-tunnel failure line.
The evidence worth keeping forever is tiny: every DEVICE-HIT row (the
windows), every ``event`` row (window seizes, lint gates, banked
artifacts), and a recent tail of failures for cadence context.  This
mode rewrites the log atomically
(qsm_tpu/resilience/checkpoint.py) keeping exactly those, and the probe
watcher invokes it when the log crosses a row threshold.  Deliberately
light: no jax, no model imports — safe to run from the watcher loop.

    python tools/soak_prune.py --compact-probe-log probe_log.jsonl \
        [--keep-failures 500]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, "/root/repo")


# ---------------------------------------------------------------------------
# Probe-log compaction (watcher-invoked; keep this path import-light)
# ---------------------------------------------------------------------------

def compact_probe_log(path: str, keep_failures: int = 500) -> dict:
    """Rewrite ``path`` keeping all device-hit rows, all ``event`` rows,
    and the last ``keep_failures`` other rows, in original order.  A
    garbled line is treated as a failure row (kept only in the tail
    window) — never a reason to abort a compaction.  Atomic: a watcher
    killed mid-compaction leaves the previous log intact."""
    from qsm_tpu.resilience.checkpoint import atomic_write_text

    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        return {"rows": 0, "kept": 0, "dropped": 0, "compacted": False}
    keep = [False] * len(lines)
    other_idx = []
    for i, ln in enumerate(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            other_idx.append(i)  # garbled: only the tail window keeps it
            continue
        if rec.get("is_device") or "event" in rec:
            keep[i] = True
        else:
            other_idx.append(i)
    for i in other_idx[-keep_failures:] if keep_failures > 0 else []:
        keep[i] = True
    kept = [lines[i] for i in range(len(lines)) if keep[i]]
    dropped = len(lines) - len(kept)
    if dropped > 0:
        atomic_write_text(path, "\n".join(kept) + "\n")
    return {"rows": len(lines), "kept": len(kept), "dropped": dropped,
            "compacted": dropped > 0}


# ---------------------------------------------------------------------------
# The pruning-soundness soak (heavy imports live here, not at module top,
# so the compaction path stays watcher-cheap)
# ---------------------------------------------------------------------------

def run_soak(args) -> int:
    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform()

    from qsm_tpu.core.generator import generate_program
    from qsm_tpu.models.registry import MODELS, SutFactory, make
    from qsm_tpu.sched.systematic import _enumerate

    t0 = time.time()
    total = both_exh = pruned_only = mismatches = 0
    saved = 0
    for family in sorted(MODELS):
        spec, _ = make(family, args.impl)
        for seed in range(args.per_family):
            prog = generate_program(spec, seed=seed, n_pids=args.pids,
                                    max_ops=args.ops)
            factory = SutFactory(family, args.impl)
            up_h, up_n, up_exh, _ = _enumerate(
                factory, prog, args.max_schedules, 100_000, prune=False)
            pr_h, pr_n, pr_exh, _ = _enumerate(
                factory, prog, args.max_schedules, 100_000, prune=True)
            total += 1
            saved += max(0, up_n - pr_n)
            up_set = {h.fingerprint() for h in up_h}
            pr_set = {h.fingerprint() for h in pr_h}
            if up_exh and pr_exh:
                both_exh += 1
                ok = up_set == pr_set
            elif pr_exh:
                pruned_only += 1
                ok = up_set <= pr_set
            else:
                ok = True  # both truncated: no completeness claim to check
            if not pr_exh and up_exh:
                ok = False  # pruning must never LOSE exhaustion
            if not ok:
                mismatches += 1
                print(json.dumps({
                    "MISMATCH": {"family": family, "impl": args.impl,
                                 "seed": seed, "pids": args.pids,
                                 "ops": args.ops,
                                 "unpruned": [len(up_set), up_n, up_exh],
                                 "pruned": [len(pr_set), pr_n, pr_exh]}}),
                    flush=True)
    print(json.dumps({
        "programs": total, "both_exhausted": both_exh,
        "pruned_only_exhausted": pruned_only,
        "schedules_saved": saved, "mismatches": mismatches,
        "families": len(MODELS), "per_family": args.per_family,
        "pids": args.pids, "ops": args.ops,
        "seconds": round(time.time() - t0, 1)}))
    return 1 if mismatches else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--per-family", type=int, default=60)
    ap.add_argument("--pids", type=int, default=3)
    ap.add_argument("--ops", type=int, default=5)
    ap.add_argument("--max-schedules", type=int, default=4_000)
    ap.add_argument("--impl", default="racy",
                    help="racy impls have the richer interleaving trees")
    ap.add_argument("--compact-probe-log", default=None, metavar="PATH",
                    help="compact a probe_log.jsonl instead of soaking: "
                         "keep device-hit rows, event rows, and the last "
                         "--keep-failures others; atomic rewrite")
    ap.add_argument("--keep-failures", type=int, default=500,
                    help="non-device, non-event rows retained from the "
                         "tail during --compact-probe-log")
    args = ap.parse_args(argv)

    if args.compact_probe_log:
        print(json.dumps({"compact_probe_log": args.compact_probe_log,
                          **compact_probe_log(args.compact_probe_log,
                                              args.keep_failures)}))
        return 0
    return run_soak(args)


if __name__ == "__main__":
    sys.exit(main())
