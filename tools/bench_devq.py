"""Window-arbitrage bench — bank on every plane, drain a simulated
window, prove the window can only ever make the system FASTER.

ISSUE 20's acceptance bars, as journal cells:

* ``bank`` — every lanes-carrying plane (check / pcomp / shrink /
  monitor) banks a deterministic corpus into one persistent queue dir,
  plus a planner ``warmup`` item via the real ``note_device_plan``
  seam; the snapshot proves per-plane pending and queue persistence.
* ``drain`` — a simulated 8-device window: ``tools/window_drain.py
  --force-devices 8`` over the banked dir (the exact no-hardware
  recipe docs/WINDOWS.md documents, the exact binary the watcher runs
  on a real window).  Gated: ``wrong_verdicts == 0`` and
  ``window_utilization >=`` the serve ``health`` SLO target.
* ``host_baseline`` — the SAME corpora through a fresh host memo
  oracle, timed; every verdict the drain banked must be bit-identical
  to the host ladder's (looked up under the originating plane's exact
  ``fingerprint_key`` in the drain's persistent bank — so this also
  proves the bank landed under keys the planes will actually hit).
* ``kill_resume`` — SIGKILL a drainer mid-window, ``--resume`` a
  successor under the same window id: exactly-once means the
  successor re-dispatches NOTHING the victim already proved
  (``resumed`` ∩ ``dispatched`` = ∅) and together they cover the
  whole queue.
* ``fleet`` — node A banks (seal-per-row log), node B adopts A's devq
  segments through the queue's anti-entropy surface (the same
  digest → missing → pull → adopt legs gossip drives over the wire),
  B wins the window and drains, A adopts B's done tombstones: A's
  pending converges to zero and every lane A banked hits B's bank.
* ``summary`` — ``gate_ok``.

Scaling honesty (the r08/r13/r19 precedent): the 8 forced virtual
devices share one host core, so ``device_vs_host_ratio`` measures
dispatch overhead, not chip speedup — the committed curve says so
(``host_cores`` is stamped).  The gates that are absolute here are
soundness gates: zero wrong verdicts, bit-identical to the host
ladder, exactly-once under SIGKILL, fleet convergence.

Output: resumable ``CellJournal`` committed as ``BENCH_DEVQ_<tag>.json``
(``make bench-devq``; probe_watcher archives it off-window and
``bench_report.py`` folds it into BENCH_REPORT.md).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WINDOW_DEVICES = 8      # the simulated main-window mesh width
KILL_DEVICES = 2        # kill/resume cell: cheap compiles, same rails
BUDGET = 2_000
DRAIN_TIMEOUT_S = 900.0
# (plane, model, lanes, seed) — one corpus per lanes-carrying plane;
# lane counts divisible by the mesh width so the sharded dispatch has
# no ragged tail to pad
PLANE_SHAPES = (("check", "register", 16, 11), ("pcomp", "kv", 8, 2026),
                ("shrink", "cas", 8, 2026), ("monitor", "queue", 8, 11))
# kill/resume queue: one item per model, each a distinct compile, so
# the victim is reliably mid-queue when the SIGKILL lands
KILL_MODELS = ("register", "cas", "queue", "set", "stack", "ticket")
KILL_LANES = 6
KILL_AFTER_CELLS = 2    # journal completions before the SIGKILL


def _corpora():
    """The deterministic per-plane corpora (seed-derived: the bank,
    drain and host_baseline cells all rebuild the same histories)."""
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.utils.corpus import build_corpus

    out = []
    for plane, fam, lanes, seed in PLANE_SHAPES:
        entry = MODELS[fam]
        spec = entry.make_spec()
        hists = build_corpus(
            spec, (entry.impls["atomic"], entry.impls["racy"]),
            n=lanes, n_pids=entry.default_pids,
            max_ops=entry.default_ops, seed_base=seed,
            seed_prefix=f"bench_devq_{plane}")
        out.append((plane, spec, hists))
    return out


def _bank_into(dir: str, *, node_id: str = "n0", seal_rows: int = 64):
    """Bank the four plane corpora + the planner warmup seam into a
    persistent queue at ``dir``.  Idempotent by fingerprint: re-banking
    after a crash rebuilds the identical queue."""
    from qsm_tpu.devq.queue import (DeviceWorkQueue, bank_histories,
                                    note_device_plan, set_global_devq)
    from qsm_tpu.search.planner import plan_search, profile_corpus

    q = DeviceWorkQueue(dir, node_id=node_id, seal_rows=seal_rows)
    lanes = 0
    for plane, spec, hists in _corpora():
        bank_histories(spec, hists, plane=plane, queue=q)
        lanes += len(hists)
        if plane == "pcomp":
            # the planner seam, driven for real: a mesh-sized plan for
            # the kv family banks its @meshN warm-compile item
            plan = plan_search(spec, profile_corpus(hists, spec),
                               mesh_devices=WINDOW_DEVICES)
            set_global_devq(q)
            try:
                note_device_plan(spec, plan)
            finally:
                set_global_devq(None)
    return q, lanes


def _run_window_drain(dir: str, out: str, *, devices: int,
                      window_s: float, window_id: str,
                      resume: bool = False, wait: bool = True):
    """Spawn the REAL drain binary (the one the watcher runs) under a
    forced virtual mesh; returns the Popen (wait=False) or the report."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "window_drain.py"),
           "--dir", dir, "--out", out, "--force-devices", str(devices),
           "--window-s", str(window_s), "--window-id", window_id,
           "--budget", str(BUDGET)]
    if resume:
        cmd.append("--resume")
    proc = subprocess.Popen(cmd, cwd=REPO, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    if not wait:
        return proc
    try:
        stdout, stderr = proc.communicate(timeout=DRAIN_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise
    if proc.returncode != 0:
        raise RuntimeError(
            f"window_drain failed ({proc.returncode}):\n"
            f"{(stdout or '')[-2000:]}\n{(stderr or '')[-2000:]}")
    with open(out) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------

def _cell_bank(qdir: str) -> dict:
    q, lanes = _bank_into(qdir)
    snap = q.snapshot()
    planes_banked = sorted(snap["pending_by_plane"])
    assert set(p for p, _, _, _ in PLANE_SHAPES) <= set(planes_banked), \
        snap
    return {"queue_dir": qdir, "lanes": lanes,
            "planes": planes_banked, **snap}


def _cell_drain(qdir: str, out: str) -> dict:
    report = _run_window_drain(
        qdir, out, devices=WINDOW_DEVICES, window_s=600.0,
        window_id="bench")
    report["force_devices"] = WINDOW_DEVICES
    return report


def _cell_host_baseline(qdir: str, drain: dict) -> dict:
    """The matched host ladder: fresh memo oracle over the same lanes,
    then bit-compare every banked drain verdict under the originating
    fingerprint.  Budget-undecided lanes are legitimately unbanked
    (the bank refuses BUDGET_EXCEEDED rows); everything decided must
    hit, identically."""
    from qsm_tpu.ops.backend import Verdict
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.serve.cache import VerdictCache, fingerprint_key

    bank = VerdictCache(max_entries=65536,
                        path=os.path.join(qdir, "drain_cache.jsonl"))
    undecided = int(Verdict.BUDGET_EXCEEDED)
    t0 = time.perf_counter()
    lanes = mismatches = missing = skipped_undecided = 0
    per_plane = {}
    for plane, spec, hists in _corpora():
        oracle = WingGongCPU(memo=True)
        verdicts = oracle.check_histories(spec, hists)
        lanes += len(hists)
        per_plane[plane] = [int(v) for v in verdicts]
        for h, v in zip(hists, verdicts):
            if int(v) == undecided:
                skipped_undecided += 1
                continue
            e = bank.get(fingerprint_key(spec, h))
            if e is None:
                missing += 1
            elif int(e.verdict) != int(v):
                mismatches += 1
    host_s = time.perf_counter() - t0
    ratios = {p: s.get("device_vs_host_ratio")
              for p, s in drain["per_plane"].items() if s["items"]}
    return {
        "lanes": lanes,
        "host_s": round(host_s, 3),
        "host_lanes_per_sec": round(lanes / max(host_s, 1e-9), 1),
        "verdicts": per_plane,
        "banked_missing": missing,
        "verdict_mismatches": mismatches,
        "skipped_undecided": skipped_undecided,
        "verdicts_identical": mismatches == 0 and missing == 0,
        "device_vs_host_ratio_by_plane": ratios,
    }


def _cell_kill_resume(workdir: str) -> dict:
    """SIGKILL a drainer mid-window; the --resume successor must
    re-dispatch nothing the victim's journal already proved.

    The victim's ``mark_done`` tombstones persist as it drains, so a
    plain re-run on the same dir would skip proved items via the QUEUE
    alone and never exercise the journal.  The successor therefore runs
    against a RESTORED pre-drain queue (gossip re-delivers banked rows
    to a node whose local queue state regressed — put() is idempotent
    by design) with the victim's journal carried over: every item is
    pending again, and the per-window journal is the only thing
    standing between the successor and double-dispatch."""
    from qsm_tpu.devq.queue import DeviceWorkQueue, bank_histories
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.utils.corpus import build_corpus

    qdir = os.path.join(workdir, "kill_q")
    q = DeviceWorkQueue(qdir)
    keys = []
    for fam in KILL_MODELS:
        entry = MODELS[fam]
        spec = entry.make_spec()
        hists = build_corpus(
            spec, (entry.impls["atomic"], entry.impls["racy"]),
            n=KILL_LANES, n_pids=entry.default_pids,
            max_ops=entry.default_ops, seed_base=7,
            seed_prefix="bench_devq_kill")
        keys.append(bank_histories(spec, hists, plane="check", queue=q))
    qdir0 = os.path.join(workdir, "kill_q_prebank")
    shutil.copytree(qdir, qdir0)           # the pre-drain replog
    journal = os.path.join(qdir, "drain_journal.jsonl")

    victim = _run_window_drain(
        qdir, os.path.join(workdir, "kill_r1.json"),
        devices=KILL_DEVICES, window_s=600.0, window_id="kill",
        wait=False)
    # each completed item is one atomically-flushed journal row (after
    # the header line): kill once the victim has proved a couple but
    # the queue still holds more
    killed_after = 0
    deadline = time.monotonic() + DRAIN_TIMEOUT_S
    while time.monotonic() < deadline and victim.poll() is None:
        try:
            with open(journal) as f:
                killed_after = max(0, sum(1 for ln in f if ln.strip()) - 1)
        except OSError:
            killed_after = 0
        if killed_after >= KILL_AFTER_CELLS:
            break
        time.sleep(0.2)
    victim.kill()
    victim.communicate()
    pending_after_kill = len(DeviceWorkQueue(qdir))

    qdir_r = os.path.join(workdir, "kill_q_restored")
    shutil.copytree(qdir0, qdir_r)
    shutil.copy(journal, os.path.join(qdir_r, "drain_journal.jsonl"))
    report = _run_window_drain(
        qdir_r, os.path.join(workdir, "kill_r2.json"),
        devices=KILL_DEVICES, window_s=600.0, window_id="kill",
        resume=True)
    resumed = set(report["resumed"])
    dispatched = set(report["dispatched"])
    # exactly-once: the journal replay folded every victim-proved item
    # (zero re-dispatch), the rest ran fresh, and together they cover
    # the whole queue
    queue_empty = len(DeviceWorkQueue(qdir_r)) == 0
    exactly_once = (not (resumed & dispatched)
                    and resumed | dispatched == set(keys)
                    and queue_empty)
    return {
        "items_banked": len(keys),
        "killed_after_cells": killed_after,
        "victim_returncode": victim.returncode,
        "pending_after_kill": pending_after_kill,
        "resumed": sorted(resumed),
        "dispatched": sorted(dispatched),
        "redispatched_overlap": sorted(resumed & dispatched),
        "queue_empty_after_resume": queue_empty,
        "wrong_verdicts": report["wrong_verdicts"],
        "exactly_once": bool(exactly_once),
    }


def _cell_fleet(workdir: str) -> dict:
    """A banks → B adopts (anti-entropy) → B wins the window and drains
    → A adopts B's tombstones → A converges; A's lanes hit B's bank."""
    from qsm_tpu.devq.drain import DrainScheduler
    from qsm_tpu.devq.queue import DeviceWorkQueue, bank_histories
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.serve.cache import VerdictCache, fingerprint_key

    da = os.path.join(workdir, "fleet_a")
    db = os.path.join(workdir, "fleet_b")
    # seal-per-row logs: every banked row is immediately a gossipable
    # sealed segment (production seals at 64; the legs are identical)
    qa = DeviceWorkQueue(da, node_id="A", seal_rows=1)
    banked_lanes = []
    for plane, spec, hists in _corpora()[:2]:
        bank_histories(spec, hists, plane=plane, queue=qa)
        banked_lanes.append((spec, hists))
    qb = DeviceWorkQueue(db, node_id="B", seal_rows=1)

    def reconcile(dst, src):
        adopted = 0
        for name in dst.missing(src.digests()):
            fp, lines = src.read_segment(name)
            adopted += dst.adopt(name, fp, lines)
        return adopted

    a_to_b = reconcile(qb, qa)
    assert len(qb) == len(qa), (len(qb), len(qa))

    bank_b = VerdictCache(max_entries=4096,
                          path=os.path.join(db, "bank.jsonl"))
    report = DrainScheduler(qb, cache=bank_b, window_s=600.0,
                            window_id="fleet", budget=BUDGET).drain()

    b_to_a = reconcile(qa, qb)   # done tombstones absorb A's pending
    hits = total = wrong = 0
    for spec, hists in banked_lanes:
        oracle = WingGongCPU(memo=True)
        proofs = oracle.check_histories(spec, hists)
        for h, p in zip(hists, proofs):
            total += 1
            e = bank_b.get(fingerprint_key(spec, h))
            if e is None:
                continue
            hits += 1
            if int(e.verdict) != int(p):
                wrong += 1
    return {
        "items_banked": len(banked_lanes),
        "segments_a_to_b": a_to_b,
        "segments_b_to_a": b_to_a,
        "drained_on_b": report["drained"],
        "drain_wrong_verdicts": report["wrong_verdicts"],
        "pending_a_after": len(qa),
        "pending_b_after": len(qb),
        "lanes": total,
        "bank_hits": hits,
        "bank_wrong": wrong,
        "converged": len(qa) == 0 and len(qb) == 0,
        "all_lanes_banked": hits == total and wrong == 0,
    }


# ---------------------------------------------------------------------------

def run(tag: str, out_path, resume: bool) -> dict:
    from qsm_tpu.obs.slo import WINDOW_UTILIZATION_TARGET
    from qsm_tpu.resilience.checkpoint import CellJournal

    path = out_path or os.path.join(REPO, f"BENCH_DEVQ_{tag}.json")
    workdir = os.path.join(tempfile.gettempdir(), f"qsm_bench_devq_{tag}")
    header = {
        "artifact": "BENCH_DEVQ",
        "device_fallback": None,   # host-only: forced virtual devices
        "platform": "cpu",
        "window_devices": WINDOW_DEVICES,
        "planes": [p for p, _, _, _ in PLANE_SHAPES],
        "budget": BUDGET,
        "utilization_floor": WINDOW_UTILIZATION_TARGET,
        "host_cores": os.cpu_count(),
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)
    qdir = os.path.join(workdir, "q")

    bank = journal.complete("bank")
    drain = journal.complete("drain")
    if bank is None:
        # fresh scan: a stale workdir would make put() dedupe against
        # last run's tombstones and hand the drain an empty queue
        shutil.rmtree(workdir, ignore_errors=True)
        os.makedirs(workdir, exist_ok=True)
        bank = journal.emit("bank", _cell_bank(qdir))
    elif drain is None and not os.path.isdir(qdir):
        # resumed past bank but the (tmp) queue dir is gone: re-bank
        # in place — same seeds, same fingerprints, identical queue
        os.makedirs(workdir, exist_ok=True)
        _bank_into(qdir)

    if drain is None:
        drain = journal.emit("drain", _cell_drain(
            qdir, os.path.join(workdir, "drain_report.json")))

    host = journal.complete("host_baseline")
    if host is None:
        if not os.path.isdir(qdir):
            raise RuntimeError(
                "queue dir lost between drain and host_baseline; "
                "re-run without --resume")
        host = journal.emit("host_baseline",
                            _cell_host_baseline(qdir, drain))

    kill = journal.complete("kill_resume")
    if kill is None:
        os.makedirs(workdir, exist_ok=True)
        kill = journal.emit("kill_resume", _cell_kill_resume(workdir))

    fleet = journal.complete("fleet")
    if fleet is None:
        os.makedirs(workdir, exist_ok=True)
        fleet = journal.emit("fleet", _cell_fleet(workdir))

    host_cores = os.cpu_count() or 1
    wrong = (drain["wrong_verdicts"] + kill["wrong_verdicts"]
             + fleet["drain_wrong_verdicts"] + fleet["bank_wrong"])
    summary = {
        "metric": "window_arbitrage",
        "host_cores": host_cores,
        "planes_banked": bank["planes"],
        "items_drained": drain["drained"],
        "window_utilization": drain["window_utilization"],
        "utilization_floor": WINDOW_UTILIZATION_TARGET,
        "gate_utilization": bool(drain["window_utilization"]
                                 >= WINDOW_UTILIZATION_TARGET),
        "wrong_verdicts": wrong,
        "key_mismatches": drain["key_mismatches"],
        "verdicts_identical_vs_host": host["verdicts_identical"],
        "device_vs_host_ratio_by_plane":
            host["device_vs_host_ratio_by_plane"],
        "host_lanes_per_sec": host["host_lanes_per_sec"],
        "exactly_once": kill["exactly_once"],
        "kill_resumed_items": len(kill["resumed"]),
        "fleet_converged": fleet["converged"],
        "fleet_lanes_banked": fleet["all_lanes_banked"],
        "scaling_honesty": (
            f"host has {host_cores} core(s): the {WINDOW_DEVICES} "
            "forced virtual devices share it, so the per-plane "
            "device-vs-host ratios measure dispatch overhead, not chip "
            "scaling; the soundness gates (zero wrong, bit-identical, "
            "exactly-once, convergence) are absolute"),
    }
    summary["gate_ok"] = bool(
        summary["gate_utilization"]
        and summary["wrong_verdicts"] == 0
        and summary["verdicts_identical_vs_host"]
        and summary["exactly_once"]
        and summary["fleet_converged"]
        and summary["fleet_lanes_banked"])
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tag", default="r20")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="skip cells already banked in a compatible "
                         "prior artifact (CellJournal rails)")
    args = ap.parse_args(argv)
    summary = run(args.tag, args.out, args.resume)
    print(summary)
    return 0 if summary["gate_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
