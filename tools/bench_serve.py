"""Serving-plane bench — warm+coalesced vs one-shot, pooled vs one core.

Round 7 (ISSUE 5) priced the serving plane itself: a warm, batching,
caching server beat the one-shot CLI 3.3×, but its own `serve_c8` row
showed the wall — one PROCESS checked every micro-batch, so throughput
*degraded* past 4 clients (121.9 → 79.1 h/s) while batch occupancy sat
at 0.98.  Round 8 (ISSUE 6) adds the worker POOL rows that attack
exactly that wall, all still on the CPU platform:

* ``baseline_cli``   — one-shot ``qsm-tpu check`` subprocess per
  corpus (startup + engine construction included: the amortized cost);
* ``serve_c{1,2,4,8}``   — the single-process served path (the r07
  shape, re-measured so the pooled ratio is same-machine honest);
* ``serve_w{1,2,4}_c{1,2,4,8}`` — the worker-count × client-count
  sweep: the same admission → batcher → cache plane dispatching to
  1/2/4 supervised worker processes (``qsm-tpu serve --workers N``);
* ``kill_worker``    — SIGKILL one worker MID-BENCH on a 2-worker
  pool: verdicts must stay bit-identical to the clean run (the shed /
  re-dispatch path priced under load, not just unit-tested);
* ``cache_hit``      — duplicate-corpus submissions: the O(1) banked-
  verdict path.

EVERY response in every served cell is verified against the host
oracle (``wrong_verdicts`` is a per-row fact, required 0), and every
row stamps ``workers``/``worker_faults``/``respawns`` so a degraded
rate can never read as a clean one.

Win condition (ISSUE 6 acceptance): ≥2× served h/s at 4 workers vs
the single-process **r07 path** at the same client count (the
committed BENCH_SERVE_r07.json serve_c4 row — diagnosing and fixing
that path's actual wall, the per-batch full-bank rewrite, was this
round's first result, so the same-run single-process row sits far
above it and is recorded alongside as the honesty ratio), zero wrong
verdicts, and a kill-one-worker cell bit-identical to the clean run.
Output: a resumable ``CellJournal`` (``--resume`` re-runs zero
completed cells) committed as ``BENCH_SERVE_<tag>.json``.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_PIDS = 4
N_OPS = 10
CLIENT_COUNTS = (1, 2, 4, 8)
WORKER_COUNTS = (1, 2, 4)
ROUNDS = 6           # closed-loop rounds per client
BASELINE_REPS = 3
CACHE_HIT_REPS = 20
SUBPROC_TIMEOUT_S = 600.0
KILL_AFTER_S = 0.3    # mid-bench point for the kill_worker cell
KILL_ROUNDS = ROUNDS * 8  # long enough that the kill lands mid-run


def _build_corpora(n_corpora: int, corpus_n: int):
    from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
    from qsm_tpu.utils.corpus import build_corpus

    spec = CasSpec()
    pool = []
    for i in range(n_corpora):
        pool.append(build_corpus(
            spec, (AtomicCasSUT, RacyCasSUT), n=corpus_n, n_pids=N_PIDS,
            max_ops=N_OPS, seed_base=i * 10_000,
            seed_prefix=f"bench_serve_{i}"))
    return spec, pool


def _expected_names(spec, pool):
    """Host-oracle verdict names per corpus — the bit-identical
    reference every served response is checked against."""
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.serve.protocol import VERDICT_NAMES

    oracle = WingGongCPU(memo=True)
    return [[VERDICT_NAMES[int(v)]
             for v in oracle.check_histories(spec, hists)]
            for hists in pool]


def _trace_doc(hists) -> dict:
    from qsm_tpu.serve.protocol import history_to_rows

    return {"model": "cas",
            "histories": [history_to_rows(h) for h in hists]}


def bench_baseline_cli(hists) -> dict:
    """One-shot CLI per corpus: the cost every caller pays today."""
    with tempfile.NamedTemporaryFile(
            "w", suffix=".json", delete=False) as f:
        json.dump(_trace_doc(hists), f)
        path = f.name
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    secs, verdicts = [], None
    try:
        for _ in range(BASELINE_REPS):
            t0 = time.perf_counter()
            r = subprocess.run(
                [sys.executable, "-m", "qsm_tpu", "check", "--trace",
                 path, "--backend", "auto"],
                capture_output=True, text=True, cwd=REPO, env=env,
                timeout=SUBPROC_TIMEOUT_S)
            secs.append(time.perf_counter() - t0)
            line = (r.stdout or "").strip().splitlines()
            verdicts = json.loads(line[-1])["verdicts"] if line else None
    finally:
        os.unlink(path)
    med = float(np.median(secs))
    return {"reps": BASELINE_REPS, "seconds_per_corpus": round(med, 3),
            "all_seconds": [round(s, 3) for s in secs],
            "histories": len(hists),
            "histories_per_sec": round(len(hists) / med, 1),
            "verdicts": verdicts,
            "note": "includes interpreter startup + engine construction "
                    "per invocation — the cost the server amortizes"}


def _fresh_server(tmp_dir: str, cell: str, workers: int = 0):
    """One server per cell, with a PER-CELL cache bank: a shared bank
    would let an earlier cell's verdicts contaminate a later cell's
    throughput (and turn the cache row's 'cold' request into a hit)."""
    from qsm_tpu.serve.server import CheckServer

    srv = CheckServer(flush_s=0.005, max_lanes=64, workers=workers,
                      cache_path=os.path.join(tmp_dir, f"bank_{cell}.jsonl"))
    srv.start()
    srv.warm("cas")
    return srv


def _drive_clients(srv, n_clients: int, pool, expected, kill_at_s=None,
                   rounds: int = ROUNDS):
    """Closed-loop clients; every response verified against the oracle.
    ``kill_at_s`` SIGKILLs the BUSIEST live worker that long into the
    run (the kill_worker cell: the busiest worker is the one in-flight
    batches are most likely riding, so the kill exercises the shed /
    re-dispatch path, not a lucky idle process)."""
    from qsm_tpu.serve.client import CheckClient

    latencies: list = []
    errors: list = []
    wrong: list = []
    served = [0]  # corpora actually answered ok (throughput numerator)
    lock = threading.Lock()

    def drive(ci: int):
        try:
            with CheckClient(srv.address, timeout_s=120.0) as client:
                for r in range(rounds):
                    idx = (ci * rounds + r) % len(pool)
                    t0 = time.perf_counter()
                    res = client.check("cas", pool[idx])
                    dt = time.perf_counter() - t0
                    with lock:
                        latencies.append(dt)
                        if not res.get("ok"):
                            errors.append(res)
                        elif res["verdicts"] != expected[idx]:
                            wrong.append({"corpus": idx,
                                          "got": res["verdicts"]})
                        else:
                            served[0] += 1
        except Exception as e:  # noqa: BLE001 — a dead client is a row fact
            with lock:
                errors.append({"error": f"{type(e).__name__}: {e}"})

    threads = [threading.Thread(target=drive, args=(ci,))
               for ci in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    killed_pid = None
    if kill_at_s is not None:
        time.sleep(kill_at_s)
        rows = srv.pool.snapshot()["workers"]
        live = [w for w in rows if w["alive"] and w["pid"]]
        if live:
            busiest = max(live, key=lambda w: w["dispatches"])
            killed_pid = busiest["pid"]
            os.kill(killed_pid, signal.SIGKILL)
    for t in threads:
        t.join(SUBPROC_TIMEOUT_S)
    wall = time.perf_counter() - t0
    return wall, latencies, errors, wrong, killed_pid, served[0]


def bench_served(n_clients: int, pool, expected, tmp_dir: str,
                 workers: int = 0) -> dict:
    """One served cell: closed-loop concurrent clients, distinct
    corpora (no cache hits), optional worker pool."""
    cell = f"w{workers}_c{n_clients}" if workers else f"c{n_clients}"
    srv = _fresh_server(tmp_dir, cell, workers=workers)
    try:
        wall, latencies, errors, wrong, _, served = _drive_clients(
            srv, n_clients, pool, expected)
        stats = srv.stats()
    finally:
        srv.stop()
    corpus_n = len(pool[0])
    # throughput counts only corpora actually ANSWERED ok — a shed or
    # errored request must depress the rate, never pad it
    total = served * corpus_n
    lat = np.asarray(sorted(latencies)) if latencies else np.asarray([0.0])
    pool_snap = stats.get("pool") or {}
    return {
        "clients": n_clients, "workers": workers, "rounds": ROUNDS,
        "histories": total, "seconds": round(wall, 3),
        "histories_per_sec": round(total / max(wall, 1e-9), 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1000, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1000, 2),
        "batch_occupancy": stats["batcher"]["mean_occupancy"],
        "batches": stats["batcher"]["batches"],
        "cache_hit_rate": stats["cache"]["hit_rate"],
        "shed": stats["admission"]["shed_queue"]
        + stats["admission"]["shed_deadline"],
        "errors": len(errors),
        "wrong_verdicts": len(wrong),
        "worker_faults": stats.get("worker_faults", 0),
        "respawns": pool_snap.get("respawns", 0),
        "quarantines": pool_snap.get("quarantines", 0),
        "worker_dispatches": [w["dispatches"]
                              for w in pool_snap.get("workers", [])],
    }


def bench_kill_worker(pool, expected, tmp_dir: str) -> dict:
    """SIGKILL one of two workers mid-bench: the shed / re-dispatch /
    respawn path under real concurrent load.  Verdicts must stay
    bit-identical to the clean (oracle) reference — zero wrong, zero
    hung clients."""
    srv = _fresh_server(tmp_dir, "kill", workers=2)
    try:
        wall, latencies, errors, wrong, killed_pid, served = \
            _drive_clients(srv, 2, pool, expected, kill_at_s=KILL_AFTER_S,
                           rounds=KILL_ROUNDS)
        stats = srv.stats()
    finally:
        srv.stop()
    corpus_n = len(pool[0])
    total = served * corpus_n
    pool_snap = stats.get("pool") or {}
    return {
        "clients": 2, "workers": 2, "rounds": KILL_ROUNDS,
        "histories": total, "seconds": round(wall, 3),
        "histories_per_sec": round(total / max(wall, 1e-9), 1),
        "killed_pid": killed_pid,
        "kill_after_s": KILL_AFTER_S,
        "errors": len(errors),
        "wrong_verdicts": len(wrong),
        "verdicts_bit_identical": not wrong and not errors,
        "worker_faults": stats.get("worker_faults", 0),
        "kill_landed_mid_run": stats.get("worker_faults", 0) >= 1,
        "respawns": pool_snap.get("respawns", 0),
        "quarantines": pool_snap.get("quarantines", 0),
        "live_workers_at_end": pool_snap.get("live", 0),
    }


def bench_cache_hit(pool, tmp_dir: str) -> dict:
    """Duplicate submissions: the O(1) banked-verdict path."""
    from qsm_tpu.serve.client import CheckClient

    srv = _fresh_server(tmp_dir, "cache_hit")
    hists = pool[0]
    with CheckClient(srv.address, timeout_s=120.0) as client:
        t0 = time.perf_counter()
        cold = client.check("cas", hists)
        cold_s = time.perf_counter() - t0
        hit_secs = []
        all_cached = True
        for _ in range(CACHE_HIT_REPS):
            t0 = time.perf_counter()
            res = client.check("cas", hists)
            hit_secs.append(time.perf_counter() - t0)
            all_cached = all_cached and all(res.get("cached", []))
    stats = srv.stats()
    srv.stop()
    hit_p50 = float(np.percentile(np.asarray(hit_secs), 50))
    return {
        "histories": len(hists), "reps": CACHE_HIT_REPS,
        "cold_ms": round(cold_s * 1000, 2),
        "hit_p50_ms": round(hit_p50 * 1000, 2),
        "hit_p99_ms": round(
            float(np.percentile(np.asarray(hit_secs), 99)) * 1000, 2),
        "speedup_vs_cold": round(cold_s / max(hit_p50, 1e-9), 1),
        "all_cached": all_cached,
        "cache_hit_rate": stats["cache"]["hit_rate"],
        "verdicts_unchanged": cold.get("verdicts") == _names_for(hists),
    }


def _r07_single_process_c4(default: float = 121.9) -> float:
    """The committed r07 artifact's single-process serve_c4 rate (the
    path ISSUE 6's gate names).  Falls back to the recorded r07 number
    when the artifact is absent."""
    path = os.path.join(REPO, "BENCH_SERVE_r07.json")
    try:
        with open(path) as f:
            for ln in f:
                try:
                    row = json.loads(ln)
                except ValueError:
                    continue
                if row.get("cell") == "serve_c4":
                    return float(row["histories_per_sec"])
    except OSError:
        pass
    return default


def _names_for(hists):
    from qsm_tpu.models import CasSpec
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.serve.protocol import VERDICT_NAMES

    v = WingGongCPU(memo=True).check_histories(CasSpec(), hists)
    return [VERDICT_NAMES[int(x)] for x in v]


def run(corpus_n: int, tag: str, out_path: str | None,
        resume: bool) -> int:
    from qsm_tpu.resilience.checkpoint import CellJournal

    path = out_path or os.path.join(REPO, f"BENCH_SERVE_{tag}.json")
    header = {
        "artifact": "BENCH_SERVE",
        "device_fallback": None,  # host-side by design: the serving win
        # is amortization + coalescing + worker parallelism, measured
        # where it is honest
        "platform": "cpu",
        "model": "cas", "pids": N_PIDS, "ops": N_OPS,
        "corpus_n": corpus_n, "rounds": ROUNDS,
        "engine": "auto (warm host cpp->memo ladder)",
        "host_cores": os.cpu_count(),
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)
    todo = ["baseline_cli"] + [f"serve_c{c}" for c in CLIENT_COUNTS] \
        + [f"serve_w{w}_c{c}" for w in WORKER_COUNTS
           for c in CLIENT_COUNTS] \
        + ["kill_worker", "cache_hit"]
    need_pool = any(journal.complete(k) is None for k in todo)
    pool = expected = None
    if need_pool:
        spec, pool = _build_corpora(max(CLIENT_COUNTS) * ROUNDS, corpus_n)
        expected = _expected_names(spec, pool)

    with tempfile.TemporaryDirectory() as tmp_dir:
        if journal.complete("baseline_cli") is None:
            journal.emit("baseline_cli", bench_baseline_cli(pool[0]))
        for c in CLIENT_COUNTS:
            key = f"serve_c{c}"
            if journal.complete(key) is None:
                journal.emit(key, bench_served(c, pool, expected, tmp_dir))
        for w in WORKER_COUNTS:
            for c in CLIENT_COUNTS:
                key = f"serve_w{w}_c{c}"
                if journal.complete(key) is None:
                    journal.emit(key, bench_served(c, pool, expected,
                                                   tmp_dir, workers=w))
        if journal.complete("kill_worker") is None:
            journal.emit("kill_worker",
                         bench_kill_worker(pool, expected, tmp_dir))
        if journal.complete("cache_hit") is None:
            journal.emit("cache_hit", bench_cache_hit(pool, tmp_dir))

    base = journal.complete("baseline_cli")
    c4 = journal.complete("serve_c4")
    w4 = journal.complete("serve_w4_c4")
    kill = journal.complete("kill_worker")
    hit = journal.complete("cache_hit")
    serve_rows = [journal.complete(f"serve_c{c}") for c in CLIENT_COUNTS] \
        + [journal.complete(f"serve_w{w}_c{c}") for w in WORKER_COUNTS
           for c in CLIENT_COUNTS]
    wrong_total = sum(r.get("wrong_verdicts", 0) for r in serve_rows) \
        + kill.get("wrong_verdicts", 0)
    # THE acceptance comparison: the pooled path vs the single-process
    # path AS SHIPPED IN r07 (its committed artifact's serve_c4 row).
    # Diagnosing that wall was this round's first result: r07's
    # single-process 121.9 h/s was dominated by a full-bank rewrite +
    # fsync per micro-batch, which the append-only bank fixes for EVERY
    # path — so the same-run single-process row is itself far above the
    # r07 wall, and on this host (host_cores in the header) a pool
    # cannot 2x a baseline that already saturates a core of checking
    # when there are only two cores to spend.  Both ratios are
    # recorded; the r07 one is the gate, the same-run one is the
    # honesty row.
    r07_c4 = _r07_single_process_c4()
    pool_ratio_r07 = w4["histories_per_sec"] / max(r07_c4, 1e-9)
    pool_ratio_same_run = (w4["histories_per_sec"]
                           / max(c4["histories_per_sec"], 1e-9))
    summary = {
        "metric": "pooled_vs_single_process_served_throughput",
        "baseline_cli_hps": base["histories_per_sec"],
        "serve_c4_hps": c4["histories_per_sec"],
        "serve_w4_c4_hps": w4["histories_per_sec"],
        "r07_single_process_c4_hps": r07_c4,
        "ratio_w4_vs_r07_single_process_c4": round(pool_ratio_r07, 2),
        "gate_2x_at_4_workers": bool(pool_ratio_r07 >= 2.0),
        "ratio_w4_vs_same_run_single_process_c4":
            round(pool_ratio_same_run, 2),
        "single_process_wall_diagnosis": {
            "r07_hps": r07_c4,
            "r08_bank_fixed_hps": c4["histories_per_sec"],
            "cause": "full-bank rewrite + fsync per micro-batch under "
                     "the cache lock (now an O(batch) append log)",
        },
        "wrong_verdicts_total": wrong_total,
        "kill_worker_bit_identical": bool(
            kill.get("verdicts_bit_identical")),
        "kill_worker_faults": kill.get("worker_faults"),
        "kill_landed_mid_run": bool(kill.get("kill_landed_mid_run")),
        "best_hps": max(r["histories_per_sec"] for r in serve_rows),
        "cache_cold_ms": hit["cold_ms"],
        "cache_hit_p50_ms": hit["hit_p50_ms"],
        "cache_speedup": hit["speedup_vs_cold"],
        "resumed_cells": journal.resumed_cells,
        "artifact": os.path.basename(path),
    }
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    print(json.dumps(summary))
    ok = (summary["gate_2x_at_4_workers"]
          and summary["wrong_verdicts_total"] == 0
          and summary["kill_worker_bit_identical"])
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--corpus", type=int, default=32,
                    help="histories per request corpus")
    ap.add_argument("--tag", default="r08")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="adopt completed cells from a prior journal at "
                         "the output path (resilience/checkpoint.py)")
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform()
    try:
        return run(args.corpus, args.tag, args.out, args.resume)
    except Exception as e:  # noqa: BLE001 — diagnostic line, not a traceback
        print(json.dumps({
            "metric": "pooled_vs_single_process_served_throughput",
            "error": f"{type(e).__name__}: {e}"}))
        return 1


if __name__ == "__main__":
    sys.exit(main())
