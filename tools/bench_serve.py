"""Serving-plane bench — does warm + coalesced beat one-shot? (ISSUE 5)

Every one-shot ``qsm-tpu check`` invocation pays interpreter startup,
engine construction and compile-bucket warmup before the first verdict;
the check server (qsm_tpu/serve) pays them once and amortizes across
requests, coalescing concurrent clients into shared micro-batches.
This tool prices exactly that trade, all on the CPU platform (the
serving win is amortization + batching, not hardware):

* ``baseline_cli``   — one-shot CLI per corpus: N subprocess reps of
  ``qsm-tpu check --trace …`` over a fixed corpus; throughput =
  corpus / median wall (full cost INCLUDING startup — that is the
  point being amortized);
* ``serve_c{1,2,4,8}`` — closed-loop concurrent clients against one
  warm in-process server, each submitting DISTINCT corpora (zero cache
  hits: this measures checking, not memoization); throughput, p50/p99
  request latency, batch occupancy;
* ``cache_hit``      — duplicate-corpus submissions: the O(1) banked-
  verdict path, cold vs hit latency.

Win condition (ISSUE 5 acceptance): served throughput at ≥4 concurrent
clients ≥ 2× the one-shot baseline at unchanged verdicts, plus the
cache-hit row.  Output: a resumable ``CellJournal`` (header + one row
per cell; ``--resume`` re-runs zero completed cells) committed as
``BENCH_SERVE_<tag>.json``.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_PIDS = 4
N_OPS = 10
CLIENT_COUNTS = (1, 2, 4, 8)
ROUNDS = 6           # closed-loop rounds per client
BASELINE_REPS = 3
CACHE_HIT_REPS = 20
SUBPROC_TIMEOUT_S = 600.0


def _build_corpora(n_corpora: int, corpus_n: int):
    from qsm_tpu.models import AtomicCasSUT, CasSpec, RacyCasSUT
    from qsm_tpu.utils.corpus import build_corpus

    spec = CasSpec()
    pool = []
    for i in range(n_corpora):
        pool.append(build_corpus(
            spec, (AtomicCasSUT, RacyCasSUT), n=corpus_n, n_pids=N_PIDS,
            max_ops=N_OPS, seed_base=i * 10_000,
            seed_prefix=f"bench_serve_{i}"))
    return spec, pool


def _trace_doc(hists) -> dict:
    from qsm_tpu.serve.protocol import history_to_rows

    return {"model": "cas",
            "histories": [history_to_rows(h) for h in hists]}


def bench_baseline_cli(hists) -> dict:
    """One-shot CLI per corpus: the cost every caller pays today."""
    with tempfile.NamedTemporaryFile(
            "w", suffix=".json", delete=False) as f:
        json.dump(_trace_doc(hists), f)
        path = f.name
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    secs, verdicts = [], None
    try:
        for _ in range(BASELINE_REPS):
            t0 = time.perf_counter()
            r = subprocess.run(
                [sys.executable, "-m", "qsm_tpu", "check", "--trace",
                 path, "--backend", "auto"],
                capture_output=True, text=True, cwd=REPO, env=env,
                timeout=SUBPROC_TIMEOUT_S)
            secs.append(time.perf_counter() - t0)
            line = (r.stdout or "").strip().splitlines()
            verdicts = json.loads(line[-1])["verdicts"] if line else None
    finally:
        os.unlink(path)
    med = float(np.median(secs))
    return {"reps": BASELINE_REPS, "seconds_per_corpus": round(med, 3),
            "all_seconds": [round(s, 3) for s in secs],
            "histories": len(hists),
            "histories_per_sec": round(len(hists) / med, 1),
            "verdicts": verdicts,
            "note": "includes interpreter startup + engine construction "
                    "per invocation — the cost the server amortizes"}


def _fresh_server(tmp_dir: str, cell: str):
    """One server per cell, with a PER-CELL cache bank: a shared bank
    would let an earlier cell's verdicts contaminate a later cell's
    throughput (and turn the cache row's 'cold' request into a hit)."""
    from qsm_tpu.serve.server import CheckServer

    srv = CheckServer(flush_s=0.005, max_lanes=64,
                      cache_path=os.path.join(tmp_dir, f"bank_{cell}.jsonl"))
    srv.start()
    srv.warm("cas")
    return srv


def bench_served(n_clients: int, pool, tmp_dir: str) -> dict:
    """Closed-loop concurrent clients, distinct corpora (no cache hits):
    the coalesced-dispatch throughput row."""
    from qsm_tpu.serve.client import CheckClient

    srv = _fresh_server(tmp_dir, f"c{n_clients}")
    latencies: list = []
    verdicts_first: dict = {}
    errors: list = []
    lock = threading.Lock()

    def drive(ci: int):
        try:
            with CheckClient(srv.address, timeout_s=120.0) as client:
                for r in range(ROUNDS):
                    hists = pool[(ci * ROUNDS + r) % len(pool)]
                    t0 = time.perf_counter()
                    res = client.check("cas", hists)
                    dt = time.perf_counter() - t0
                    with lock:
                        latencies.append(dt)
                        if not res.get("ok"):
                            errors.append(res)
                        elif ci == 0 and r == 0:
                            verdicts_first["v"] = res["verdicts"]
                            verdicts_first["cached"] = res["cached"]
        except Exception as e:  # noqa: BLE001 — a dead client is a row fact
            with lock:
                errors.append({"error": f"{type(e).__name__}: {e}"})

    threads = [threading.Thread(target=drive, args=(ci,))
               for ci in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(SUBPROC_TIMEOUT_S)
    wall = time.perf_counter() - t0
    stats = srv.stats()
    srv.stop()
    corpus_n = len(pool[0])
    total = n_clients * ROUNDS * corpus_n
    lat = np.asarray(sorted(latencies)) if latencies else np.asarray([0.0])
    return {
        "clients": n_clients, "rounds": ROUNDS,
        "histories": total, "seconds": round(wall, 3),
        "histories_per_sec": round(total / wall, 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1000, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1000, 2),
        "batch_occupancy": stats["batcher"]["mean_occupancy"],
        "batches": stats["batcher"]["batches"],
        "cache_hit_rate": stats["cache"]["hit_rate"],
        "shed": stats["admission"]["shed_queue"]
        + stats["admission"]["shed_deadline"],
        "errors": len(errors),
        "verdicts_first_corpus": verdicts_first.get("v"),
    }


def bench_cache_hit(pool, tmp_dir: str) -> dict:
    """Duplicate submissions: the O(1) banked-verdict path."""
    from qsm_tpu.serve.client import CheckClient

    srv = _fresh_server(tmp_dir, "cache_hit")
    hists = pool[0]
    with CheckClient(srv.address, timeout_s=120.0) as client:
        t0 = time.perf_counter()
        cold = client.check("cas", hists)
        cold_s = time.perf_counter() - t0
        hit_secs = []
        all_cached = True
        for _ in range(CACHE_HIT_REPS):
            t0 = time.perf_counter()
            res = client.check("cas", hists)
            hit_secs.append(time.perf_counter() - t0)
            all_cached = all_cached and all(res.get("cached", []))
    stats = srv.stats()
    srv.stop()
    hit_p50 = float(np.percentile(np.asarray(hit_secs), 50))
    return {
        "histories": len(hists), "reps": CACHE_HIT_REPS,
        "cold_ms": round(cold_s * 1000, 2),
        "hit_p50_ms": round(hit_p50 * 1000, 2),
        "hit_p99_ms": round(
            float(np.percentile(np.asarray(hit_secs), 99)) * 1000, 2),
        "speedup_vs_cold": round(cold_s / max(hit_p50, 1e-9), 1),
        "all_cached": all_cached,
        "cache_hit_rate": stats["cache"]["hit_rate"],
        "verdicts_unchanged": cold.get("verdicts")
        == _names_for(hists),
    }


def _names_for(hists):
    from qsm_tpu.models import CasSpec
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.serve.protocol import VERDICT_NAMES

    v = WingGongCPU(memo=True).check_histories(CasSpec(), hists)
    return [VERDICT_NAMES[int(x)] for x in v]


def run(corpus_n: int, tag: str, out_path: str | None,
        resume: bool) -> int:
    from qsm_tpu.resilience.checkpoint import CellJournal

    path = out_path or os.path.join(REPO, f"BENCH_SERVE_{tag}.json")
    header = {
        "artifact": "BENCH_SERVE",
        "device_fallback": None,  # host-side by design: the serving win
        # is amortization + coalescing, measured where it is honest
        "platform": "cpu",
        "model": "cas", "pids": N_PIDS, "ops": N_OPS,
        "corpus_n": corpus_n, "rounds": ROUNDS,
        "engine": "auto (warm host cpp->memo ladder)",
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)
    todo = ["baseline_cli"] + [f"serve_c{c}" for c in CLIENT_COUNTS] \
        + ["cache_hit"]
    need_pool = any(journal.complete(k) is None for k in todo)
    pool = None
    if need_pool:
        _spec, pool = _build_corpora(max(CLIENT_COUNTS) * ROUNDS, corpus_n)

    with tempfile.TemporaryDirectory() as tmp_dir:
        if journal.complete("baseline_cli") is None:
            journal.emit("baseline_cli", bench_baseline_cli(pool[0]))
        for c in CLIENT_COUNTS:
            key = f"serve_c{c}"
            if journal.complete(key) is None:
                journal.emit(key, bench_served(c, pool, tmp_dir))
        if journal.complete("cache_hit") is None:
            journal.emit("cache_hit", bench_cache_hit(pool, tmp_dir))

    base = journal.complete("baseline_cli")
    c4 = journal.complete("serve_c4")
    hit = journal.complete("cache_hit")
    ratio = c4["histories_per_sec"] / max(base["histories_per_sec"], 1e-9)
    unchanged = (base.get("verdicts") is not None
                 and base["verdicts"] == c4.get("verdicts_first_corpus"))
    summary = {
        "metric": "served_vs_oneshot_cli_throughput",
        "baseline_hps": base["histories_per_sec"],
        "serve_c4_hps": c4["histories_per_sec"],
        "ratio_c4": round(ratio, 1),
        "gate_2x_at_4_clients": bool(ratio >= 2.0),
        "verdicts_unchanged": bool(unchanged),
        "cache_cold_ms": hit["cold_ms"],
        "cache_hit_p50_ms": hit["hit_p50_ms"],
        "cache_speedup": hit["speedup_vs_cold"],
        "resumed_cells": journal.resumed_cells,
        "artifact": os.path.basename(path),
    }
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    print(json.dumps(summary))
    return 0 if (summary["gate_2x_at_4_clients"]
                 and summary["verdicts_unchanged"]) else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--corpus", type=int, default=32,
                    help="histories per request corpus")
    ap.add_argument("--tag", default="r07")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="adopt completed cells from a prior journal at "
                         "the output path (resilience/checkpoint.py)")
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform()
    try:
        return run(args.corpus, args.tag, args.out, args.resume)
    except Exception as e:  # noqa: BLE001 — diagnostic line, not a traceback
        print(json.dumps({"metric": "served_vs_oneshot_cli_throughput",
                          "error": f"{type(e).__name__}: {e}"}))
        return 1


if __name__ == "__main__":
    sys.exit(main())
