"""Durable-session chaos soak — the ISSUE 18 acceptance artifact.

Runs the ``qsm-tpu soak`` rig (qsm_tpu/gen/soak.py) at gate scale —
≥1000 concurrent monitor sessions held open through (a) a rolling
SIGKILL restart of all three nodes, (b) a SIGKILL of the active router
with standby takeover off the shared lease + session-journal stores,
and (c) one node leave + one node join with replog handoff — plus a
PR 17 closed-loop fuzz pass against the surviving router, every flip
and close verdict re-proved by a fresh memo oracle.

Output: a resumable ``CellJournal`` (``--resume`` re-runs zero
completed cells) banked as BENCH_SESSIONS_<tag>.json; `make
soak-sessions` commits it and tools/bench_report.py folds it into
BENCH_REPORT.md.

    python tools/soak_sessions.py [--tag r18] [--sessions 1000]
        [--resume]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run(tag: str, out_path, sessions: int, workers: int,
        resume: bool) -> int:
    from qsm_tpu.gen.soak import soak_sessions
    from qsm_tpu.resilience.checkpoint import CellJournal

    path = out_path or os.path.join(REPO, f"BENCH_SESSIONS_{tag}.json")
    header = {
        "artifact": "BENCH_SESSIONS",
        "device_fallback": None,   # host-side by design: process
        # churn + durable restores, measured where they are honest
        "platform": "cpu",
        "schedule": "rolling node restart x3 + active-router SIGKILL "
                    "+ node leave/join + closed-loop fuzz",
        "sessions": sessions,
        "host_cores": os.cpu_count(),
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)
    if journal.complete("soak") is None:
        journal.emit("soak", soak_sessions(
            sessions=sessions, workers=workers,
            log=lambda m: print(m, file=sys.stderr)))
    rep = journal.complete("soak")
    summary = {
        "metric": "durable_session_chaos_soak",
        "sessions": rep["sessions"],
        "ops_per_session": rep["ops_per_session"],
        "truth_violations": rep["truth_violations"],
        "wrong_verdicts": rep["wrong_verdicts"],
        "wrong_verdicts_total": (rep["wrong_verdicts"]
                                 + rep["fuzz"]["wrong_verdicts_total"]),
        "flips_total": rep["flips_total"],
        "lost_flips": rep["lost_flips"],
        "unproved_flips": rep["unproved_flips"],
        "rolling_restart_s": rep["rolling_restart_s"],
        "rolling_restart_zero_lost": bool(
            rep["wrong_verdicts"] == 0 and rep["lost_flips"] == 0),
        "router_takeover": rep["router_takeover"],
        "router_takeover_s": rep["router_takeover_s"],
        "node_leave": rep["node_leave"],
        "node_join": rep["node_join"],
        "resume_restored_total": rep["resume_restored_total"],
        "prefix_hits_total": rep["prefix_hits_total"],
        "health_status": rep["health_status"],
        "health_exit_code": rep["exit_code"],
        "elapsed_s": rep["elapsed_s"],
        "gate_ok": rep["gate_ok"],
        "resumed_cells": journal.resumed_cells,
        "artifact": os.path.basename(path),
    }
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    print(json.dumps(summary))
    return 0 if summary["gate_ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tag", default="r18")
    ap.add_argument("--out", default=None)
    ap.add_argument("--sessions", type=int, default=1000,
                    help="concurrent sessions (the gate floor)")
    ap.add_argument("--workers", type=int, default=8,
                    help="client threads driving the session verbs")
    ap.add_argument("--resume", action="store_true",
                    help="adopt completed cells from a prior journal "
                         "at the output path (resilience/checkpoint)")
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform()
    try:
        return run(args.tag, args.out, args.sessions, args.workers,
                   args.resume)
    except Exception as e:  # noqa: BLE001 — diagnostic line, not a traceback
        print(json.dumps({"metric": "durable_session_chaos_soak",
                          "error": f"{type(e).__name__}: {e}"}))
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
