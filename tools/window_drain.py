"""Drain the persistent device-work queue into an open TPU window.

The watcher (tools/probe_watcher.py) calls this the moment a probe finds
the tunnel healed; ``make bench-devq`` calls it under a forced virtual
CPU mesh so the whole drain plane is benchable without hardware.  One
bounded run:

1. re-probe the default backend (bounded subprocess; the window may
   have closed between the watcher's probe and this launch) — unless
   ``--force-devices`` forces a virtual CPU mesh for the simulated path;
2. load the queue at ``--dir`` (qsm_tpu/devq), build the drain mesh from
   the devices the probe ACTUALLY found (mesh/topology.py
   ``mesh_from_devices`` — never a forced count; a 2-chip window must
   not be asked to lay out 8 shards), and spend the window on the
   queue in score order with the deadline threaded through every item;
3. every verdict is re-proved by a fresh host memo oracle before it is
   banked under the exact fingerprint the originating plane recorded
   (qsm_tpu/devq/drain.py — soundness does not ride on the device);
4. write the drain report to ``--out`` atomically and print it as ONE
   JSON line.  ``--resume`` replays the per-item CellJournal, so a
   window that closed (or a process that was SIGKILLed) mid-drain
   re-dispatches nothing it already proved: exactly-once banking.

Exit codes: 0 drained (or empty queue), 3 window closed at re-probe.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from qsm_tpu.resilience.checkpoint import atomic_write_json  # noqa: E402
from qsm_tpu.utils.device import (forced_host_device_env,  # noqa: E402
                                  probe_default_backend)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", required=True,
                    help="device-work queue directory (serve --devq-dir)")
    ap.add_argument("--out", default=None,
                    help="drain report artifact (atomic; default "
                         "DEVQ_DRAIN_WINDOW.json beside --dir)")
    ap.add_argument("--cache", default=None,
                    help="persistent verdict-cache bank to land proofs "
                         "in (serve --cache path); default: "
                         "<dir>/drain_cache.jsonl")
    ap.add_argument("--window-s", type=float, default=300.0,
                    help="wall-clock budget; every item's dispatch "
                         "deadline is bounded by what remains of it")
    ap.add_argument("--window-id", default="window",
                    help="journal identity: --resume with the SAME id "
                         "skips every item this id already proved")
    ap.add_argument("--resume", action="store_true",
                    help="replay the per-item journal; proved items "
                         "are banked again, never re-dispatched")
    ap.add_argument("--force-devices", type=int, default=None,
                    help="simulated window: re-exec under a forced "
                         "N-device virtual CPU mesh and skip the probe "
                         "(bench/CI path; see docs/WINDOWS.md)")
    ap.add_argument("--budget", type=int, default=2000,
                    help="per-lane node budget for the device backends")
    args = ap.parse_args()

    if args.force_devices and "--xla_force_host_platform_device_count" \
            not in os.environ.get("XLA_FLAGS", ""):
        # the flag must precede the first backend init: re-exec, don't set
        env = forced_host_device_env(args.force_devices)
        os.execve(sys.executable, [sys.executable, *sys.argv], env)

    if not args.force_devices:
        from qsm_tpu.resilience.policy import preset

        p = probe_default_backend(policy=preset("window-reprobe"))
        if not p.is_device:
            print(json.dumps({"error": "window closed at re-probe",
                              "detail": p.detail[:200]}), flush=True)
            return 3

    import jax

    from qsm_tpu.devq import DeviceWorkQueue, DrainScheduler
    from qsm_tpu.serve.cache import VerdictCache

    queue = DeviceWorkQueue(args.dir)
    out = args.out or os.path.join(args.dir, "..",
                                   "DEVQ_DRAIN_WINDOW.json")
    cache = VerdictCache(
        max_entries=65536,
        path=args.cache or os.path.join(args.dir, "drain_cache.jsonl"))
    sched = DrainScheduler(
        queue, cache=cache,
        devices=jax.devices(),  # the window's ACTUAL device set
        window_s=args.window_s,
        journal_path=os.path.join(args.dir, "drain_journal.jsonl"),
        window_id=args.window_id, resume=args.resume,
        budget=args.budget)
    report = sched.drain()
    cache.flush()
    atomic_write_json(out, report)
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
