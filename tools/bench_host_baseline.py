"""Frozen host-oracle denominators — measured once per round, committed.

VERDICT.md round 4, "Next round" #5: vs_baseline swung 694× → 512×
between the two banked windows purely from host-side re-measurement of
the naive oracle on a 14-18-history sample under unknown host load.  The
number the round is judged on must not inherit ~30% noise from its
denominator.  This tool measures the three host checkers ONCE on the
exact bench.py corpus (CAS 32 ops × 8 pids, seed_base 1000) with a
≥100-sample naive corpus, and writes ``BASELINE_HOST_rN.json``;
bench.py then reports ``vs_baseline_frozen`` / ``vs_best_host_frozen``
against this file alongside the live-remeasured ratios, flagging >20%
drift.

Host-only by design: run it while the chip is wedged (most of the round)
so the measurement happens on an otherwise idle machine.

Usage: python tools/bench_host_baseline.py [--out BASELINE_HOST_rN.json]
"""

from __future__ import annotations

import argparse
import datetime
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/root/repo/BASELINE_HOST_r05.json")
    ap.add_argument("--naive-sample", type=int, default=128,
                    help="histories for the naive-oracle rate (>=100 per "
                         "VERDICT r4 task #5)")
    ap.add_argument("--naive-timebox", type=float, default=1500.0)
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform()  # never touch the chip; host rates only

    from bench import build_corpus
    from qsm_tpu.models import CasSpec
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    spec = CasSpec()
    corpus = build_corpus(spec, 512)

    # --- naive oracle (the reference-faithful baseline denominator) ------
    oracle = WingGongCPU(node_budget=20_000_000)
    times = []
    t0 = time.perf_counter()
    for h in corpus[:args.naive_sample]:
        t1 = time.perf_counter()
        oracle.check_histories(spec, [h])
        times.append(time.perf_counter() - t1)
        if time.perf_counter() - t0 > args.naive_timebox:
            break
    naive_s = time.perf_counter() - t0
    naive_rate = len(times) / naive_s

    # --- memoised oracle (best pure-Python host checker) -----------------
    memo = WingGongCPU(memo=True)
    t0 = time.perf_counter()
    memo.check_histories(spec, corpus)
    memo_rate = len(corpus) / (time.perf_counter() - t0)

    # --- native C++ checker (best host checker overall) ------------------
    cpp_rate = None
    try:
        from qsm_tpu.native import CppOracle, native_available

        if native_available():
            cpp = CppOracle(spec)
            cpp.check_histories(spec, corpus)  # build + table compile
            t0 = time.perf_counter()
            cpp.check_histories(spec, corpus)
            if cpp.native_histories > 0:
                cpp_rate = round(len(corpus) / (time.perf_counter() - t0), 1)
    except Exception:  # noqa: BLE001 — optional fast path
        pass

    result = {
        "artifact": "host_baseline",
        "config": "cas 32ops x 8pids, seed_base 1000 (bench.py corpus)",
        "iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "cpu_oracle_rate": round(naive_rate, 4),
        "cpu_oracle_sample": len(times),
        "cpu_oracle_median_s": round(float(np.median(times)), 4),
        "cpu_oracle_p90_s": round(float(np.percentile(times, 90)), 4),
        "cpu_memo_oracle_rate": round(memo_rate, 1),
        "cpp_oracle_rate": cpp_rate,
        "corpus_unique": len(corpus),
    }
    from qsm_tpu.resilience.checkpoint import atomic_write_json

    atomic_write_json(args.out, result, indent=1)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
