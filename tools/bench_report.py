"""Bench trend report — every committed BENCH artifact, one table.

Fifteen-plus ``BENCH_*.json`` artifacts at the repo root carry the
whole performance trajectory of this codebase — and no human can see
it, because every round used the schema its bench needed: some files
are single JSON documents, most are ``CellJournal`` JSONL (header +
one row per cell), headline numbers live under different keys
(``histories_per_sec``, ``reduction_vs_hand``, ``wall_ratio``, gate
summaries).  This tool folds all of them into ONE per-round trend
table, without pretending they are comparable beyond what they say:

* each artifact contributes its round (parsed from the ``_rNN`` file
  tag), its artifact name, its cell count, and the HEADLINE FACTS it
  actually contains (a priority-ordered key sweep over every row —
  throughput rates, gate ratios, reduction factors);
* rates are never cross-normalized: a ``serve_c4`` h/s and a device
  ``h/s`` remain labeled by their cell of origin;
* the lint-gate artifact (``LINT_rNN.json``) rides along too: its
  nested ``protocol`` summary block is flattened into ``protocol_*``
  facts, so the wire-contract trend (op count, handler coverage,
  idempotent-set size) is trendable next to the perf rounds.

Output: ``BENCH_REPORT.md`` (the human table, newest round first) and
``BENCH_REPORT.json`` (the structured form), both written atomically
(``make bench-report``).  Deterministic: no timestamps, stable sort —
a re-run over unchanged artifacts is byte-identical, so the committed
report never churns.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# priority-ordered headline keys: the first few found (across an
# artifact's rows) become its trend-table facts.  Rates before ratios
# before counts; gate verdicts always included.
_HEADLINE_KEYS = (
    # the MESH artifact's lane-axis trend: best lanes/sec across mesh
    # widths and the 8-vs-1-width rate ratio (forced host devices on a
    # 1-core host partition rather than accelerate; the honest gate is
    # no-collapse, not speedup — docs/MESH.md)
    "lanes_per_sec", "ratio_d8_vs_d1",
    "histories_per_sec", "h_per_s", "reduction_vs_hand",
    "engine_call_ratio", "call_ratio_batched", "wall_ratio",
    "nodes_ratio", "ratio_n3_vs_n1", "speedup", "ratio", "mean_ratio",
    "tracing_off_overhead_pct", "tracing_on_overhead_pct",
    # the GEN artifact's steering trend: best steered/unsteered flip
    # ratio and how many families cleared the ≥3× gate
    "max_flip_ratio", "families_passing",
    # the SESSIONS artifact's durability trend: how many session
    # resumes the chaos schedule forced, how many rode banked decided
    # prefixes, and the standby-takeover latency
    "resume_restored_total", "prefix_hits_total", "router_takeover_s",
    # the DEVQ artifact's window-arbitrage trend: fraction of the
    # simulated window spent in engine dispatch (the serve `health`
    # SLO) and how much banked work the window paid down
    "window_utilization", "items_drained", "host_lanes_per_sec",
    "value", "p50_ms", "p99_ms",
    # the LINT artifact's wire-contract trend (flattened from its
    # nested ``protocol`` block): op vocabulary size, handler/caller
    # coverage, declared-idempotent count
    "protocol_ops", "protocol_handled_ops", "protocol_called_ops",
    "protocol_idempotent_ops", "protocol_send_sites",
)
_GATE_KEYS = ("gate_ok", "all_verified", "wrong_verdicts",
              "wrong_verdicts_total", "rolling_restart_zero_lost")
_MAX_FACTS = 5


def _parse_file(path: str) -> Tuple[Optional[dict], List[dict]]:
    """(header, rows) for either artifact shape: a single JSON document
    becomes one row with no header; CellJournal JSONL splits into its
    header line + cell rows.  A garbled trailing line is dropped (the
    journals' own tolerance rule)."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None, []
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            proto = doc.get("protocol")
            if isinstance(proto, dict):
                # lift the lint document's nested contract summary
                # into scalar ``protocol_*`` row keys the headline
                # sweep can see
                for k, v in proto.items():
                    if isinstance(v, (int, float)):
                        doc.setdefault(f"protocol_{k}", v)
            return None, [doc]
        return None, []
    except ValueError:
        pass
    rows: List[dict] = []
    for ln in text.splitlines():
        if not ln.strip():
            continue
        try:
            rows.append(json.loads(ln))
        except ValueError:
            continue
    if rows and "artifact" in rows[0] and "cell" not in rows[0]:
        return rows[0], rows[1:]
    return None, rows


def _round_of(path: str) -> Optional[int]:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else None


def _facts(rows: List[dict]) -> List[str]:
    """The artifact's headline facts: for each priority key, the cell
    that carries it (first occurrence wins — journals emit their
    headline cells first), rendered ``cell.key=value``."""
    facts: List[str] = []
    seen_keys = set()
    for key in _HEADLINE_KEYS:
        if len(facts) >= _MAX_FACTS:
            break
        for row in rows:
            v = row.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            cell = row.get("cell") or row.get("metric") or ""
            label = f"{cell}.{key}" if cell else key
            facts.append(f"{label}={v}")
            seen_keys.add(key)
            break
    gates: List[str] = []
    for key in _GATE_KEYS:
        for row in rows:
            if key in row:
                gates.append(f"{key}={row[key]}")
                break
    return facts + gates[:2]


def build_report(paths: List[str]) -> List[dict]:
    entries = []
    for path in sorted(paths):
        header, rows = _parse_file(path)
        all_rows = ([header] if header else []) + rows
        entries.append({
            "file": os.path.basename(path),
            "round": _round_of(path),
            "artifact": (header or {}).get(
                "artifact", os.path.basename(path).split(".")[0]),
            "platform": (header or {}).get("platform"),
            "cells": len(rows),
            "facts": _facts(all_rows),
        })
    # newest round first; unknown rounds (no _rNN tag) sink to the end
    entries.sort(key=lambda e: (-(e["round"] if e["round"] is not None
                                  else -1), e["file"]))
    return entries


def render_markdown(entries: List[dict]) -> str:
    lines = [
        "# Bench trend report",
        "",
        "Generated by `tools/bench_report.py` (`make bench-report`) "
        "from the committed `BENCH_*.json` artifacts.  Facts are "
        "quoted from each artifact's own cells — rates from different "
        "benches are NOT cross-comparable; the cell label says what "
        "was measured.",
        "",
        "| Round | Artifact | File | Cells | Headline facts |",
        "|---|---|---|---|---|",
    ]
    for e in entries:
        rnd = f"r{e['round']:02d}" if e["round"] is not None else "—"
        facts = "<br>".join(e["facts"]) if e["facts"] else "—"
        lines.append(f"| {rnd} | {e['artifact']} | `{e['file']}` | "
                     f"{e['cells']} | {facts} |")
    lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--glob", action="append", default=None,
                    help="artifact glob, repeatable (default: "
                         "repo-root BENCH_*.json + LINT_*.json)")
    ap.add_argument("--md", default=os.path.join(REPO, "BENCH_REPORT.md"))
    ap.add_argument("--json", dest="json_out",
                    default=os.path.join(REPO, "BENCH_REPORT.json"))
    args = ap.parse_args(argv)
    globs = args.glob or [os.path.join(REPO, "BENCH_*.json"),
                          os.path.join(REPO, "LINT_*.json")]
    paths = sorted({p for g in globs for p in glob.glob(g)
                    if not p.endswith(("BENCH_REPORT.json",))})
    entries = build_report(paths)
    from qsm_tpu.resilience.checkpoint import (atomic_write_json,
                                               atomic_write_text)

    atomic_write_text(args.md, render_markdown(entries))
    atomic_write_json(args.json_out,
                      {"artifact": "BENCH_REPORT", "version": 1,
                       "source_globs": sorted(os.path.basename(g)
                                              for g in globs),
                       "artifacts": entries}, indent=1)
    print(f"{len(entries)} artifact(s) -> {args.md} + {args.json_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
