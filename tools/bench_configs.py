"""Per-config benchmark artifact — one JSON line per model config
(VERDICT.md round 2, "Next round" #7; BASELINE.json:6-12, plus the extra
set/stack families).

For each model config at full default size, measures histories/sec for
the memoised host oracle and for the config's natural device path
(JaxTPU for scalar-state specs; SegDC(JaxTPU) for queue-48;
PComp(JaxTPU) for multi-key KV-64), with verdict-parity accounting.

Probe-guarded exactly like bench.py: real chip when the tunnel answers,
honestly-labelled CPU fallback otherwise.  Usage:

    python tools/bench_configs.py [--force-cpu] [--out BENCH_CONFIGS_rN.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def _backends_for(model: str, spec, on_tpu: bool):
    from qsm_tpu.ops.jax_kernel import JaxTPU
    from qsm_tpu.ops.pcomp import PComp
    from qsm_tpu.ops.segdc import SegDC
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU

    # host fallback pays vmapped-step lockstep iterations for vector-state
    # specs (no scalar step table) — cap their budgets so the artifact run
    # stays bounded; the real chip gets the full defaults
    vec_kw = (dict() if on_tpu
              else dict(budget=2_000, mid_budget=10_000,
                        rescue_budget=100_000))
    from qsm_tpu.native import CppOracle, native_available

    if model == "kv":
        # the UNdecomposed memo oracle on 16-pid × 64-op multi-key
        # histories is exponential in practice (it ran >5 min on this
        # corpus) — per-key P-compositionality is the only sane host
        # checker at this size, so that is the honest host comparator
        out = {
            "memo": PComp(spec),  # pcomp(memo)
            "device": PComp(spec, make_inner=lambda s: JaxTPU(s, **vec_kw)),
        }
        if native_available():
            out["cpp"] = PComp(spec, make_inner=lambda s: CppOracle(s))
        return out
    out = {"memo": WingGongCPU(memo=True)}
    if model == "queue":
        from qsm_tpu.ops.router import AutoDevice

        out["device"] = SegDC(spec,
                              make_inner=lambda s: JaxTPU(s, **vec_kw))
        # the router (ops/router.py) picks segdc/plain per history; its
        # row shows what `--backend auto-tpu` actually delivers
        out["auto_device"] = AutoDevice(spec, **vec_kw)
    else:
        # stack included: its state scalarizes (ops/scalarize.py), so it
        # rides the table-gather path at the same default budgets as the
        # scalar configs
        out["device"] = JaxTPU(spec)
    if native_available():
        out["cpp"] = CppOracle(spec)
    return out


def bench_config(model: str, on_tpu: bool, n_corpus: int) -> dict:
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.utils.corpus import build_corpus

    entry = MODELS[model]
    spec = entry.make_spec()
    suts = (entry.impls["atomic"], entry.impls["racy"])
    t0 = time.perf_counter()
    corpus = build_corpus(spec, suts, n=n_corpus,
                          n_pids=entry.default_pids,
                          max_ops=entry.default_ops,
                          seed_base=1000, seed_prefix="bench")
    gen_s = time.perf_counter() - t0

    rec = {"model": model, "pids": entry.default_pids,
           "ops": entry.default_ops, "corpus": len(corpus),
           "corpus_gen_s": round(gen_s, 1), "backends": {}}
    verdicts = {}
    for bname, backend in _backends_for(model, spec, on_tpu).items():
        if "device" in bname:
            # warmup = compile; host oracles have nothing to warm (the
            # memo cache is per-history, per-call) and the double pass
            # would just double the artifact's wall-clock
            backend.check_histories(spec, corpus)
        t0 = time.perf_counter()
        v = backend.check_histories(spec, corpus)
        dt = time.perf_counter() - t0
        verdicts[bname] = np.asarray(v)
        undecided = int((v == 2).sum())
        rec["backends"][bname] = {
            "name": backend.name,
            "histories_per_sec": round((len(corpus) - undecided)
                                       / max(dt, 1e-9), 1),
            "seconds": round(dt, 3),
            "undecided": undecided,
        }
    # wrong verdicts: both sides decided, disagreed (BUDGET is honest)
    m, d = verdicts["memo"], verdicts["device"]
    both = (m != 2) & (d != 2)
    rec["wrong_verdicts"] = int(((m != d) & both).sum())
    rec["violations_in_corpus"] = int((m == 0).sum())
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/root/repo/BENCH_CONFIGS_r05.json")
    ap.add_argument("--force-cpu", action="store_true")
    ap.add_argument("--probe-timeout", type=float, default=None,
                    help="override the probe preset's per-attempt bound "
                         "(resilience/policy.py)")
    ap.add_argument("--corpus", type=int, default=None,
                    help="override corpus size (default 128 cpu / 256 tpu)")
    ap.add_argument("--resume", action="store_true",
                    help="adopt completed per-model rows from an "
                         "existing --out journal instead of re-measuring")
    args = ap.parse_args(argv)

    from qsm_tpu.resilience.checkpoint import CellJournal
    from qsm_tpu.utils.device import probe_or_force_cpu

    on_tpu, _detail, header = probe_or_force_cpu(args.force_cpu,
                                                 args.probe_timeout)
    n_corpus = args.corpus or (256 if on_tpu else 128)
    # per-model journal (resilience/checkpoint.py): rows land atomically
    # so a window that closes mid-matrix still banks the configs already
    # measured, and --resume re-runs zero of them
    journal = CellJournal(args.out, {"artifact": "bench_configs",
                                     **header}, resume=args.resume)
    for model in ("register", "ticket", "cas", "queue", "kv",
                  "set", "stack"):
        rec = journal.complete(model)
        if rec is None:
            rec = journal.emit(model, bench_config(model, on_tpu,
                                                   n_corpus))
        print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
