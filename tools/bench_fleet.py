"""Fleet-tier soak — survival under node kill/wedge/partition/restart.

ISSUE 12's tier is judged on *survival*, not just speed: verdicts must
stay correct and available while fleet nodes crash, wedge, partition
and restart.  This harness replays ONE recorded traffic mix — plain
``check`` corpora (cas), pcomp-split corpora (kv, multireg — per-key
sub-lanes on the nodes), and ``shrink`` requests on failing histories
— against 1/2/3-node fleets behind a :class:`~qsm_tpu.fleet.router.
FleetRouter`, with chaos cells driven through the faults plane and
plain POSIX signals:

* ``fleet_n{1,2,3}`` — the healthy scaling sweep at the same client
  load; EVERY response oracle-verified (``wrong_verdicts`` required 0);
* ``kill_node``      — SIGKILL the busiest node MID-soak: undecided
  lanes re-dispatch to survivors, the router's flight dump must name
  the doomed dispatches' trace ids, and the span log must show the
  ``route.hop`` from the dead node to the surviving one (the
  ``qsm-tpu trace <id>`` acceptance, checked from the same log);
* ``wedge_node``     — SIGSTOP a node (alive, silent — the wedge the
  worker pool knows one level down): bounded link timeouts shed it,
  lanes re-dispatch, zero wrong answers;
* ``partition``      — ``QSM_TPU_FAULTS=partition:node:p`` drops a
  random fraction of router→node exchanges both directions (seeded,
  replayable): the exclude-and-re-dispatch ladder absorbs every drop;
* ``rolling_restart``— restart every node IN SEQUENCE (SIGKILL +
  respawn on the same replog dir/address), anti-entropy catch-up
  between steps, then the whole recorded mix re-submitted: zero wrong
  verdicts AND zero lost banked verdicts (every check lane answers
  from the bank — ``cached`` all true) and shrink results bit-equal.

Scaling honesty (the r08 precedent): the ≥2× three-node gate needs
``host_cores >= nodes + 1`` to be physically expressible — three node
processes cannot out-check one on a single core.  The summary stamps
``host_cores``, the measured ratio, and ``gate_waived_insufficient_
cores`` when the machine cannot express the gate; correctness gates
(zero wrong, zero lost, chaos-cell survival) are NEVER waived.

Output: a resumable ``CellJournal`` (``--resume`` re-runs zero
completed cells) committed as ``BENCH_FLEET_<tag>.json``
(``make bench-fleet``; probe_watcher archives it off-window).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_CLIENTS = 4
ROUNDS = 2            # mix replays per client in a scaling cell
CHAOS_ROUNDS = 4      # longer soak so mid-run faults land mid-run
SUBPROC_TIMEOUT_S = 600.0
KILL_AFTER_S = 0.3   # early: later soak rounds are bank hits and fly
LINK_TIMEOUT_S = 3.0  # router→node bound for the chaos cells


# ---------------------------------------------------------------------------
# the recorded traffic mix
# ---------------------------------------------------------------------------

def _build_mix():
    """The recorded mix: (kind, model, payload) requests — cas check
    corpora, pcomp-splitting kv/multireg corpora, and shrink requests
    on failing cas histories — plus the oracle reference for each."""
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.serve.protocol import VERDICT_NAMES, history_to_rows
    from qsm_tpu.utils.corpus import build_corpus

    oracle = WingGongCPU(memo=True)
    mix = []

    def add_check(model, n_corpora, corpus_n, n_pids, max_ops, seed0):
        entry = MODELS[model]
        spec = entry.make_spec()
        for i in range(n_corpora):
            hists = build_corpus(
                spec, (entry.impls["atomic"], entry.impls["racy"]),
                n=corpus_n, n_pids=n_pids, max_ops=max_ops,
                seed_base=seed0 + i * 10_000,
                seed_prefix=f"bench_fleet_{model}_{i}")
            expected = [VERDICT_NAMES[int(v)]
                        for v in oracle.check_histories(spec, hists)]
            mix.append({"kind": "check", "model": model,
                        "rows": [history_to_rows(h) for h in hists],
                        "expected": expected})

    add_check("cas", 6, 8, 4, 10, 0)
    add_check("kv", 2, 4, 8, 24, 500_000)       # pcomp-split lanes
    add_check("multireg", 2, 4, 8, 16, 900_000)  # second split family
    # failing cas histories for the shrink lanes
    entry = MODELS["cas"]
    spec = entry.make_spec()
    pool = build_corpus(
        spec, (entry.impls["atomic"], entry.impls["racy"]),
        n=24, n_pids=6, max_ops=16, seed_base=0,
        seed_prefix="bench_fleet_shrink")
    failing = [h for h in pool
               if int(oracle.check_histories(spec, [h])[0]) == 0]
    for h in failing[:2]:
        mix.append({"kind": "shrink", "model": "cas",
                    "rows": history_to_rows(h), "expected": None})
    return mix


# ---------------------------------------------------------------------------
# node processes (UNIX sockets: a restarted node keeps its address)
# ---------------------------------------------------------------------------

class Node:
    def __init__(self, nid: str, run_dir: str, seal_rows: int = 64):
        self.nid = nid
        self.unix_path = os.path.join(run_dir, f"{nid}.sock")
        self.replog_dir = os.path.join(run_dir, f"replog_{nid}")
        self.seal_rows = seal_rows
        self.proc = None

    def spawn(self) -> "Node":
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # chaos rules target the ROUTER's node site; a spawned node
        # must not inherit them (kill:serve etc. would be a different
        # cell's drill)
        env.pop("QSM_TPU_FAULTS", None)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "qsm_tpu", "serve",
             "--unix", self.unix_path, "--node-id", self.nid,
             "--replog-dir", self.replog_dir,
             "--replog-seal-rows", str(self.seal_rows),
             # warm every mix model (register = the projected spec kv/
             # multireg sub-lanes ride): a cold engine build under
             # full 1-core load can outlast a chaos-tuned link bound
             # and read as a wedge
             "--warm", "cas,kv,multireg,register"],
            stdout=subprocess.PIPE, text=True, cwd=REPO, env=env)
        line = self.proc.stdout.readline()
        doc = json.loads(line)
        assert doc.get("serving") == self.unix_path, doc
        return self

    def sigkill(self) -> None:
        self.proc.kill()
        self.proc.wait(timeout=10.0)

    def sigstop(self) -> None:
        os.kill(self.proc.pid, signal.SIGSTOP)

    def sigcont(self) -> None:
        try:
            os.kill(self.proc.pid, signal.SIGCONT)
        except OSError:
            pass

    def stop(self) -> None:
        if self.proc is None or self.proc.poll() is not None:
            return
        try:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5.0)
        except OSError:
            pass


def _fleet(n_nodes: int, run_dir: str, cell: str, seal_rows: int = 64,
           trace: bool = False, link_timeout_s: float = 10.0):
    """Spawn N nodes + an in-process router for one cell.  Fresh
    per-cell replog dirs: an earlier cell's banked verdicts must not
    contaminate a later cell's throughput.  ``link_timeout_s`` stays
    generous except in the chaos cells (LINK_TIMEOUT_S): on a shared
    single core a loaded-but-healthy node can miss a wedge-tuned
    bound, and a timeout is indistinguishable from a wedge at the
    link layer."""
    from qsm_tpu.fleet.router import FleetRouter
    from qsm_tpu.resilience.policy import preset

    cell_dir = os.path.join(run_dir, cell)
    os.makedirs(cell_dir, exist_ok=True)
    nodes = [Node(f"n{i}", cell_dir, seal_rows=seal_rows).spawn()
             for i in range(n_nodes)]
    kw = {}
    if trace:
        kw["trace_log"] = os.path.join(cell_dir, "router_trace.jsonl")
        kw["flight_dir"] = os.path.join(cell_dir, "flight")
    router = FleetRouter(
        [(n.nid, n.unix_path) for n in nodes],
        policy=preset("fleet-route").with_(timeout_s=link_timeout_s),
        probe_policy=preset("fleet-probe").with_(timeout_s=1.0),
        heartbeat_s=0.3, anti_entropy_s=0.0, **kw).start()
    return router, nodes


def _busiest_node(router, mix) -> str:
    """The node owning the most of the mix's whole-history keys — the
    one in-flight lanes are most likely riding when the chaos lands."""
    from qsm_tpu.serve.cache import fingerprint_key
    from qsm_tpu.serve.protocol import rows_to_history

    owned: dict = {}
    allowed = set(router.membership.all_ids())
    for req in mix:
        spec = router._spec_for(req["model"], {})
        hists = ([rows_to_history(req["rows"])]
                 if req["kind"] == "shrink"
                 else [rows_to_history(r) for r in req["rows"]])
        for h in hists:
            nid = router.membership.ring.node_for(
                fingerprint_key(spec, h), allowed)
            owned[nid] = owned.get(nid, 0) + 1
    return max(owned, key=owned.get)


# ---------------------------------------------------------------------------
# the client drive
# ---------------------------------------------------------------------------

def _drive(router, mix, n_clients: int, rounds: int,
           chaos=None, chaos_at_s: float = None):
    """Closed-loop clients replaying the recorded mix; every check
    response verified against the oracle reference on receipt.
    ``chaos`` is a zero-arg callable fired ``chaos_at_s`` into the
    drive (SIGKILL/SIGSTOP/...)."""
    from qsm_tpu.serve.client import CheckClient

    lock = threading.Lock()
    latencies, errors, wrong = [], [], []
    served = [0]
    shrink_results = {}

    def drive(ci: int):
        try:
            with CheckClient(router.address, timeout_s=120.0) as client:
                for _r in range(rounds):
                    # each client starts at its own offset so the mix
                    # interleaves across connections instead of
                    # marching in lockstep
                    for k in [(j + ci) % len(mix)
                              for j in range(len(mix))]:
                        req = mix[k]
                        t0 = time.perf_counter()
                        if req["kind"] == "check":
                            res = client.check(req["model"], req["rows"])
                        else:
                            res = client.shrink(req["model"], req["rows"])
                        dt = time.perf_counter() - t0
                        with lock:
                            latencies.append(dt)
                            if not res.get("ok"):
                                errors.append(res)
                            elif (req["kind"] == "check"
                                  and res["verdicts"] != req["expected"]):
                                wrong.append({"mix": k,
                                              "got": res["verdicts"]})
                            elif (req["kind"] == "shrink"
                                  and res.get("verdict") != "VIOLATION"):
                                wrong.append({"mix": k, "shrink": res})
                            else:
                                served[0] += (len(req["rows"])
                                              if req["kind"] == "check"
                                              else 1)
                                if req["kind"] == "shrink":
                                    shrink_results[k] = res["history"]
        except Exception as e:  # noqa: BLE001 — a dead client is a row fact
            with lock:
                errors.append({"error": f"{type(e).__name__}: {e}"})

    threads = [threading.Thread(target=drive, args=(ci,))
               for ci in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    if chaos is not None:
        time.sleep(chaos_at_s or KILL_AFTER_S)
        chaos()
    for t in threads:
        t.join(SUBPROC_TIMEOUT_S)
    wall = time.perf_counter() - t0
    return wall, latencies, errors, wrong, served[0], shrink_results


def _row(cell, n_nodes, wall, latencies, errors, wrong, served,
         router_stats) -> dict:
    lat = np.asarray(sorted(latencies)) if latencies else np.asarray([0.0])
    mem = router_stats.get("membership", {})
    return {
        "nodes": n_nodes, "clients": N_CLIENTS,
        "histories": served, "seconds": round(wall, 3),
        "histories_per_sec": round(served / max(wall, 1e-9), 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1000, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1000, 2),
        "errors": len(errors),
        "wrong_verdicts": len(wrong),
        "node_faults": router_stats.get("node_faults", 0),
        "node_sheds": router_stats.get("node_sheds", 0),
        "redispatches": router_stats.get("redispatches", 0),
        "ladder_lanes": router_stats.get("ladder_lanes", 0),
        "quarantines": mem.get("quarantines", 0),
        "readmissions": mem.get("readmissions", 0),
    }


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------

def bench_scaling(n_nodes: int, mix, run_dir: str) -> dict:
    router, nodes = _fleet(n_nodes, run_dir, f"n{n_nodes}")
    try:
        wall, lat, errors, wrong, served, _ = _drive(
            router, mix, N_CLIENTS, ROUNDS)
        stats = router.stats()
    finally:
        router.stop()
        for n in nodes:
            n.stop()
    return _row(f"fleet_n{n_nodes}", n_nodes, wall, lat, errors, wrong,
                served, stats)


def bench_kill_node(mix, run_dir: str) -> dict:
    """SIGKILL the busiest node mid-soak; afterwards audit the three
    acceptance artifacts: correct verdicts, a flight dump naming the
    doomed trace ids, and the route.hop span from the dead node."""
    from qsm_tpu.obs import load_dump, load_events, recent_events

    router, nodes = _fleet(3, run_dir, "kill", trace=True,
                           link_timeout_s=LINK_TIMEOUT_S)
    victim = _busiest_node(router, mix)
    node_by_id = {n.nid: n for n in nodes}
    try:
        wall, lat, errors, wrong, served, _ = _drive(
            router, mix, N_CLIENTS, CHAOS_ROUNDS,
            chaos=lambda: node_by_id[victim].sigkill(),
            chaos_at_s=KILL_AFTER_S)
        stats = router.stats()
        flight_dir = os.path.join(run_dir, "kill", "flight")
        trace_log = os.path.join(run_dir, "kill", "router_trace.jsonl")
        router.obs.tracer.close()
        doomed = []
        dump_path = None
        for name in sorted(os.listdir(flight_dir)
                           if os.path.isdir(flight_dir) else []):
            if "node_death" not in name and "partition" not in name:
                continue
            dump = load_dump(os.path.join(flight_dir, name))
            for ev in recent_events(dump, "node"):
                at = ev.get("attrs") or {}
                if ev.get("name") == "node.shed" \
                        and at.get("node") == victim:
                    doomed.extend(at.get("traces") or [])
                    dump_path = name
        hop_seen = False
        for trace_id in doomed[:8]:
            for ev in load_events(trace_log, trace_id=trace_id):
                at = ev.get("attrs") or {}
                if ev.get("name") == "route.hop" \
                        and at.get("hop_from") == victim:
                    hop_seen = True
    finally:
        router.stop()
        for n in nodes:
            n.stop()
    row = _row("kill_node", 3, wall, lat, errors, wrong, served, stats)
    row.update({
        "killed_node": victim,
        "kill_after_s": KILL_AFTER_S,
        "kill_landed_mid_run": stats.get("node_faults", 0) >= 1,
        "flight_dump": dump_path,
        "flight_dump_names_doomed_traces": bool(doomed),
        "doomed_traces": doomed[:4],
        "trace_shows_hop_off_dead_node": hop_seen,
        "verdicts_bit_identical": not wrong and not errors,
    })
    return row


def bench_wedge_node(mix, run_dir: str) -> dict:
    """SIGSTOP (wedge: alive, holds its sockets, answers nothing) the
    busiest node mid-soak — the failure bounded link timeouts exist
    for."""
    router, nodes = _fleet(3, run_dir, "wedge",
                           link_timeout_s=LINK_TIMEOUT_S)
    victim = _busiest_node(router, mix)
    node_by_id = {n.nid: n for n in nodes}
    try:
        wall, lat, errors, wrong, served, _ = _drive(
            router, mix, N_CLIENTS, CHAOS_ROUNDS,
            chaos=lambda: node_by_id[victim].sigstop(),
            chaos_at_s=KILL_AFTER_S)
        stats = router.stats()
    finally:
        node_by_id[victim].sigcont()
        router.stop()
        for n in nodes:
            n.stop()
    row = _row("wedge_node", 3, wall, lat, errors, wrong, served, stats)
    row.update({
        "wedged_node": victim,
        "wedge_detected": stats.get("node_faults", 0) >= 1,
        "verdicts_bit_identical": not wrong and not errors,
    })
    return row


def bench_partition(mix, run_dir: str) -> dict:
    """Seeded random partition: a fraction of router→node exchanges
    drop frames both directions (``partition:node:p`` — the faults
    plane's grammar, replayable by seed)."""
    os.environ["QSM_TPU_FAULTS"] = "partition:node:0.2"
    os.environ["QSM_TPU_FAULTS_SEED"] = "12"
    try:
        router, nodes = _fleet(3, run_dir, "partition",
                               link_timeout_s=LINK_TIMEOUT_S)
        try:
            wall, lat, errors, wrong, served, _ = _drive(
                router, mix, N_CLIENTS, CHAOS_ROUNDS)
            stats = router.stats()
        finally:
            router.stop()
            for n in nodes:
                n.stop()
    finally:
        os.environ.pop("QSM_TPU_FAULTS", None)
        os.environ.pop("QSM_TPU_FAULTS_SEED", None)
    row = _row("partition", 3, wall, lat, errors, wrong, served, stats)
    row.update({
        "partition_p": 0.2,
        "partitions_fired": stats.get("faults", {}).get("node", 0),
        "verdicts_bit_identical": not wrong and not errors,
    })
    return row


def bench_rolling_restart(mix, run_dir: str) -> dict:
    """Restart every node in sequence (SIGKILL + respawn on the same
    replog dir and address), anti-entropy catch-up between steps, then
    the whole mix re-submitted: zero wrong verdicts, zero lost banked
    verdicts (every check lane a bank hit), shrink results bit-equal."""
    from qsm_tpu.serve.client import CheckClient

    # seal_rows=1: every banked batch seals its own segment, so the
    # anti-entropy sweep replicates the COMPLETE bank — the zero-lost
    # assertion below is exact, not modulo an unsealed tail
    router, nodes = _fleet(3, run_dir, "rolling", seal_rows=1)
    try:
        # phase A: bank the whole mix
        wall_a, lat_a, errors_a, wrong_a, served_a, shrink_a = _drive(
            router, mix, N_CLIENTS, 1)
        router.anti_entropy_sweep()
        restarts = []
        for node in nodes:
            node.sigkill()
            time.sleep(0.3)
            node.spawn()
            # membership must see it healthy again before the next
            # restart (sustained health re-admission)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 30.0:
                router.membership.probe(node.nid)
                if node.nid in router.membership.healthy_ids():
                    break
                time.sleep(0.2)
            # catch the restarted node up before the next one dies —
            # sweeps until quiescent (bounded: segment count is finite)
            for _ in range(32):
                if router.anti_entropy_sweep()["segments_shipped"] == 0:
                    break
            restarts.append(node.nid)
        # phase B: the whole mix again — all from the bank
        miss = []
        wrong_b = []
        shrink_equal = True
        with CheckClient(router.address, timeout_s=120.0) as client:
            for k, req in enumerate(mix):
                if req["kind"] == "check":
                    res = client.check(req["model"], req["rows"])
                    if not res.get("ok") \
                            or res["verdicts"] != req["expected"]:
                        wrong_b.append(k)
                    elif not all(res.get("cached", [])):
                        miss.append({"mix": k,
                                     "cached": res.get("cached")})
                else:
                    res = client.shrink(req["model"], req["rows"])
                    if not res.get("ok") \
                            or res.get("verdict") != "VIOLATION":
                        wrong_b.append(k)
                    elif k in shrink_a \
                            and res["history"] != shrink_a[k]:
                        shrink_equal = False
        stats = router.stats()
    finally:
        router.stop()
        for n in nodes:
            n.stop()
    row = _row("rolling_restart", 3, wall_a, lat_a, errors_a, wrong_a,
               served_a, stats)
    row.update({
        "restarted": restarts,
        "phase_b_wrong": len(wrong_b),
        "lanes_not_from_bank": len(miss),
        "zero_lost_banked_verdicts": not miss and not wrong_b,
        "shrink_results_bit_equal": shrink_equal,
        "ae_segments_shipped": stats.get("anti_entropy", {}).get(
            "segments_shipped", 0),
        "ae_rows_shipped": stats.get("anti_entropy", {}).get(
            "rows_shipped", 0),
    })
    return row


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def run(tag: str, out_path, resume: bool) -> int:
    from qsm_tpu.resilience.checkpoint import CellJournal

    path = out_path or os.path.join(REPO, f"BENCH_FLEET_{tag}.json")
    header = {
        "artifact": "BENCH_FLEET",
        "device_fallback": None,  # host-side by design: survival +
        # fleet fan-out, measured where it is honest
        "platform": "cpu",
        "mix": "cas check x6 + kv pcomp x2 + multireg pcomp x2 + "
               "cas shrink x2",
        "clients": N_CLIENTS, "rounds": ROUNDS,
        "host_cores": os.cpu_count(),
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)
    todo = ["fleet_n1", "fleet_n2", "fleet_n3", "kill_node",
            "wedge_node", "partition", "rolling_restart"]
    mix = None
    if any(journal.complete(k) is None for k in todo):
        mix = _build_mix()

    with tempfile.TemporaryDirectory() as run_dir:
        for n in (1, 2, 3):
            key = f"fleet_n{n}"
            if journal.complete(key) is None:
                journal.emit(key, bench_scaling(n, mix, run_dir))
        if journal.complete("kill_node") is None:
            journal.emit("kill_node", bench_kill_node(mix, run_dir))
        if journal.complete("wedge_node") is None:
            journal.emit("wedge_node", bench_wedge_node(mix, run_dir))
        if journal.complete("partition") is None:
            journal.emit("partition", bench_partition(mix, run_dir))
        if journal.complete("rolling_restart") is None:
            journal.emit("rolling_restart",
                         bench_rolling_restart(mix, run_dir))

    n1 = journal.complete("fleet_n1")
    n3 = journal.complete("fleet_n3")
    kill = journal.complete("kill_node")
    wedge = journal.complete("wedge_node")
    part = journal.complete("partition")
    roll = journal.complete("rolling_restart")
    rows = [journal.complete(k) for k in todo]
    wrong_total = sum(r.get("wrong_verdicts", 0) for r in rows) \
        + roll.get("phase_b_wrong", 0)
    host_cores = os.cpu_count() or 1
    ratio = n3["histories_per_sec"] / max(n1["histories_per_sec"], 1e-9)
    # the r08 honesty framing: three node processes cannot out-check
    # one on a host without the cores to run them — the gate needs
    # host_cores >= nodes + 1 (3 nodes + router/clients) to be
    # physically expressible.  The ratio is recorded either way;
    # correctness gates below are never waived.
    cores_sufficient = host_cores >= 4
    summary = {
        "metric": "fleet_survival_and_scaling",
        "host_cores": host_cores,
        "fleet_n1_hps": n1["histories_per_sec"],
        "fleet_n2_hps": journal.complete("fleet_n2")[
            "histories_per_sec"],
        "fleet_n3_hps": n3["histories_per_sec"],
        "ratio_n3_vs_n1": round(ratio, 2),
        "gate_2x_at_3_nodes": bool(ratio >= 2.0),
        "gate_waived_insufficient_cores": not cores_sufficient,
        "scaling_honesty": (
            None if cores_sufficient else
            f"host has {host_cores} core(s): 3 node processes + router "
            "+ clients share it, so near-linear node scaling is not "
            "expressible here (needs host_cores >= nodes + 1, the r08 "
            "workers+1 rule one level up); the chaos/correctness "
            "gates below are measured fully"),
        "wrong_verdicts_total": wrong_total,
        "kill_node_survived": bool(kill.get("verdicts_bit_identical")),
        "kill_flight_dump_names_doomed_traces": bool(
            kill.get("flight_dump_names_doomed_traces")),
        "kill_trace_shows_hop": bool(
            kill.get("trace_shows_hop_off_dead_node")),
        "kill_landed_mid_run": bool(kill.get("kill_landed_mid_run")),
        "wedge_node_survived": bool(wedge.get("verdicts_bit_identical")),
        "wedge_detected": bool(wedge.get("wedge_detected")),
        "partition_survived": bool(part.get("verdicts_bit_identical")),
        "partitions_fired": part.get("partitions_fired", 0),
        "rolling_restart_zero_lost": bool(
            roll.get("zero_lost_banked_verdicts")),
        "rolling_restart_shrink_bit_equal": bool(
            roll.get("shrink_results_bit_equal")),
        "resumed_cells": journal.resumed_cells,
        "artifact": os.path.basename(path),
    }
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    print(json.dumps(summary))
    ok = (summary["wrong_verdicts_total"] == 0
          and summary["kill_node_survived"]
          and summary["kill_landed_mid_run"]
          and summary["kill_flight_dump_names_doomed_traces"]
          and summary["kill_trace_shows_hop"]
          and summary["wedge_node_survived"]
          and summary["wedge_detected"]
          and summary["partition_survived"]
          and summary["rolling_restart_zero_lost"]
          and (summary["gate_2x_at_3_nodes"]
               or summary["gate_waived_insufficient_cores"]))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tag", default="r12")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="adopt completed cells from a prior journal "
                         "at the output path (resilience/checkpoint)")
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform()
    try:
        return run(args.tag, args.out, args.resume)
    except Exception as e:  # noqa: BLE001 — diagnostic line, not a traceback
        print(json.dumps({"metric": "fleet_survival_and_scaling",
                          "error": f"{type(e).__name__}: {e}"}))
        return 1


if __name__ == "__main__":
    sys.exit(main())
