"""Fleet-tier soak — survival under node kill/wedge/partition/restart.

ISSUE 12's tier is judged on *survival*, not just speed: verdicts must
stay correct and available while fleet nodes crash, wedge, partition
and restart.  This harness replays ONE recorded traffic mix — plain
``check`` corpora (cas), pcomp-split corpora (kv, multireg — per-key
sub-lanes on the nodes), and ``shrink`` requests on failing histories
— against 1/2/3-node fleets behind a :class:`~qsm_tpu.fleet.router.
FleetRouter`, with chaos cells driven through the faults plane and
plain POSIX signals:

* ``fleet_n{1,2,3}`` — the healthy scaling sweep at the same client
  load; EVERY response oracle-verified (``wrong_verdicts`` required 0);
* ``kill_node``      — SIGKILL the busiest node MID-soak: undecided
  lanes re-dispatch to survivors, the router's flight dump must name
  the doomed dispatches' trace ids, and the span log must show the
  ``route.hop`` from the dead node to the surviving one (the
  ``qsm-tpu trace <id>`` acceptance, checked from the same log);
* ``wedge_node``     — SIGSTOP a node (alive, silent — the wedge the
  worker pool knows one level down): bounded link timeouts shed it,
  lanes re-dispatch, zero wrong answers;
* ``partition``      — ``QSM_TPU_FAULTS=partition:node:p`` drops a
  random fraction of router→node exchanges both directions (seeded,
  replayable): the exclude-and-re-dispatch ladder absorbs every drop;
* ``rolling_restart``— restart every node IN SEQUENCE (SIGKILL +
  respawn on the same replog dir/address), anti-entropy catch-up
  between steps, then the whole recorded mix re-submitted: zero wrong
  verdicts AND zero lost banked verdicts (every check lane answers
  from the bank — ``cached`` all true) and shrink results bit-equal.

The r13 cells kill the ROUTER itself (ISSUE 13 — the tier's last
single points of failure):

* ``kill_router``    — SIGKILL the ACTIVE of an HA router pair
  MID-soak (fleet/lease.py): the standby takes the lease within the
  TTL window, clients on ``--addr a,b`` fail over, the recorded mix
  completes with zero wrong and zero lost verdicts, and the standby's
  span log shows the ``router.takeover`` span with the superseded
  term;
* ``wedge_router``   — SIGSTOP the active (alive, holds the lease
  file, renews nothing): the lease expires, the standby promotes, and
  after SIGCONT the STALE-term router answers SHED with
  ``router_superseded`` — the split-brain pin, live;
* ``gossip_router_dead`` — stop every router outright after banking
  the mix: node-to-node gossip (fleet/gossip.py) alone converges the
  replogs within a bounded number of beats — every segment in the
  fleet union held-or-covered by every node (row-level subsumption
  makes held-set equality unreachable by design when a key banked on
  two nodes).

Scaling honesty (the r08 precedent): the ≥2× three-node gate needs
``host_cores >= nodes + 1`` to be physically expressible — three node
processes cannot out-check one on a single core.  The summary stamps
``host_cores``, the measured ratio, and ``gate_waived_insufficient_
cores`` when the machine cannot express the gate; correctness gates
(zero wrong, zero lost, chaos-cell survival) are NEVER waived.

Output: a resumable ``CellJournal`` (``--resume`` re-runs zero
completed cells) committed as ``BENCH_FLEET_<tag>.json``
(``make bench-fleet``; probe_watcher archives it off-window).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_CLIENTS = 4
ROUNDS = 2            # mix replays per client in a scaling cell
CHAOS_ROUNDS = 4      # longer soak so mid-run faults land mid-run
SUBPROC_TIMEOUT_S = 600.0
KILL_AFTER_S = 0.3   # early: later soak rounds are bank hits and fly
LINK_TIMEOUT_S = 3.0  # router→node bound for the chaos cells
LEASE_TTL_S = 2.0     # router-HA lease TTL for the r13 chaos cells
GOSSIP_BEAT_S = 0.3   # node-to-node gossip beat in the r13 cells


# ---------------------------------------------------------------------------
# the recorded traffic mix
# ---------------------------------------------------------------------------

def _build_mix():
    """The recorded mix: (kind, model, payload) requests — cas check
    corpora, pcomp-splitting kv/multireg corpora, and shrink requests
    on failing cas histories — plus the oracle reference for each."""
    from qsm_tpu.models.registry import MODELS
    from qsm_tpu.ops.wing_gong_cpu import WingGongCPU
    from qsm_tpu.serve.protocol import VERDICT_NAMES, history_to_rows
    from qsm_tpu.utils.corpus import build_corpus

    oracle = WingGongCPU(memo=True)
    mix = []

    def add_check(model, n_corpora, corpus_n, n_pids, max_ops, seed0):
        entry = MODELS[model]
        spec = entry.make_spec()
        for i in range(n_corpora):
            hists = build_corpus(
                spec, (entry.impls["atomic"], entry.impls["racy"]),
                n=corpus_n, n_pids=n_pids, max_ops=max_ops,
                seed_base=seed0 + i * 10_000,
                seed_prefix=f"bench_fleet_{model}_{i}")
            expected = [VERDICT_NAMES[int(v)]
                        for v in oracle.check_histories(spec, hists)]
            mix.append({"kind": "check", "model": model,
                        "rows": [history_to_rows(h) for h in hists],
                        "expected": expected})

    add_check("cas", 6, 8, 4, 10, 0)
    add_check("kv", 2, 4, 8, 24, 500_000)       # pcomp-split lanes
    add_check("multireg", 2, 4, 8, 16, 900_000)  # second split family
    # failing cas histories for the shrink lanes
    entry = MODELS["cas"]
    spec = entry.make_spec()
    pool = build_corpus(
        spec, (entry.impls["atomic"], entry.impls["racy"]),
        n=24, n_pids=6, max_ops=16, seed_base=0,
        seed_prefix="bench_fleet_shrink")
    failing = [h for h in pool
               if int(oracle.check_histories(spec, [h])[0]) == 0]
    for h in failing[:2]:
        mix.append({"kind": "shrink", "model": "cas",
                    "rows": history_to_rows(h), "expected": None})
    return mix


# ---------------------------------------------------------------------------
# node processes (UNIX sockets: a restarted node keeps its address)
# ---------------------------------------------------------------------------

class Node:
    def __init__(self, nid: str, run_dir: str, seal_rows: int = 64):
        self.nid = nid
        self.unix_path = os.path.join(run_dir, f"{nid}.sock")
        self.replog_dir = os.path.join(run_dir, f"replog_{nid}")
        self.seal_rows = seal_rows
        self.proc = None

    def spawn(self) -> "Node":
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # chaos rules target the ROUTER's node site; a spawned node
        # must not inherit them (kill:serve etc. would be a different
        # cell's drill)
        env.pop("QSM_TPU_FAULTS", None)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "qsm_tpu", "serve",
             "--unix", self.unix_path, "--node-id", self.nid,
             "--replog-dir", self.replog_dir,
             "--replog-seal-rows", str(self.seal_rows),
             # warm every mix model (register = the projected spec kv/
             # multireg sub-lanes ride): a cold engine build under
             # full 1-core load can outlast a chaos-tuned link bound
             # and read as a wedge
             "--warm", "cas,kv,multireg,register"],
            stdout=subprocess.PIPE, text=True, cwd=REPO, env=env)
        line = self.proc.stdout.readline()
        doc = json.loads(line)
        assert doc.get("serving") == self.unix_path, doc
        return self

    def sigkill(self) -> None:
        self.proc.kill()
        self.proc.wait(timeout=10.0)

    def sigstop(self) -> None:
        os.kill(self.proc.pid, signal.SIGSTOP)

    def sigcont(self) -> None:
        try:
            os.kill(self.proc.pid, signal.SIGCONT)
        except OSError:
            pass

    def stop(self) -> None:
        if self.proc is None or self.proc.poll() is not None:
            return
        try:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5.0)
        except OSError:
            pass


def _fleet(n_nodes: int, run_dir: str, cell: str, seal_rows: int = 64,
           trace: bool = False, link_timeout_s: float = 10.0):
    """Spawn N nodes + an in-process router for one cell.  Fresh
    per-cell replog dirs: an earlier cell's banked verdicts must not
    contaminate a later cell's throughput.  ``link_timeout_s`` stays
    generous except in the chaos cells (LINK_TIMEOUT_S): on a shared
    single core a loaded-but-healthy node can miss a wedge-tuned
    bound, and a timeout is indistinguishable from a wedge at the
    link layer."""
    from qsm_tpu.fleet.router import FleetRouter
    from qsm_tpu.resilience.policy import preset

    cell_dir = os.path.join(run_dir, cell)
    os.makedirs(cell_dir, exist_ok=True)
    nodes = [Node(f"n{i}", cell_dir, seal_rows=seal_rows).spawn()
             for i in range(n_nodes)]
    kw = {}
    if trace:
        kw["trace_log"] = os.path.join(cell_dir, "router_trace.jsonl")
        kw["flight_dir"] = os.path.join(cell_dir, "flight")
    router = FleetRouter(
        [(n.nid, n.unix_path) for n in nodes],
        policy=preset("fleet-route").with_(timeout_s=link_timeout_s),
        probe_policy=preset("fleet-probe").with_(timeout_s=1.0),
        heartbeat_s=0.3, anti_entropy_s=0.0, **kw).start()
    return router, nodes


def _send_op(addr: str, doc: dict, timeout_s: float = 5.0) -> dict:
    """One raw op round-trip (gossip.peers wiring, digest polling)."""
    from qsm_tpu.serve.protocol import LineChannel, connect, send_doc

    sock = connect(addr, timeout_s=timeout_s)
    try:
        send_doc(sock, doc)
        line = LineChannel(sock).read_line(timeout_s=timeout_s)
        return json.loads(line) if line else {}
    finally:
        sock.close()


def _wire_gossip(nodes, beat_s: float = GOSSIP_BEAT_S) -> None:
    """Node-to-node anti-entropy: every node gets every OTHER node as
    a gossip peer (the gossip.peers op `qsm-tpu fleet` drives)."""
    for n in nodes:
        peers = [[o.nid, o.unix_path] for o in nodes if o is not n]
        resp = _send_op(n.unix_path, {"op": "gossip.peers",
                                      "peers": peers,
                                      "interval_s": beat_s})
        assert resp.get("ok"), resp


class RouterProc:
    """One `qsm-tpu fleet` router subprocess fronting externally-spawned
    nodes — the r13 chaos cells SIGKILL/SIGSTOP these like nodes."""

    def __init__(self, rid: str, run_dir: str, node_addrs,
                 lease_path: str, trace: bool = False):
        self.rid = rid
        self.unix_path = os.path.join(run_dir, f"{rid}.sock")
        self.node_addrs = list(node_addrs)
        self.lease_path = lease_path
        self.trace_log = (os.path.join(run_dir, f"{rid}_trace.jsonl")
                          if trace else None)
        self.flight_dir = (os.path.join(run_dir, f"{rid}_flight")
                           if trace else None)
        self.proc = None
        self.role = None
        self.term = None

    def spawn(self) -> "RouterProc":
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("QSM_TPU_FAULTS", None)
        cmd = [sys.executable, "-m", "qsm_tpu", "fleet",
               "--addrs", ",".join(self.node_addrs),
               "--unix", self.unix_path,
               "--router-id", self.rid,
               "--lease-path", self.lease_path,
               "--lease-ttl-s", str(LEASE_TTL_S),
               "--heartbeat-s", "0.3",
               "--anti-entropy-s", "0.5",
               "--gossip-s", "0"]
        if self.trace_log:
            cmd += ["--trace-log", self.trace_log,
                    "--flight-dir", self.flight_dir]
        self.proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                     text=True, cwd=REPO, env=env)
        banner = json.loads(self.proc.stdout.readline())
        assert banner.get("fleet") == self.unix_path, banner
        self.role = banner.get("role")
        self.term = banner.get("term")
        return self

    def sigkill(self) -> None:
        self.proc.kill()
        self.proc.wait(timeout=10.0)

    def sigstop(self) -> None:
        os.kill(self.proc.pid, signal.SIGSTOP)

    def sigcont(self) -> None:
        try:
            os.kill(self.proc.pid, signal.SIGCONT)
        except OSError:
            pass

    def stop(self) -> None:
        if self.proc is None or self.proc.poll() is not None:
            return
        try:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5.0)
        except OSError:
            pass


def _ha_pair(run_dir: str, cell: str, n_nodes: int = 3,
             trace_standby: bool = True):
    """N nodes + an active/standby `qsm-tpu fleet` router pair sharing
    one lease.  The FIRST router wins the lease (spawned and bannered
    before the second starts)."""
    cell_dir = os.path.join(run_dir, cell)
    os.makedirs(cell_dir, exist_ok=True)
    nodes = [Node(f"n{i}", cell_dir).spawn() for i in range(n_nodes)]
    addrs = [n.unix_path for n in nodes]
    lease = os.path.join(cell_dir, "lease.json")
    ra = RouterProc("rA", cell_dir, addrs, lease).spawn()
    rb = RouterProc("rB", cell_dir, addrs, lease,
                    trace=trace_standby).spawn()
    assert ra.role == "active" and ra.term == 1, (ra.role, ra.term)
    assert rb.role == "standby", rb.role
    return nodes, ra, rb


def _busiest_node(router, mix) -> str:
    """The node owning the most of the mix's whole-history keys — the
    one in-flight lanes are most likely riding when the chaos lands."""
    from qsm_tpu.serve.cache import fingerprint_key
    from qsm_tpu.serve.protocol import rows_to_history

    owned: dict = {}
    allowed = set(router.membership.all_ids())
    for req in mix:
        spec = router._spec_for(req["model"], {})
        hists = ([rows_to_history(req["rows"])]
                 if req["kind"] == "shrink"
                 else [rows_to_history(r) for r in req["rows"]])
        for h in hists:
            nid = router.membership.ring.node_for(
                fingerprint_key(spec, h), allowed)
            owned[nid] = owned.get(nid, 0) + 1
    return max(owned, key=owned.get)


# ---------------------------------------------------------------------------
# the client drive
# ---------------------------------------------------------------------------

def _drive(router, mix, n_clients: int, rounds: int,
           chaos=None, chaos_at_s: float = None):
    """Closed-loop clients replaying the recorded mix; every check
    response verified against the oracle reference on receipt.
    ``chaos`` is a zero-arg callable fired ``chaos_at_s`` into the
    drive (SIGKILL/SIGSTOP/...).  ``router`` is a FleetRouter or a
    plain address string — the r13 HA cells pass ``"a,b"`` so clients
    exercise real multi-address failover."""
    from qsm_tpu.serve.client import CheckClient

    address = router if isinstance(router, str) else router.address
    lock = threading.Lock()
    latencies, errors, wrong = [], [], []
    served = [0]
    shrink_results = {}

    def drive(ci: int):
        try:
            with CheckClient(address, timeout_s=120.0) as client:
                for _r in range(rounds):
                    # each client starts at its own offset so the mix
                    # interleaves across connections instead of
                    # marching in lockstep
                    for k in [(j + ci) % len(mix)
                              for j in range(len(mix))]:
                        req = mix[k]
                        t0 = time.perf_counter()
                        if req["kind"] == "check":
                            res = client.check(req["model"], req["rows"])
                        else:
                            res = client.shrink(req["model"], req["rows"])
                        dt = time.perf_counter() - t0
                        with lock:
                            latencies.append(dt)
                            if not res.get("ok"):
                                errors.append(res)
                            elif (req["kind"] == "check"
                                  and res["verdicts"] != req["expected"]):
                                wrong.append({"mix": k,
                                              "got": res["verdicts"]})
                            elif (req["kind"] == "shrink"
                                  and res.get("verdict") != "VIOLATION"):
                                wrong.append({"mix": k, "shrink": res})
                            else:
                                served[0] += (len(req["rows"])
                                              if req["kind"] == "check"
                                              else 1)
                                if req["kind"] == "shrink":
                                    shrink_results[k] = res["history"]
        except Exception as e:  # noqa: BLE001 — a dead client is a row fact
            with lock:
                errors.append({"error": f"{type(e).__name__}: {e}"})

    threads = [threading.Thread(target=drive, args=(ci,))
               for ci in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    if chaos is not None:
        time.sleep(chaos_at_s or KILL_AFTER_S)
        chaos()
    for t in threads:
        t.join(SUBPROC_TIMEOUT_S)
    wall = time.perf_counter() - t0
    return wall, latencies, errors, wrong, served[0], shrink_results


def _row(cell, n_nodes, wall, latencies, errors, wrong, served,
         router_stats) -> dict:
    lat = np.asarray(sorted(latencies)) if latencies else np.asarray([0.0])
    mem = router_stats.get("membership", {})
    return {
        "nodes": n_nodes, "clients": N_CLIENTS,
        "histories": served, "seconds": round(wall, 3),
        "histories_per_sec": round(served / max(wall, 1e-9), 1),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1000, 2),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1000, 2),
        "errors": len(errors),
        "wrong_verdicts": len(wrong),
        "node_faults": router_stats.get("node_faults", 0),
        "node_sheds": router_stats.get("node_sheds", 0),
        "redispatches": router_stats.get("redispatches", 0),
        "ladder_lanes": router_stats.get("ladder_lanes", 0),
        "quarantines": mem.get("quarantines", 0),
        "readmissions": mem.get("readmissions", 0),
    }


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------

def bench_scaling(n_nodes: int, mix, run_dir: str) -> dict:
    router, nodes = _fleet(n_nodes, run_dir, f"n{n_nodes}")
    try:
        wall, lat, errors, wrong, served, _ = _drive(
            router, mix, N_CLIENTS, ROUNDS)
        stats = router.stats()
    finally:
        router.stop()
        for n in nodes:
            n.stop()
    return _row(f"fleet_n{n_nodes}", n_nodes, wall, lat, errors, wrong,
                served, stats)


def bench_kill_node(mix, run_dir: str) -> dict:
    """SIGKILL the busiest node mid-soak; afterwards audit the three
    acceptance artifacts: correct verdicts, a flight dump naming the
    doomed trace ids, and the route.hop span from the dead node."""
    from qsm_tpu.obs import load_dump, load_events, recent_events

    router, nodes = _fleet(3, run_dir, "kill", trace=True,
                           link_timeout_s=LINK_TIMEOUT_S)
    victim = _busiest_node(router, mix)
    node_by_id = {n.nid: n for n in nodes}
    try:
        wall, lat, errors, wrong, served, _ = _drive(
            router, mix, N_CLIENTS, CHAOS_ROUNDS,
            chaos=lambda: node_by_id[victim].sigkill(),
            chaos_at_s=KILL_AFTER_S)
        stats = router.stats()
        flight_dir = os.path.join(run_dir, "kill", "flight")
        trace_log = os.path.join(run_dir, "kill", "router_trace.jsonl")
        router.obs.tracer.close()
        doomed = []
        dump_path = None
        for name in sorted(os.listdir(flight_dir)
                           if os.path.isdir(flight_dir) else []):
            if "node_death" not in name and "partition" not in name:
                continue
            dump = load_dump(os.path.join(flight_dir, name))
            for ev in recent_events(dump, "node"):
                at = ev.get("attrs") or {}
                if ev.get("name") == "node.shed" \
                        and at.get("node") == victim:
                    doomed.extend(at.get("traces") or [])
                    dump_path = name
        hop_seen = False
        for trace_id in doomed[:8]:
            for ev in load_events(trace_log, trace_id=trace_id):
                at = ev.get("attrs") or {}
                if ev.get("name") == "route.hop" \
                        and at.get("hop_from") == victim:
                    hop_seen = True
    finally:
        router.stop()
        for n in nodes:
            n.stop()
    row = _row("kill_node", 3, wall, lat, errors, wrong, served, stats)
    row.update({
        "killed_node": victim,
        "kill_after_s": KILL_AFTER_S,
        "kill_landed_mid_run": stats.get("node_faults", 0) >= 1,
        "flight_dump": dump_path,
        "flight_dump_names_doomed_traces": bool(doomed),
        "doomed_traces": doomed[:4],
        "trace_shows_hop_off_dead_node": hop_seen,
        "verdicts_bit_identical": not wrong and not errors,
    })
    return row


def bench_wedge_node(mix, run_dir: str) -> dict:
    """SIGSTOP (wedge: alive, holds its sockets, answers nothing) the
    busiest node mid-soak — the failure bounded link timeouts exist
    for."""
    router, nodes = _fleet(3, run_dir, "wedge",
                           link_timeout_s=LINK_TIMEOUT_S)
    victim = _busiest_node(router, mix)
    node_by_id = {n.nid: n for n in nodes}
    try:
        wall, lat, errors, wrong, served, _ = _drive(
            router, mix, N_CLIENTS, CHAOS_ROUNDS,
            chaos=lambda: node_by_id[victim].sigstop(),
            chaos_at_s=KILL_AFTER_S)
        stats = router.stats()
    finally:
        node_by_id[victim].sigcont()
        router.stop()
        for n in nodes:
            n.stop()
    row = _row("wedge_node", 3, wall, lat, errors, wrong, served, stats)
    row.update({
        "wedged_node": victim,
        "wedge_detected": stats.get("node_faults", 0) >= 1,
        "verdicts_bit_identical": not wrong and not errors,
    })
    return row


def bench_partition(mix, run_dir: str) -> dict:
    """Seeded random partition: a fraction of router→node exchanges
    drop frames both directions (``partition:node:p`` — the faults
    plane's grammar, replayable by seed)."""
    os.environ["QSM_TPU_FAULTS"] = "partition:node:0.2"
    os.environ["QSM_TPU_FAULTS_SEED"] = "12"
    try:
        router, nodes = _fleet(3, run_dir, "partition",
                               link_timeout_s=LINK_TIMEOUT_S)
        try:
            wall, lat, errors, wrong, served, _ = _drive(
                router, mix, N_CLIENTS, CHAOS_ROUNDS)
            stats = router.stats()
        finally:
            router.stop()
            for n in nodes:
                n.stop()
    finally:
        os.environ.pop("QSM_TPU_FAULTS", None)
        os.environ.pop("QSM_TPU_FAULTS_SEED", None)
    row = _row("partition", 3, wall, lat, errors, wrong, served, stats)
    row.update({
        "partition_p": 0.2,
        "partitions_fired": stats.get("faults", {}).get("node", 0),
        "verdicts_bit_identical": not wrong and not errors,
    })
    return row


def bench_rolling_restart(mix, run_dir: str) -> dict:
    """Restart every node in sequence (SIGKILL + respawn on the same
    replog dir and address), anti-entropy catch-up between steps, then
    the whole mix re-submitted: zero wrong verdicts, zero lost banked
    verdicts (every check lane a bank hit), shrink results bit-equal."""
    from qsm_tpu.serve.client import CheckClient

    # seal_rows=1: every banked batch seals its own segment, so the
    # anti-entropy sweep replicates the COMPLETE bank — the zero-lost
    # assertion below is exact, not modulo an unsealed tail
    router, nodes = _fleet(3, run_dir, "rolling", seal_rows=1)
    try:
        # phase A: bank the whole mix
        wall_a, lat_a, errors_a, wrong_a, served_a, shrink_a = _drive(
            router, mix, N_CLIENTS, 1)
        router.anti_entropy_sweep()
        restarts = []
        for node in nodes:
            node.sigkill()
            time.sleep(0.3)
            node.spawn()
            # membership must see it healthy again before the next
            # restart (sustained health re-admission)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 30.0:
                router.membership.probe(node.nid)
                if node.nid in router.membership.healthy_ids():
                    break
                time.sleep(0.2)
            # catch the restarted node up before the next one dies —
            # sweeps until quiescent (bounded: segment count is finite)
            for _ in range(32):
                if router.anti_entropy_sweep()["segments_shipped"] == 0:
                    break
            restarts.append(node.nid)
        # phase B: the whole mix again — all from the bank
        miss = []
        wrong_b = []
        shrink_equal = True
        with CheckClient(router.address, timeout_s=120.0) as client:
            for k, req in enumerate(mix):
                if req["kind"] == "check":
                    res = client.check(req["model"], req["rows"])
                    if not res.get("ok") \
                            or res["verdicts"] != req["expected"]:
                        wrong_b.append(k)
                    elif not all(res.get("cached", [])):
                        miss.append({"mix": k,
                                     "cached": res.get("cached")})
                else:
                    res = client.shrink(req["model"], req["rows"])
                    if not res.get("ok") \
                            or res.get("verdict") != "VIOLATION":
                        wrong_b.append(k)
                    elif k in shrink_a \
                            and res["history"] != shrink_a[k]:
                        shrink_equal = False
        stats = router.stats()
    finally:
        router.stop()
        for n in nodes:
            n.stop()
    row = _row("rolling_restart", 3, wall_a, lat_a, errors_a, wrong_a,
               served_a, stats)
    row.update({
        "restarted": restarts,
        "phase_b_wrong": len(wrong_b),
        "lanes_not_from_bank": len(miss),
        "zero_lost_banked_verdicts": not miss and not wrong_b,
        "shrink_results_bit_equal": shrink_equal,
        "ae_segments_shipped": stats.get("anti_entropy", {}).get(
            "segments_shipped", 0),
        "ae_rows_shipped": stats.get("anti_entropy", {}).get(
            "rows_shipped", 0),
    })
    return row


def bench_kill_router(mix, run_dir: str) -> dict:
    """SIGKILL the ACTIVE router of an HA pair mid-soak: the standby
    must take the lease within the TTL window, multi-address clients
    fail over, the mix completes with zero wrong/lost verdicts, and
    the standby's span log carries the ``router.takeover`` span with
    the superseded term."""
    from qsm_tpu.obs import load_events
    from qsm_tpu.serve.client import CheckClient

    nodes, ra, rb = _ha_pair(run_dir, "kill_router")
    takeover_s = [None]

    def chaos():
        t0 = time.monotonic()
        ra.sigkill()
        # the measured takeover bound: lease file holder flips to rB
        deadline = t0 + 4 * LEASE_TTL_S
        while time.monotonic() < deadline:
            try:
                with open(ra.lease_path) as f:
                    rec = json.load(f)
                if rec.get("holder") == "rB":
                    takeover_s[0] = round(time.monotonic() - t0, 2)
                    return
            except (OSError, ValueError):
                pass
            time.sleep(0.05)

    try:
        wall, lat, errors, wrong, served, _ = _drive(
            f"{ra.unix_path},{rb.unix_path}", mix, N_CLIENTS,
            CHAOS_ROUNDS, chaos=chaos, chaos_at_s=KILL_AFTER_S)
        with CheckClient(rb.unix_path, timeout_s=30.0) as c:
            stats = c.stats()["stats"]
        lease = stats.get("lease") or {}
        events = [e for e in load_events(rb.trace_log)
                  if e.get("name") == "router.takeover"]
        at = (events[0].get("attrs") or {}) if events else {}
    finally:
        ra.stop()
        rb.stop()
        for n in nodes:
            n.stop()
    row = _row("kill_router", 3, wall, lat, errors, wrong, served,
               stats)
    # the TTL gate: the takeover window is expiry (TTL) + grace
    # (0.5*TTL) + one beat (TTL/3) + scheduling slack on a loaded
    # 1-core host — the holder flip must land inside 2*TTL total,
    # i.e. within ONE further TTL of the lease expiring
    row.update({
        "killed_router": "rA",
        "lease_ttl_s": LEASE_TTL_S,
        "takeover_observed_s": takeover_s[0],
        "takeover_within_ttl": bool(
            takeover_s[0] is not None
            and takeover_s[0] <= 2 * LEASE_TTL_S),
        "standby_promoted": lease.get("role") == "active"
        and lease.get("term", 0) >= 2,
        "standby_takeovers": lease.get("takeovers", 0),
        "takeover_span_in_trace": bool(events),
        "takeover_span_superseded_term": at.get("superseded_term"),
        "verdicts_bit_identical": not wrong and not errors,
    })
    return row


def bench_wedge_router(mix, run_dir: str) -> dict:
    """SIGSTOP the active router (alive, renews nothing): the lease
    expires, the standby promotes, the mix completes — and after
    SIGCONT the stale-term router answers SHED ``router_superseded``,
    never a verdict: the split-brain pin, live."""
    from qsm_tpu.serve.client import CheckClient

    nodes, ra, rb = _ha_pair(run_dir, "wedge_router",
                             trace_standby=False)
    try:
        wall, lat, errors, wrong, served, _ = _drive(
            f"{ra.unix_path},{rb.unix_path}", mix, N_CLIENTS,
            CHAOS_ROUNDS, chaos=ra.sigstop, chaos_at_s=KILL_AFTER_S)
        with CheckClient(rb.unix_path, timeout_s=30.0) as c:
            stats = c.stats()["stats"]
        lease = stats.get("lease") or {}
        # wake the frozen active: its term is long gone — the stale
        # router must refuse with router_superseded, never answer
        ra.sigcont()
        req = mix[0]
        with CheckClient(ra.unix_path, timeout_s=30.0) as c:
            stale = c.check(req["model"], req["rows"])
    finally:
        ra.stop()
        rb.stop()
        for n in nodes:
            n.stop()
    row = _row("wedge_router", 3, wall, lat, errors, wrong, served,
               stats)
    row.update({
        "wedged_router": "rA",
        "standby_promoted": lease.get("role") == "active"
        and lease.get("term", 0) >= 2,
        "stale_router_shed_superseded": bool(
            stale.get("shed")
            and stale.get("reason") == "router_superseded"
            and not stale.get("ok")),
        "stale_router_block": stale.get("router"),
        "verdicts_bit_identical": not wrong and not errors,
    })
    return row


def bench_gossip_router_dead(mix, run_dir: str) -> dict:
    """Bank the mix through a router, then STOP every router: node-to-
    node gossip alone must converge the replogs within a bounded
    number of beats (coverage fixed point — see the inline note)."""
    cell_dir = os.path.join(run_dir, "gossip_dead")
    os.makedirs(cell_dir, exist_ok=True)
    nodes = [Node(f"n{i}", cell_dir, seal_rows=1).spawn()
             for i in range(3)]
    from qsm_tpu.fleet.router import FleetRouter
    from qsm_tpu.resilience.policy import preset

    router = FleetRouter(
        [(n.nid, n.unix_path) for n in nodes],
        policy=preset("fleet-route").with_(timeout_s=10.0),
        probe_policy=preset("fleet-probe").with_(timeout_s=1.0),
        heartbeat_s=0.3, anti_entropy_s=0.0).start()
    try:
        wall, lat, errors, wrong, served, _ = _drive(
            router, mix, N_CLIENTS, 1)
        router.stop()  # every router DEAD from here on
        router = None
        _wire_gossip(nodes)  # beats start now, router already gone
        # the gossip fixed point is COVERAGE, not held-set equality:
        # with row-level subsumption, a node whose live set already
        # holds a segment's rows records it covered and never holds
        # it — so "every segment in the fleet union is held-or-
        # covered by every node" is convergence (duplicate banking of
        # one key on two nodes — a backpressure hop mid-drive — makes
        # strict digest equality unreachable BY DESIGN)
        t0 = time.monotonic()
        deadline = t0 + 60.0
        converged = False
        union = set()
        while time.monotonic() < deadline and not converged:
            time.sleep(GOSSIP_BEAT_S)
            docs = [_send_op(n.unix_path, {"op": "replog.digests"})
                    for n in nodes]
            if not all(d.get("ok") for d in docs):
                continue
            union = set().union(*[set(d.get("digests") or {})
                                  for d in docs])
            converged = bool(union) and all(
                union <= (set(d.get("digests") or {})
                          | set(d.get("absorbed") or {}))
                for d in docs)
        elapsed = time.monotonic() - t0
        beats = max(1, int(elapsed / GOSSIP_BEAT_S + 0.999))
        gsnaps = [
            _send_op(n.unix_path,
                     {"op": "stats"})["stats"].get("gossip") or {}
            for n in nodes]
    finally:
        if router is not None:
            router.stop()
        for n in nodes:
            n.stop()
    return {
        "nodes": 3, "clients": N_CLIENTS,
        "histories": served, "errors": len(errors),
        "wrong_verdicts": len(wrong),
        "gossip_beat_s": GOSSIP_BEAT_S,
        "router_alive_during_convergence": False,
        "converged": converged,
        "converged_s": round(elapsed, 2),
        "converged_beats": beats,
        "converged_segments": len(union),
        "segments_pulled": sum(g.get("segments_pulled", 0)
                               for g in gsnaps),
        "segments_pushed": sum(g.get("segments_pushed", 0)
                               for g in gsnaps),
        "segments_subsumed": sum(g.get("segments_subsumed", 0)
                                 for g in gsnaps),
        "peer_faults": sum(g.get("peer_faults", 0) for g in gsnaps),
    }


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def run(tag: str, out_path, resume: bool) -> int:
    from qsm_tpu.resilience.checkpoint import CellJournal

    path = out_path or os.path.join(REPO, f"BENCH_FLEET_{tag}.json")
    header = {
        "artifact": "BENCH_FLEET",
        "device_fallback": None,  # host-side by design: survival +
        # fleet fan-out, measured where it is honest
        "platform": "cpu",
        "mix": "cas check x6 + kv pcomp x2 + multireg pcomp x2 + "
               "cas shrink x2",
        "clients": N_CLIENTS, "rounds": ROUNDS,
        "lease_ttl_s": LEASE_TTL_S, "gossip_beat_s": GOSSIP_BEAT_S,
        "host_cores": os.cpu_count(),
        "captured_iso": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    journal = CellJournal(path, header, resume=resume)
    todo = ["fleet_n1", "fleet_n2", "fleet_n3", "kill_node",
            "wedge_node", "partition", "rolling_restart",
            "kill_router", "wedge_router", "gossip_router_dead"]
    mix = None
    if any(journal.complete(k) is None for k in todo):
        mix = _build_mix()

    with tempfile.TemporaryDirectory() as run_dir:
        for n in (1, 2, 3):
            key = f"fleet_n{n}"
            if journal.complete(key) is None:
                journal.emit(key, bench_scaling(n, mix, run_dir))
        if journal.complete("kill_node") is None:
            journal.emit("kill_node", bench_kill_node(mix, run_dir))
        if journal.complete("wedge_node") is None:
            journal.emit("wedge_node", bench_wedge_node(mix, run_dir))
        if journal.complete("partition") is None:
            journal.emit("partition", bench_partition(mix, run_dir))
        if journal.complete("rolling_restart") is None:
            journal.emit("rolling_restart",
                         bench_rolling_restart(mix, run_dir))
        if journal.complete("kill_router") is None:
            journal.emit("kill_router",
                         bench_kill_router(mix, run_dir))
        if journal.complete("wedge_router") is None:
            journal.emit("wedge_router",
                         bench_wedge_router(mix, run_dir))
        if journal.complete("gossip_router_dead") is None:
            journal.emit("gossip_router_dead",
                         bench_gossip_router_dead(mix, run_dir))

    n1 = journal.complete("fleet_n1")
    n3 = journal.complete("fleet_n3")
    kill = journal.complete("kill_node")
    wedge = journal.complete("wedge_node")
    part = journal.complete("partition")
    roll = journal.complete("rolling_restart")
    rkill = journal.complete("kill_router")
    rwedge = journal.complete("wedge_router")
    gdead = journal.complete("gossip_router_dead")
    rows = [journal.complete(k) for k in todo]
    wrong_total = sum(r.get("wrong_verdicts", 0) for r in rows) \
        + roll.get("phase_b_wrong", 0)
    host_cores = os.cpu_count() or 1
    ratio = n3["histories_per_sec"] / max(n1["histories_per_sec"], 1e-9)
    # the r08 honesty framing: three node processes cannot out-check
    # one on a host without the cores to run them — the gate needs
    # host_cores >= nodes + 1 (3 nodes + router/clients) to be
    # physically expressible.  The ratio is recorded either way;
    # correctness gates below are never waived.
    cores_sufficient = host_cores >= 4
    summary = {
        "metric": "fleet_survival_and_scaling",
        "host_cores": host_cores,
        "fleet_n1_hps": n1["histories_per_sec"],
        "fleet_n2_hps": journal.complete("fleet_n2")[
            "histories_per_sec"],
        "fleet_n3_hps": n3["histories_per_sec"],
        "ratio_n3_vs_n1": round(ratio, 2),
        "gate_2x_at_3_nodes": bool(ratio >= 2.0),
        "gate_waived_insufficient_cores": not cores_sufficient,
        "scaling_honesty": (
            None if cores_sufficient else
            f"host has {host_cores} core(s): 3 node processes + router "
            "+ clients share it, so near-linear node scaling is not "
            "expressible here (needs host_cores >= nodes + 1, the r08 "
            "workers+1 rule one level up); the chaos/correctness "
            "gates below are measured fully"),
        "wrong_verdicts_total": wrong_total,
        "kill_node_survived": bool(kill.get("verdicts_bit_identical")),
        "kill_flight_dump_names_doomed_traces": bool(
            kill.get("flight_dump_names_doomed_traces")),
        "kill_trace_shows_hop": bool(
            kill.get("trace_shows_hop_off_dead_node")),
        "kill_landed_mid_run": bool(kill.get("kill_landed_mid_run")),
        "wedge_node_survived": bool(wedge.get("verdicts_bit_identical")),
        "wedge_detected": bool(wedge.get("wedge_detected")),
        "partition_survived": bool(part.get("verdicts_bit_identical")),
        "partitions_fired": part.get("partitions_fired", 0),
        "rolling_restart_zero_lost": bool(
            roll.get("zero_lost_banked_verdicts")),
        "rolling_restart_shrink_bit_equal": bool(
            roll.get("shrink_results_bit_equal")),
        # the r13 de-SPOF gates (ISSUE 13): router HA + gossip
        "kill_router_survived": bool(
            rkill.get("verdicts_bit_identical")),
        "kill_router_takeover_within_ttl": bool(
            rkill.get("takeover_within_ttl")),
        "kill_router_takeover_span": bool(
            rkill.get("takeover_span_in_trace")),
        "wedge_router_survived": bool(
            rwedge.get("verdicts_bit_identical")),
        "split_brain_refused": bool(
            rwedge.get("stale_router_shed_superseded")),
        "gossip_converged_router_dead": bool(gdead.get("converged")),
        "gossip_converged_beats": gdead.get("converged_beats"),
        "resumed_cells": journal.resumed_cells,
        "artifact": os.path.basename(path),
    }
    if journal.complete("summary") is None:
        journal.emit("summary", summary)
    print(json.dumps(summary))
    ok = (summary["wrong_verdicts_total"] == 0
          and summary["kill_node_survived"]
          and summary["kill_landed_mid_run"]
          and summary["kill_flight_dump_names_doomed_traces"]
          and summary["kill_trace_shows_hop"]
          and summary["wedge_node_survived"]
          and summary["wedge_detected"]
          and summary["partition_survived"]
          and summary["rolling_restart_zero_lost"]
          and summary["kill_router_survived"]
          and summary["kill_router_takeover_within_ttl"]
          and summary["kill_router_takeover_span"]
          and summary["wedge_router_survived"]
          and summary["split_brain_refused"]
          and summary["gossip_converged_router_dead"]
          and (summary["gate_2x_at_3_nodes"]
               or summary["gate_waived_insufficient_cores"]))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tag", default="r13")
    ap.add_argument("--out", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="adopt completed cells from a prior journal "
                         "at the output path (resilience/checkpoint)")
    args = ap.parse_args(argv)

    from qsm_tpu.utils.device import force_cpu_platform

    force_cpu_platform()
    try:
        return run(args.tag, args.out, args.resume)
    except Exception as e:  # noqa: BLE001 — diagnostic line, not a traceback
        print(json.dumps({"metric": "fleet_survival_and_scaling",
                          "error": f"{type(e).__name__}: {e}"}))
        return 1


if __name__ == "__main__":
    sys.exit(main())
